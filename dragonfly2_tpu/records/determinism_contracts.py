"""Replay-determinism contracts: declared once, checked twice (DESIGN.md §27).

The replay-equals-live property (§23 burn-rate replay, §26 autopilot
drift-0, the accounting rebuild drill) holds only while every function
on a replay path stays free of *ambient* nondeterminism — wall-clock
reads, unseeded randomness, ``hash()``/``id()``, set-iteration feeding
ordered output — and every journal/replay artifact serializes
canonically.  This registry is the single declaration of that boundary:

- ``tools/dflint/detrules.py`` reads it with ``ast.literal_eval`` (never
  imported — dflint stays stdlib-only) and enforces **DF018** (taint
  every function reachable from a replay root; ambient nondeterminism
  fails unless the value arrives through a declared injection seam) and
  **DF019** (canonical serialization on every declared artifact writer:
  ``json.dumps`` pins ``sort_keys=True``, frame payload keys come from
  the bounded declared sets below);
- ``dragonfly2_tpu/utils/dfdet.py`` imports it at runtime (the witness
  side) to arm call-site recorders while a declared replay root is on
  the stack; ``tests/test_zz_detwitness.py`` cross-validates the two
  and re-runs every root twice over identical journal bytes under
  different PYTHONHASHSEED values — decision output must be
  byte-identical.

Keep this a PURE LITERAL: one dict, no imports used in the value, no
computed entries.  dflint emits a DF018 finding if ``ast.literal_eval``
stops working on it.
"""

from __future__ import annotations

DETERMINISM_CONTRACTS = {
    # -- replay roots -------------------------------------------------------
    # name -> {file, qual}: the functions whose output must be a pure
    # function of their inputs (journal bytes, snapshots, scripted
    # clocks).  Everything statically reachable from a root through the
    # project call graph is tainted by DF018.  The name is the stable
    # identity the runtime witness and the dual-run drill report by.
    "replay_roots": {
        "slo.ingest_snapshot": {
            "file": "dragonfly2_tpu/utils/slo.py",
            "qual": "SLOEngine.ingest_snapshot",
        },
        "slo.evaluate": {
            "file": "dragonfly2_tpu/utils/slo.py",
            "qual": "SLOEngine.evaluate",
        },
        "slo.replay_fleet": {
            "file": "dragonfly2_tpu/utils/slo.py",
            "qual": "replay_fleet",
        },
        "autopilot.ingest": {
            "file": "dragonfly2_tpu/qos/autopilot.py",
            "qual": "SLOAutopilot.ingest",
        },
        "autopilot.replay": {
            "file": "dragonfly2_tpu/qos/autopilot.py",
            "qual": "SLOAutopilot.replay",
        },
        "accounting.note_at": {
            "file": "dragonfly2_tpu/qos/accounting.py",
            "qual": "TenantAccounting.note_at",
        },
        "accounting.snapshot": {
            "file": "dragonfly2_tpu/qos/accounting.py",
            "qual": "TenantAccounting.snapshot",
        },
        "rollout.breach": {
            "file": "dragonfly2_tpu/rollout/controller.py",
            "qual": "RolloutController._breach",
        },
        "rollout.evaluate_shadow": {
            "file": "dragonfly2_tpu/rollout/evaluation.py",
            "qual": "evaluate_shadow",
        },
        "rollout.regret_at_k": {
            "file": "dragonfly2_tpu/rollout/evaluation.py",
            "qual": "regret_at_k",
        },
        "rollout.inversion_rate": {
            "file": "dragonfly2_tpu/rollout/evaluation.py",
            "qual": "pairwise_inversion_rate",
        },
        "sharding.owner": {
            "file": "dragonfly2_tpu/scheduler/sharding.py",
            "qual": "ShardRing.owner",
        },
        "sharding.pick": {
            "file": "dragonfly2_tpu/scheduler/sharding.py",
            "qual": "ShardRing.pick",
        },
        "fleet_assemble.merge_runs": {
            "file": "tools/fleet_assemble.py",
            "qual": "merge_runs",
        },
        "fleet_assemble.build_report": {
            "file": "tools/fleet_assemble.py",
            "qual": "build_report",
        },
        "trace_assemble.critical_path": {
            "file": "tools/trace_assemble.py",
            "qual": "critical_path",
        },
        "trace_assemble.summarize_trace": {
            "file": "tools/trace_assemble.py",
            "qual": "summarize_trace",
        },
        "lifecycle.arbitrate": {
            "file": "dragonfly2_tpu/lifecycle/arbiter.py",
            "qual": "arbitrate_candidates",
        },
        "lifecycle.epoch_plan": {
            "file": "dragonfly2_tpu/lifecycle/arbiter.py",
            "qual": "plan_epoch",
        },
    },
    # -- injection seams ----------------------------------------------------
    # The ONLY doors nondeterminism may enter a replay path through: a
    # declared parameter on a declared function.  The live edge (tick(),
    # note(), the journal cadence thread) samples the ambient source
    # OUTSIDE the taint closure and passes the value in; replay passes
    # journal timestamps / scripted clocks through the same door.  Each
    # entry must name a real parameter of a real function — stale seams
    # fail DF018 by name.
    "injection_seams": [
        {
            "file": "dragonfly2_tpu/utils/slo.py",
            "qual": "SLOEngine.evaluate",
            "params": ["now"],
            "kind": "clock",
        },
        {
            "file": "dragonfly2_tpu/qos/accounting.py",
            "qual": "TenantAccounting.note_at",
            "params": ["now"],
            "kind": "clock",
        },
        {
            "file": "dragonfly2_tpu/qos/accounting.py",
            "qual": "TenantAccounting.__init__",
            "params": ["now"],
            "kind": "clock",
        },
        {
            "file": "dragonfly2_tpu/utils/metric_journal.py",
            "qual": "MetricJournal.__init__",
            "params": ["run_id"],
            "kind": "identity",
        },
        {
            "file": "dragonfly2_tpu/rpc/ratelimit.py",
            "qual": "TokenBucket.take_at",
            "params": ["now"],
            "kind": "clock",
        },
        {
            "file": "dragonfly2_tpu/sim/fleet.py",
            "qual": "FleetConfig",
            "params": ["seed"],
            "kind": "rng",
        },
        {
            "file": "dragonfly2_tpu/sim/qos.py",
            "qual": "QoSDrillConfig",
            "params": ["seed"],
            "kind": "rng",
        },
        {
            "file": "dragonfly2_tpu/sim/lifecycle.py",
            "qual": "LifecycleDrillConfig",
            "params": ["seed"],
            "kind": "rng",
        },
    ],
    # -- observability sinks -------------------------------------------------
    # Fire-and-forget diagnostics reachable from replay paths whose
    # values NEVER flow back into decision output: the flight recorder
    # (span timestamps are wall-clock by design), metric gauge/counter
    # writes, and the chaos seam.  DF018 taint does not descend into a
    # callee matching one of these prefixes ("relpath:*" = whole
    # module, "relpath:Qual" = one function/method), and the runtime
    # witness excuses ambient reads observed inside their spans.
    "sinks": [
        "dragonfly2_tpu/utils/tracing.py:*",
        "dragonfly2_tpu/utils/faultinject.py:*",
        "dragonfly2_tpu/utils/metrics.py:Counter.inc",
        "dragonfly2_tpu/utils/metrics.py:Gauge.set",
        "dragonfly2_tpu/utils/metrics.py:Sketch.observe",
    ],
    # -- canonical serialization (DF019) -------------------------------------
    # Every journal/replay artifact writer: each ``json.dumps`` in the
    # writer must pin ``sort_keys=True``, and when a frame payload
    # builder is declared, the dict literal it returns must carry
    # exactly the declared key set (drift fails in BOTH directions).
    "serialization": {
        "metric_journal.frame": {
            "file": "dragonfly2_tpu/utils/metric_journal.py",
            "qual": "encode_frame",
            "format": "DFMJ1",
            "builder": "MetricJournal._payload",
            "keys": ["metrics", "pid", "run_id", "seq", "service", "ts", "v"],
        },
        "trace_log.frame": {
            "file": "dragonfly2_tpu/utils/tracing.py",
            "qual": "DurableSpanExporter._write",
            "format": "DFTL1",
            "builder": "build_export_request",
            "keys": ["resourceSpans"],
        },
        "columnar.header": {
            "file": "dragonfly2_tpu/records/columnar.py",
            "qual": "_encode_header",
            "format": "DFC1",
            "builder": "_encode_header",
            "keys": ["columns", "created_at_ns", "dtype"],
        },
        "fleet_assemble.json": {
            "file": "tools/fleet_assemble.py",
            "qual": "main",
            "format": "json",
        },
        "trace_assemble.json": {
            "file": "tools/trace_assemble.py",
            "qual": "main",
            "format": "json",
        },
        "bench_sched.json": {
            "file": "tools/bench_sched.py",
            "qual": "main",
            "format": "json",
        },
        "bench_download.json": {
            "file": "tools/bench_download.py",
            "qual": "main",
            "format": "json",
        },
    },
}
