"""Reference-CSV compatibility codec (scheduler/storage/types.go +
gocsv MarshalWithoutHeaders).

The reference persists training records as HEADERLESS positional CSV:
struct fields flattened in declaration order, slice fields padded to
fixed caps (pieces=10 types.go:169, parents=20 :218, destHosts=5 :293).
A Download row is exactly 1934 columns, a NetworkTopology row 71 —
verified against trainer/storage/testdata/*.csv.

This module reads/writes that exact layout so a reference deployment's
accumulated datasets (or a reference trainer expecting CSV) interoperate
with this framework's records.  Two schema divergences are adapted at
the boundary:

- reference CPUTimes carries ``guestNice`` (our CPUTimes stops at
  ``guest``) → written as 0, ignored on read;
- our NetworkStat appends download/upload rate fields the reference
  lacks → only the reference's four columns cross the CSV boundary.
"""

from __future__ import annotations

import csv
import io
from typing import Iterable, List

from ..utils.hostinfo import BuildInfo, CPUStat, CPUTimes, DiskStat, MemoryStat, NetworkStat
from .schema import (
    Download,
    DownloadError,
    HostRecord,
    NetworkTopologyRecord,
    Parent,
    Piece,
    ProbeStats,
    TaskRecord,
    TopoHost,
)

_PAD = "__pad__"  # reference-only column: write zero value, skip on read

# Spec grammar: a list of entries, each one of
#   (field_name, type)                    scalar (str | int | float)
#   (field_name, [spec])                  nested dataclass
#   (field_name, [spec], count, factory)  fixed-cap list of dataclasses
_TIMES = [(n, float) for n in (
    "user", "system", "idle", "nice", "iowait", "irq", "softirq", "steal",
    "guest",
)] + [(_PAD, float)]  # guestNice (host.go:267)

_CPU = [
    ("logical_count", int), ("physical_count", int),
    ("percent", float), ("process_percent", float),
    ("times", _TIMES),
]

_MEMORY = [
    ("total", int), ("available", int), ("used", int),
    ("used_percent", float), ("process_used_percent", float), ("free", int),
]

# Reference Network (host.go:294-303) has exactly these four.
_NETWORK = [
    ("tcp_connection_count", int), ("upload_tcp_connection_count", int),
    ("location", str), ("idc", str),
]

_DISK = [
    ("total", int), ("free", int), ("used", int), ("used_percent", float),
    ("inodes_total", int), ("inodes_used", int), ("inodes_free", int),
    ("inodes_used_percent", float),
]

_BUILD = [
    ("git_version", str), ("git_commit", str), ("go_version", str),
    ("platform", str),
]

_HOST = [
    ("id", str), ("type", str), ("hostname", str), ("ip", str),
    ("port", int), ("download_port", int), ("os", str), ("platform", str),
    ("platform_family", str), ("platform_version", str),
    ("kernel_version", str), ("concurrent_upload_limit", int),
    ("concurrent_upload_count", int), ("upload_count", int),
    ("upload_failed_count", int),
    ("cpu", _CPU), ("memory", _MEMORY), ("network", _NETWORK),
    ("disk", _DISK), ("build", _BUILD),
    ("scheduler_cluster_id", int), ("created_at", int), ("updated_at", int),
]

_TASK = [
    ("id", str), ("url", str), ("type", str), ("content_length", int),
    ("total_piece_count", int), ("back_to_source_limit", int),
    ("back_to_source_peer_count", int), ("state", str),
    ("created_at", int), ("updated_at", int),
]

_PIECE = [("length", int), ("cost", int), ("created_at", int)]

_PARENT = [
    ("id", str), ("tag", str), ("application", str), ("state", str),
    ("cost", int), ("upload_piece_count", int), ("finished_piece_count", int),
    ("host", _HOST), ("pieces", _PIECE, 10, Piece),
    ("created_at", int), ("updated_at", int),
]

_DOWNLOAD = [
    ("id", str), ("tag", str), ("application", str), ("state", str),
    ("error", [("code", str), ("message", str)]),
    ("cost", int), ("finished_piece_count", int),
    ("task", _TASK), ("host", _HOST),
    ("parents", _PARENT, 20, Parent),
    ("created_at", int), ("updated_at", int),
]

_PROBES = [("average_rtt", int), ("created_at", int), ("updated_at", int)]

_SRC_HOST = [
    ("id", str), ("type", str), ("hostname", str), ("ip", str),
    ("port", int), ("network", _NETWORK),
]

_DEST_HOST = _SRC_HOST + [("probes", _PROBES)]

_NETWORK_TOPOLOGY = [
    ("id", str), ("host", _SRC_HOST),
    ("dest_hosts", _DEST_HOST, 5, TopoHost),
    ("created_at", int),
]

# Nested dataclass factories for read-side construction, keyed by the
# spec object identity.
_FACTORIES = {
    id(_TIMES): CPUTimes, id(_CPU): CPUStat, id(_MEMORY): MemoryStat,
    id(_NETWORK): NetworkStat, id(_DISK): DiskStat, id(_BUILD): BuildInfo,
    id(_HOST): HostRecord, id(_TASK): TaskRecord, id(_PIECE): Piece,
    id(_PARENT): Parent, id(_DOWNLOAD): Download, id(_PROBES): ProbeStats,
    id(_SRC_HOST): TopoHost, id(_DEST_HOST): TopoHost,
    id(_NETWORK_TOPOLOGY): NetworkTopologyRecord,
    id(_DOWNLOAD[4][1]): DownloadError,
}


def _spec_width(spec) -> int:
    width = 0
    for entry in spec:
        if len(entry) == 4:
            _, sub, count, _ = entry
            width += _spec_width(sub) * count
        elif isinstance(entry[1], list):
            width += _spec_width(entry[1])
        else:
            width += 1
    return width

DOWNLOAD_COLUMNS_TOTAL = _spec_width(_DOWNLOAD)            # 1934
NETWORK_TOPOLOGY_COLUMNS_TOTAL = _spec_width(_NETWORK_TOPOLOGY)  # 71
assert DOWNLOAD_COLUMNS_TOTAL == 1934
assert NETWORK_TOPOLOGY_COLUMNS_TOTAL == 71


def _go_float(value: float) -> str:
    """Go's %v for float64: strconv.FormatFloat(v, 'g', -1, 64) —
    shortest round-trip digits; scientific form when the decimal
    exponent is < -4 or >= 6 (ftoa.go uses eprec=6 for the shortest
    path), else plain form.  So 123456.78 → "123456.78" but
    1000000 → "1e+06" and 8589934592 → "8.589934592e+09"."""
    import math

    v = float(value)
    if v == 0.0:
        return "0"
    if not math.isfinite(v):  # Go fmt: +Inf / -Inf / NaN
        return "NaN" if math.isnan(v) else ("+Inf" if v > 0 else "-Inf")
    # Fast path: derive the decimal exponent from repr() without Decimal
    # (this runs per float across 1934-column rows).
    s = repr(v)
    mant_str, _, exp_str = s.partition("e")
    if exp_str:
        # repr e-notation is normalized to one integer digit.
        sci_exp = int(exp_str)
    else:
        digits_str = mant_str.lstrip("-")
        int_part, _, frac = digits_str.partition(".")
        if int_part != "0":
            sci_exp = len(int_part) - 1
        else:
            leading_zeros = len(frac) - len(frac.lstrip("0"))
            sci_exp = -(leading_zeros + 1)
    if -4 <= sci_exp < 6:
        # Python repr is plain-form throughout this range already.
        return s[:-2] if s.endswith(".0") else s
    from decimal import Decimal

    sign, digits, _exp = Decimal(s).normalize().as_tuple()
    prefix = "-" if sign else ""
    mantissa = str(digits[0])
    if len(digits) > 1:
        mantissa += "." + "".join(map(str, digits[1:]))
    return (
        f"{prefix}{mantissa}e{'+' if sci_exp >= 0 else '-'}{abs(sci_exp):02d}"
    )


def _fmt(value, typ) -> str:
    if typ is str:
        return value or ""
    if typ is float:
        return _go_float(value)
    return str(int(value))


def _flatten_zero(spec, out: List[str]) -> None:
    """Padding slots render as GO zero values (""/0) regardless of our
    dataclass defaults — what gocsv writes for empty array slots."""
    for entry in spec:
        if len(entry) == 4:
            _, sub, count, _ = entry
            for _ in range(count):
                _flatten_zero(sub, out)
        elif isinstance(entry[1], list):
            _flatten_zero(entry[1], out)
        else:
            out.append(_fmt(entry[1](), entry[1]))


def _flatten(obj, spec, out: List[str]) -> None:
    for entry in spec:
        if len(entry) == 4:
            name, sub, count, _factory = entry
            items = list(getattr(obj, name))[:count]
            for item in items:
                _flatten(item, sub, out)
            for _ in range(count - len(items)):
                _flatten_zero(sub, out)
        elif isinstance(entry[1], list):
            name, sub = entry
            _flatten(getattr(obj, name), sub, out)
        else:
            name, typ = entry
            if name is _PAD:
                out.append(_fmt(typ(), typ))
            else:
                out.append(_fmt(getattr(obj, name), typ))


_PARSED_BLANKS = {}


def _parsed_blank(spec):
    """The record an all-empty cell run parses to — the padding shape.
    NOT the dataclass defaults: ours differ from Go zero values (e.g.
    content_length=-1, host type 'normal'), and padding written by the
    reference is Go-zero shaped."""
    blank = _PARSED_BLANKS.get(id(spec))
    if blank is None:
        blank, _ = _parse([""] * _spec_width(spec), 0, spec)
        _PARSED_BLANKS[id(spec)] = blank
    return blank


def _parse(cells, pos: int, spec):
    factory = _FACTORIES[id(spec)]
    kwargs = {}
    for entry in spec:
        if len(entry) == 4:
            name, sub, count, _item_factory = entry
            items = []
            for _ in range(count):
                item, pos = _parse(cells, pos, sub)
                items.append(item)
            # Trailing padding slots are not data.
            blank = _parsed_blank(sub)
            while items and items[-1] == blank:
                items.pop()
            kwargs[name] = items
        elif isinstance(entry[1], list):
            name, sub = entry
            kwargs[name], pos = _parse(cells, pos, sub)
        else:
            name, typ = entry
            raw = cells[pos]
            pos += 1
            if name is _PAD:
                continue
            if typ is str:
                kwargs[name] = raw
            elif typ is float:
                kwargs[name] = float(raw) if raw else 0.0
            elif not raw:
                kwargs[name] = 0
            else:
                try:
                    # Direct int parse: the float detour rounds int64s
                    # ≥ 2^53 (nanosecond timestamps) — silent corruption.
                    kwargs[name] = int(raw)
                except ValueError:
                    kwargs[name] = int(float(raw))
    return factory(**kwargs), pos


# -- public API --------------------------------------------------------------


def download_to_row(d: Download) -> List[str]:
    out: List[str] = []
    _flatten(d, _DOWNLOAD, out)
    return out


def download_from_row(cells: List[str]) -> Download:
    if len(cells) != DOWNLOAD_COLUMNS_TOTAL:
        raise ValueError(
            f"download row has {len(cells)} columns, "
            f"expected {DOWNLOAD_COLUMNS_TOTAL}"
        )
    record, _ = _parse(cells, 0, _DOWNLOAD)
    return record


def topology_to_row(t: NetworkTopologyRecord) -> List[str]:
    out: List[str] = []
    _flatten(t, _NETWORK_TOPOLOGY, out)
    return out


def topology_from_row(cells: List[str]) -> NetworkTopologyRecord:
    if len(cells) != NETWORK_TOPOLOGY_COLUMNS_TOTAL:
        raise ValueError(
            f"topology row has {len(cells)} columns, "
            f"expected {NETWORK_TOPOLOGY_COLUMNS_TOTAL}"
        )
    record, _ = _parse(cells, 0, _NETWORK_TOPOLOGY)
    return record


def write_download_csv(records: Iterable[Download], path: str) -> int:
    n = 0
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        for r in records:
            writer.writerow(download_to_row(r))
            n += 1
    return n


def read_download_csv(path: str) -> List[Download]:
    return list(iter_download_csv(path))


def write_topology_csv(records: Iterable[NetworkTopologyRecord], path: str) -> int:
    n = 0
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        for r in records:
            writer.writerow(topology_to_row(r))
            n += 1
    return n


def read_topology_csv(path: str) -> List[NetworkTopologyRecord]:
    return list(iter_topology_csv(path))


def iter_download_csv(path: str):
    """Stream Download records row by row — a multi-GB reference dataset
    must never be materialized as a list of deep dataclasses."""
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if row:
                yield download_from_row(row)


def iter_topology_csv(path: str):
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if row:
                yield topology_from_row(row)


def convert_download_csv_to_columnar(csv_path: str, out_path: str) -> int:
    """Reference CSV dataset → this framework's columnar TPU-ingest shard
    (the migration path for a reference deployment's accumulated data).
    Streams record-by-record; returns feature rows written."""
    import numpy as np

    from .columnar import ColumnarWriter
    from .features import DOWNLOAD_COLUMNS, download_to_rows

    n = 0
    with ColumnarWriter(out_path, DOWNLOAD_COLUMNS) as w:
        for record in iter_download_csv(csv_path):
            rows = download_to_rows(record)
            if len(rows):
                w.append(np.asarray(rows, np.float32))
                n += len(rows)
    return n


def convert_topology_csv_to_columnar(csv_path: str, out_path: str) -> int:
    import numpy as np

    from .columnar import ColumnarWriter
    from .features import TOPO_COLUMNS, topology_to_rows

    n = 0
    with ColumnarWriter(out_path, TOPO_COLUMNS) as w:
        for record in iter_topology_csv(csv_path):
            rows = topology_to_rows(record)
            if len(rows):
                w.append(np.asarray(rows, np.float32))
                n += len(rows)
    return n


def parse_download_csv_bytes(data: bytes) -> List[Download]:
    return [
        download_from_row(row)
        for row in csv.reader(io.StringIO(data.decode()))
        if row
    ]


def parse_topology_csv_bytes(data: bytes) -> List[NetworkTopologyRecord]:
    return [
        topology_from_row(row)
        for row in csv.reader(io.StringIO(data.decode()))
        if row
    ]
