"""Featurization: records → fixed-width tensors (the model's input spec).

The reference intended (but never built) this step — trainer/training's
TODOs say "preprocess dataset" (training.go:82-99).  Here it is explicit
and versioned: every Download record yields one training row per parent
edge (features of child host, parent host, and the transfer; target =
observed bandwidth), and NetworkTopology records yield probe-graph edges.

Feature engineering notes (TPU-first):
- Everything is float32, fixed width, no strings — rows append straight
  into columnar files and batch into static-shape device arrays.
- Counts/bytes are log1p-compressed; percentages scaled to [0,1]; the
  bandwidth target is log1p(bytes/sec) (dynamic range spans KB/s..GB/s).
- Host identity is carried as a hash bucket so the GNN can build its node
  index without string lookups on device.
"""

from __future__ import annotations

import functools
import math
from typing import List, Optional

import numpy as np

from .schema import Download, HostRecord, NetworkTopologyRecord, Parent

# ---------------------------------------------------------------------------
# Host features
# ---------------------------------------------------------------------------

HOST_FEATURE_NAMES = (
    "cpu_percent",            # [0,1]
    "mem_used_percent",       # [0,1]
    "disk_used_percent",      # [0,1]
    "tcp_conn_log",           # log1p
    "upload_tcp_conn_log",    # log1p
    "upload_load",            # concurrent uploads / limit
    "upload_success_ratio",   # 1 - failed/total
    "upload_count_log",       # log1p
    "type_normal",
    "type_super",
    "type_strong",
    "type_weak",
)
HOST_FEATURE_DIM = len(HOST_FEATURE_NAMES)

_HOST_TYPE_INDEX = {"normal": 8, "super": 9, "strong": 10, "weak": 11}


def host_features(h: HostRecord) -> np.ndarray:
    out = np.zeros(HOST_FEATURE_DIM, dtype=np.float32)
    out[0] = min(max(h.cpu.percent / 100.0, 0.0), 1.0)
    out[1] = min(max(h.memory.used_percent / 100.0, 0.0), 1.0)
    out[2] = min(max(h.disk.used_percent / 100.0, 0.0), 1.0)
    out[3] = math.log1p(max(h.network.tcp_connection_count, 0))
    out[4] = math.log1p(max(h.network.upload_tcp_connection_count, 0))
    limit = max(h.concurrent_upload_limit, 1)
    out[5] = min(h.concurrent_upload_count / limit, 4.0)
    total = max(h.upload_count, 1)
    out[6] = 1.0 - min(h.upload_failed_count / total, 1.0)
    out[7] = math.log1p(max(h.upload_count, 0))
    idx = _HOST_TYPE_INDEX.get(h.type, 8)
    out[idx] = 1.0
    return out


@functools.lru_cache(maxsize=65536)
def _location_affinity(a: str, b: str) -> float:
    """Fraction of matching location path segments (reference scores location
    affinity by shared '|'-separated prefix, evaluator_base.go).

    lru_cache: location strings come from a small fleet-topology
    vocabulary and recur on every announce — the split-and-compare was a
    measurable slice of the serving featurize profile (BENCHMARKS.md)."""
    if not a or not b:
        return 0.0
    pa, pb = a.split("|"), b.split("|")
    n = min(len(pa), len(pb))
    match = 0
    for i in range(n):
        if pa[i] != pb[i]:
            break
        match += 1
    return match / max(len(pa), len(pb))


# ---------------------------------------------------------------------------
# Download → MLP training rows (one per parent edge)
# ---------------------------------------------------------------------------

EDGE_FEATURE_NAMES = (
    "same_idc",
    "location_affinity",
    "piece_count_log",
    "mean_piece_size_log",
    "content_length_log",
    "finished_piece_ratio",
    "parent_cost_log_s",
    "parent_upload_pieces_log",
)
EDGE_FEATURE_DIM = len(EDGE_FEATURE_NAMES)

DOWNLOAD_FEATURE_NAMES = (
    tuple(f"child_{n}" for n in HOST_FEATURE_NAMES)
    + tuple(f"parent_{n}" for n in HOST_FEATURE_NAMES)
    + EDGE_FEATURE_NAMES
)
DOWNLOAD_FEATURE_DIM = len(DOWNLOAD_FEATURE_NAMES)  # 32

# Features measured DURING/AFTER the very transfer being predicted — known
# in a completed Download record but NOT at scheduling time (the evaluator
# ranks parents before any piece moves).  The deployed scorer must train
# with these zeroed so train and serve distributions match; leaving them in
# lets the model key on the leak and collapse at serve time.
POST_HOC_FEATURE_NAMES = (
    "piece_count_log",          # pieces this parent served to this child
    "mean_piece_size_log",
    "parent_cost_log_s",        # duration of this parent's transfers
    "parent_upload_pieces_log",
)
POST_HOC_FEATURE_IDX = tuple(
    i for i, n in enumerate(DOWNLOAD_FEATURE_NAMES)
    if n in POST_HOC_FEATURE_NAMES
)


_POST_HOC_IDX_ARR = np.asarray(POST_HOC_FEATURE_IDX, dtype=np.intp)


def mask_post_hoc(features: np.ndarray) -> np.ndarray:
    """Zero the post-hoc columns of [n, DOWNLOAD_FEATURE_DIM] rows (copy)."""
    out = np.array(features, dtype=np.float32, copy=True)
    out[..., _POST_HOC_IDX_ARR] = 0.0
    return out

# Full columnar row = src hash bucket, dst hash bucket, features..., target.
DOWNLOAD_COLUMNS = ("src_bucket", "dst_bucket") + DOWNLOAD_FEATURE_NAMES + ("target_log_bw",)

NUM_HASH_BUCKETS = 1 << 20


def accumulate_host_feature_sums(
    rows: np.ndarray,
    src: np.ndarray,
    dst: np.ndarray,
    feat_sum: np.ndarray,
    feat_cnt: np.ndarray,
) -> None:
    """Fold download rows' host features into per-node (sum, count)
    accumulators: child features at cols [2, 2+H) credit ``dst``, parent
    features at [2+H, 2+2H) credit ``src``.  THE one implementation of
    this attribution — the batch trainer and the online wire adapter
    must agree on it.  Uses bincount, not ``np.add.at``: the fancy-index
    scatter runs at single-digit M updates/s and measurably capped the
    online wire soak (BENCHMARKS.md)."""
    n_nodes = feat_cnt.shape[0]
    child_f = rows[:, 2 : 2 + HOST_FEATURE_DIM]
    parent_f = rows[:, 2 + HOST_FEATURE_DIM : 2 + 2 * HOST_FEATURE_DIM]
    for ids, feats in ((src, parent_f), (dst, child_f)):
        feat_cnt += np.bincount(ids, minlength=n_nodes).astype(feat_cnt.dtype)
        for j in range(feats.shape[1]):
            feat_sum[:, j] += np.bincount(
                ids, weights=feats[:, j], minlength=n_nodes
            ).astype(feat_sum.dtype)


def host_bucket(host_id: str) -> int:
    """Stable hash bucket for a host id (string → int node key)."""
    import zlib

    return zlib.crc32(host_id.encode("utf-8")) % NUM_HASH_BUCKETS


def edge_features(download: Download, parent: Parent) -> np.ndarray:
    out = np.zeros(EDGE_FEATURE_DIM, dtype=np.float32)
    child, ph = download.host, parent.host
    out[0] = 1.0 if (child.network.idc and child.network.idc == ph.network.idc) else 0.0
    out[1] = _location_affinity(child.network.location, ph.network.location)
    out[2] = math.log1p(len(parent.pieces))
    total_len = sum(p.length for p in parent.pieces)
    if parent.pieces:
        out[3] = math.log1p(total_len / len(parent.pieces))
    out[4] = math.log1p(max(download.task.content_length, 0))
    total_pieces = max(download.task.total_piece_count, 1)
    out[5] = min(parent.finished_piece_count / total_pieces, 1.0)
    out[6] = math.log1p(max(parent.cost, 0) / 1e9)
    out[7] = math.log1p(max(parent.upload_piece_count, 0))
    return out


def edge_features_batch(  # dflint: hotpath
    *,
    same_idc: np.ndarray,
    location_affinity: np.ndarray,
    served_counts: np.ndarray,
    served_len_sums: np.ndarray,
    content_length: int,
    finished_piece_counts: np.ndarray,
    total_piece_count: int,
    cost_ns: np.ndarray,
    upload_piece_counts: np.ndarray,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Vectorized ``edge_features`` over n parent edges (the scheduler
    serving hot path, DESIGN.md §14).

    Inputs mirror what ``Peer.to_parent_record`` would have materialized
    per edge: ``served_counts``/``served_len_sums`` are the child's
    pieces attributed to each parent AFTER the ``MAX_PIECES_PER_PARENT``
    truncation (they feed columns 2-3), while ``upload_piece_counts`` is
    the untruncated per-parent serve count (column 7) — exactly the
    record's split.  Column-for-column byte-identical to stacking scalar
    ``edge_features`` rows (asserted in tests/test_sched_vectorized.py):
    every column runs the same float64 math and takes one float32
    rounding on assignment, like the scalar path's array fill.

    ``out`` (optional, [n, EDGE_FEATURE_DIM] float32, may be a column
    slice of a larger matrix): written in place and returned — the
    serving path lands edge features directly in its feature matrix
    instead of paying a temp + copy.  Every column is assigned.
    """
    n = len(finished_piece_counts)
    if out is None:
        out = np.empty((n, EDGE_FEATURE_DIM), dtype=np.float32)
    out[:, 0] = same_idc
    out[:, 1] = location_affinity
    # dflint: disable=DF012 pragmas below: reviewed float64 INTERMEDIATES
    # — the scalar path's math runs in float64 and each column takes one
    # float32 rounding on assignment into `out` (byte-parity contract).
    counts = np.asarray(served_counts, dtype=np.float64)  # dflint: disable=DF012
    lens = np.asarray(served_len_sums, dtype=np.float64)  # dflint: disable=DF012
    out[:, 2] = np.log1p(counts)
    out[:, 3] = np.where(
        counts > 0, np.log1p(lens / np.maximum(counts, 1.0)), 0.0
    )
    out[:, 4] = math.log1p(max(content_length, 0))
    total = max(total_piece_count, 1)
    out[:, 5] = np.minimum(
        np.asarray(finished_piece_counts, dtype=np.float64) / total, 1.0  # dflint: disable=DF012
    )
    out[:, 6] = np.log1p(
        np.maximum(np.asarray(cost_ns, dtype=np.float64), 0) / 1e9  # dflint: disable=DF012
    )
    out[:, 7] = np.log1p(
        np.maximum(np.asarray(upload_piece_counts, dtype=np.float64), 0)  # dflint: disable=DF012
    )
    return out


def target_log_bandwidth(parent: Parent) -> Optional[float]:
    bw = parent.observed_bandwidth()
    if bw <= 0.0:
        return None
    return math.log1p(bw)


def download_to_rows(download: Download) -> np.ndarray:
    """[n_parents_with_signal, len(DOWNLOAD_COLUMNS)] float32 rows."""
    child_f = host_features(download.host)
    child_b = float(host_bucket(download.host.id))
    rows: List[np.ndarray] = []
    for parent in download.parents:
        target = target_log_bandwidth(parent)
        if target is None:
            continue
        row = np.concatenate(
            [
                np.array([host_bucket(parent.host.id), child_b], dtype=np.float32),
                child_f,
                host_features(parent.host),
                edge_features(download, parent),
                np.array([target], dtype=np.float32),
            ]
        )
        rows.append(row)
    if not rows:
        return np.zeros((0, len(DOWNLOAD_COLUMNS)), dtype=np.float32)
    return np.stack(rows)


def unlog_bandwidth(y: np.ndarray) -> np.ndarray:
    return np.expm1(y)


# ---------------------------------------------------------------------------
# NetworkTopology → probe-edge rows
# ---------------------------------------------------------------------------

TOPO_COLUMNS = (
    "src_bucket",
    "dst_bucket",
    "avg_rtt_norm",      # EMA RTT / 1s ping timeout, clipped to [0,1]
    "src_tcp_conn_log",
    "dst_tcp_conn_log",
    "same_idc",
    "location_affinity",
    "freshness",         # exp(-age_hours)
)

PING_TIMEOUT_NS = 1_000_000_000  # 1s normalization, evaluator_network_topology.go:53-56


def topology_to_rows(record: NetworkTopologyRecord, now_ns: Optional[int] = None) -> np.ndarray:
    import time as _time

    if now_ns is None:
        now_ns = _time.time_ns()
    src = record.host
    src_b = float(host_bucket(src.id))
    src_conn = math.log1p(max(src.network.tcp_connection_count, 0))
    rows: List[np.ndarray] = []
    for dst in record.dest_hosts:
        rtt = min(max(dst.probes.average_rtt, 0) / PING_TIMEOUT_NS, 1.0)
        age_h = max(now_ns - dst.probes.updated_at, 0) / 3.6e12
        rows.append(
            np.array(
                [
                    src_b,
                    float(host_bucket(dst.id)),
                    rtt,
                    src_conn,
                    math.log1p(max(dst.network.tcp_connection_count, 0)),
                    1.0 if (src.network.idc and src.network.idc == dst.network.idc) else 0.0,
                    _location_affinity(src.network.location, dst.network.location),
                    math.exp(-age_h),
                ],
                dtype=np.float32,
            )
        )
    if not rows:
        return np.zeros((0, len(TOPO_COLUMNS)), dtype=np.float32)
    return np.stack(rows)
