"""Fixed-width columnar record files — the TPU ingest format.

The reference streams gocsv-encoded text (scheduler/storage/storage.go,
announcer.go:173-237); parsing that at 1B-records/10min is hopeless.  Here
every record is featurized *at write time* into a fixed-width float32 row
(see features.py), and files are raw row-major matrices with a small JSON
header:

    [4B magic "DFC1"][4B little-endian header length][header JSON][rows...]

- Append is O(row) with no serialization beyond ``ndarray.tobytes``.
- Read is zero-copy ``np.memmap`` — the host input pipeline slices batches
  straight out of the page cache into device transfers.
- Fixed width ⇒ static shapes ⇒ XLA compiles the train step once.

The C++ record engine (native/) implements this same format for the
scheduler's hot write path; this module is the canonical spec and the
Python reader/writer.
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass
from typing import Iterator, Sequence

import numpy as np

# Shared with the C++ record engine (native.cpp kMagic) — the ABI
# registry pins both sides to the same 4 bytes (DF020).
from . import abi_contracts as _abi

MAGIC = _abi.constant("kMagic").encode("ascii")
_LEN_FMT = "<I"


@dataclass(frozen=True)
class ColumnarHeader:
    columns: tuple
    dtype: str = "float32"
    created_at_ns: int = 0

    @property
    def row_nbytes(self) -> int:
        return np.dtype(self.dtype).itemsize * len(self.columns)


def _encode_header(header: ColumnarHeader) -> bytes:
    # sort_keys pins canonical header bytes (DF019): equal headers must
    # serialize identically regardless of dict hash order.
    payload = json.dumps(
        {
            "columns": list(header.columns),
            "dtype": header.dtype,
            "created_at_ns": header.created_at_ns,
        },
        sort_keys=True,
    ).encode("utf-8")
    return MAGIC + struct.pack(_LEN_FMT, len(payload)) + payload


def _header_from_meta(meta: dict) -> ColumnarHeader:
    """ONE place that maps the header's JSON meta onto ColumnarHeader —
    the file reader and the streaming decoder must agree on defaults."""
    return ColumnarHeader(
        columns=tuple(meta["columns"]),
        dtype=meta.get("dtype", "float32"),
        created_at_ns=meta.get("created_at_ns", 0),
    )


def read_header(path: str) -> tuple[ColumnarHeader, int]:
    """Returns (header, data_offset).  Every malformed-prefix shape —
    short magic, short length word, a header cut off mid-JSON, corrupt
    JSON — raises ValueError (never struct/json errors or silent
    garbage): callers distinguish exactly 'bad file' from IO errors."""
    with open(path, "rb") as f:
        magic = f.read(4)
        if magic != MAGIC:
            raise ValueError(f"{path}: bad magic {magic!r}")
        raw_len = f.read(4)
        if len(raw_len) < 4:
            raise ValueError(f"{path}: truncated header length")
        (hlen,) = struct.unpack(_LEN_FMT, raw_len)
        raw = f.read(hlen)
        if len(raw) < hlen:
            raise ValueError(
                f"{path}: truncated header ({len(raw)} of {hlen} bytes)"
            )
        try:
            meta = json.loads(raw.decode("utf-8"))
            header = _header_from_meta(meta)
        except (UnicodeDecodeError, json.JSONDecodeError, KeyError, TypeError) as exc:
            raise ValueError(f"{path}: corrupt header: {exc}") from exc
    return header, 8 + hlen


class ColumnarWriter:
    """Append-only writer. Safe for a single writer; readers may mmap live files
    (rows are only visible once fully flushed, tracked by file size)."""

    def __init__(self, path: str, columns: Sequence[str], dtype: str = "float32"):
        self.path = path
        self.header = ColumnarHeader(columns=tuple(columns), dtype=dtype)
        exists = os.path.exists(path) and os.path.getsize(path) > 0
        if exists:
            existing, self._data_offset = read_header(path)
            if existing.columns != self.header.columns:
                raise ValueError(
                    f"{path}: existing columns {existing.columns} != {self.header.columns}"
                )
            self.header = existing
            self._f = open(path, "ab")
        else:
            self._f = open(path, "wb")
            raw = _encode_header(self.header)
            self._f.write(raw)
            self._data_offset = len(raw)
        self._width = len(self.header.columns)
        self._np_dtype = np.dtype(self.header.dtype)

    def append(self, rows: np.ndarray) -> int:
        """Append a [n, ncols] (or [ncols]) array; returns rows written."""
        rows = np.ascontiguousarray(rows, dtype=self._np_dtype)
        if rows.ndim == 1:
            rows = rows[None, :]
        if rows.shape[-1] != self._width:
            raise ValueError(f"row width {rows.shape[-1]} != {self._width}")
        self._f.write(rows.tobytes())
        return rows.shape[0]

    def flush(self) -> None:
        self._f.flush()

    def tell_rows(self) -> int:
        return (self._f.tell() - self._data_offset) // (
            self._np_dtype.itemsize * self._width
        )

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "ColumnarWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class StreamingRowDecoder:
    """Incremental DFC1 decode over a byte stream.

    The mmap reader needs a whole file; the ONLINE ingest path
    (trainer/service feeding trainer/online_graph straight off the
    ``Train`` stream, service_v1.go:128-143 semantics) gets arbitrary
    chunk boundaries mid-flight.  ``feed(data)`` buffers, parses the
    header once, and returns every COMPLETE row received so far; the
    partial tail stays buffered for the next chunk.
    """

    def __init__(self) -> None:
        self._buf = bytearray()
        self.header: ColumnarHeader | None = None
        self.rows_decoded = 0

    def feed(self, data: bytes) -> np.ndarray:
        """Returns the complete rows in ``data`` (+ any buffered tail) as
        a READ-ONLY view where possible — bytearray churn on 100 MB
        chunks cost seconds per chunk (measured), so the bulk of every
        chunk decodes as a zero-copy view even when chunk boundaries
        never align with rows (fixed-size chunkers realign every chunk:
        only the split row is assembled from the buffer, never the
        whole chunk)."""
        width_zero = (0, 0)
        if self.header is None:
            self._buf += data
            if len(self._buf) < 8:
                return np.zeros(width_zero, np.float32)
            if bytes(self._buf[:4]) != MAGIC:
                raise ValueError(f"bad magic {bytes(self._buf[:4])!r}")
            (hlen,) = struct.unpack(_LEN_FMT, self._buf[4:8])
            if len(self._buf) < 8 + hlen:
                return np.zeros(width_zero, np.float32)
            meta = json.loads(bytes(self._buf[8 : 8 + hlen]).decode("utf-8"))
            self.header = _header_from_meta(meta)
            data = bytes(self._buf[8 + hlen :])
            self._buf = bytearray()

        rb = self.header.row_nbytes
        width = len(self.header.columns)
        first = None
        if self._buf:
            # Complete ONLY the split row from the new chunk (tiny copy);
            # the remainder stays eligible for the zero-copy view.
            need = rb - len(self._buf)
            if len(data) < need:
                self._buf += data
                return np.zeros((0, width), np.float32)
            self._buf += data[:need]
            first = np.frombuffer(
                bytes(self._buf), dtype=self.header.dtype
            ).reshape(1, width)
            self._buf = bytearray()
            data = memoryview(data)[need:]
        n = len(data) // rb
        tail = len(data) - n * rb
        if tail:
            self._buf += data[n * rb :]
        if n == 0:
            rows = np.zeros((0, width), np.float32) if first is None else first
        else:
            rows = np.frombuffer(
                memoryview(data)[: n * rb], dtype=self.header.dtype
            ).reshape(n, width)
            if first is not None:
                rows = np.concatenate([first, rows], axis=0)
        self.rows_decoded += len(rows)
        return rows


class ColumnarReader:
    """Zero-copy mmap reader over one columnar file."""

    def __init__(self, path: str):
        self.path = path
        self.header, self._data_offset = read_header(path)
        self._np_dtype = np.dtype(self.header.dtype)
        self._width = len(self.header.columns)
        size = os.path.getsize(path) - self._data_offset
        self.num_rows = size // (self._np_dtype.itemsize * self._width)
        if self.num_rows > 0:
            self._mm = np.memmap(
                path,
                dtype=self._np_dtype,
                mode="r",
                offset=self._data_offset,
                shape=(self.num_rows, self._width),
            )
        else:
            self._mm = np.empty((0, self._width), dtype=self._np_dtype)

    @property
    def columns(self) -> tuple:
        return self.header.columns

    def __len__(self) -> int:
        return self.num_rows

    def __getitem__(self, idx) -> np.ndarray:
        return self._mm[idx]

    def to_array(self) -> np.ndarray:
        return np.asarray(self._mm)

    def batches(self, batch_size: int, drop_remainder: bool = False) -> Iterator[np.ndarray]:
        n = self.num_rows
        for start in range(0, n, batch_size):
            end = start + batch_size
            if end > n and drop_remainder:
                return
            yield np.asarray(self._mm[start:end])


def concat_readers(paths: Sequence[str]) -> np.ndarray:
    """Materialize multiple shards into one array (small datasets / tests)."""
    readers = [ColumnarReader(p) for p in paths if os.path.getsize(p) > 0]
    if not readers:
        raise ValueError("no non-empty shards")
    cols = readers[0].columns
    for r in readers[1:]:
        if r.columns != cols:
            raise ValueError(f"{r.path}: column mismatch")
    return np.concatenate([r.to_array() for r in readers], axis=0)
