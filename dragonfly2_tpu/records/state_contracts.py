"""DF013-DF015 state-machine / crash-consistency / RPC-parity contract
registry — declared ONCE, checked twice (DESIGN.md §19).

The invariants the Manager-HA and sharded-scheduler roadmap items stand
on live here as one literal dict, in the mould of
``records/contracts.py`` (DF012):

- **statically**, ``tools/dflint/staterules.py`` parses this file's AST
  (``ast.literal_eval`` — no import, dflint stays stdlib-only) and
  machine-checks every mutation site: FSM event legality and mirror
  discipline (DF013), StateBackend persistence-site crash consistency —
  one-transaction multi-row flips, owning-lock writes, recovery loaders,
  write ordering, foreign-key delete cleanup (DF014), and RPC
  client/server/transport method parity + retry idempotency
  classification (DF015).
- **dynamically**, the crash witness (``utils/dfcrash.py`` +
  ``tests/test_zz_crashwitness.py``) records every KVTable write the
  tier-1 suite performs and cross-validates it against the static
  persistence inventory, then crash-injects at the declared multi-row
  sites and asserts each namespace's declared invariant after reload.

Because dflint evaluates ``STATE_CONTRACTS`` with ``ast.literal_eval``,
the dict MUST stay a pure literal: no names, calls, or comprehensions.

Sections:

``machines``
    One entry per state machine.  FSM-style machines (``kind: "fsm"``)
    name the defining module/class, the FSM attribute, the
    ``EventDesc`` tuple variable (cross-checked literal-for-literal —
    drift between this registry and the code fails DF013 by machine
    name), declared mirror attributes with their allowed writer
    functions, and the modules allowed to call ``fsm.set_state`` (wire
    mirrors).  Enum-style machines (``kind: "enum"``) name the enum
    class, the attribute carrying the state, the owning modules (the
    only places a direct ``.state =`` write is legal), the declared
    edge list, and ``mutators``: which module may request which target
    state through the registry gateways (``set_state``/``activate``/
    ``deactivate``).

``persistence``
    ``namespaces``: every StateBackend table namespace ever written,
    with its owning module, owning lock (``Class.attr``), recovery
    loader (a ``load_all`` consumer reachable from a constructor),
    declared multi-row transaction sites (must be ONE ``put_many``,
    never sequential ``put``s), boot-time writers exempt from the lock
    rule, and the invariant name the crash witness asserts after a
    reload.  ``write_order``: ordered namespace pairs — in any function
    writing both, the first write to the second namespace must not
    precede the first write to the first (a crash between them must
    leave the referencing row absent, not dangling).
    ``foreign_keys``: parent/child delete coupling — the child cleanup
    must be the only caller of the parent's delete primitive.
    ``implementation``: modules whose table-class bodies ARE the
    backend (exempt from consumer rules).

``rpc``
    Per logical service: the client classes whose ``_call`` literals
    are the method inventory, the inproc server dispatch set, the gRPC
    transport method table, and the idempotency classification every
    retried method must carry — ``idempotent`` (blind retry safe) or
    ``deduped`` (named server-side dedup seam, verified to exist).
"""

from __future__ import annotations

STATE_CONTRACTS = {
    "machines": {
        # -- scheduler peer lifecycle (peer.go:52-110) ----------------------
        "peer": {
            "kind": "fsm",
            "file": "dragonfly2_tpu/scheduler/resource.py",
            "class": "Peer",
            "attr": "fsm",
            "events_var": "PEER_EVENTS",
            "initial": "Pending",
            "states": [
                "Pending", "ReceivedEmpty", "ReceivedTiny", "ReceivedSmall",
                "ReceivedNormal", "Running", "BackToSource", "Succeeded",
                "Failed", "Leave",
            ],
            "events": {
                "RegisterEmpty": [["Pending", "ReceivedEmpty"]],
                "RegisterTiny": [["Pending", "ReceivedTiny"]],
                "RegisterSmall": [["Pending", "ReceivedSmall"]],
                "RegisterNormal": [["Pending", "ReceivedNormal"]],
                "Download": [
                    ["ReceivedEmpty", "Running"], ["ReceivedTiny", "Running"],
                    ["ReceivedSmall", "Running"], ["ReceivedNormal", "Running"],
                ],
                "DownloadBackToSource": [
                    ["ReceivedEmpty", "BackToSource"],
                    ["ReceivedTiny", "BackToSource"],
                    ["ReceivedSmall", "BackToSource"],
                    ["ReceivedNormal", "BackToSource"],
                    ["Running", "BackToSource"],
                ],
                "DownloadSucceeded": [
                    ["ReceivedEmpty", "Succeeded"], ["ReceivedTiny", "Succeeded"],
                    ["ReceivedSmall", "Succeeded"],
                    ["ReceivedNormal", "Succeeded"], ["Running", "Succeeded"],
                    ["BackToSource", "Succeeded"],
                ],
                "DownloadFailed": [
                    ["Pending", "Failed"], ["ReceivedEmpty", "Failed"],
                    ["ReceivedTiny", "Failed"], ["ReceivedSmall", "Failed"],
                    ["ReceivedNormal", "Failed"], ["Running", "Failed"],
                    ["BackToSource", "Failed"], ["Succeeded", "Failed"],
                ],
                "Leave": [
                    ["Pending", "Leave"], ["ReceivedEmpty", "Leave"],
                    ["ReceivedTiny", "Leave"], ["ReceivedSmall", "Leave"],
                    ["ReceivedNormal", "Leave"], ["Running", "Leave"],
                    ["BackToSource", "Leave"], ["Failed", "Leave"],
                    ["Succeeded", "Leave"],
                ],
            },
            # Lock-free serving mirrors (DESIGN.md §18): written ONLY at
            # construction and inside the FSM's enter_state callback.
            "mirrors": {
                "fsm_state": ["Peer.__init__", "Peer._mirror_fsm"],
                "fsm_elevated": ["Peer.__init__", "Peer._mirror_fsm"],
            },
            # Wire-mirror peers (client-side stand-ins for remote state)
            # may force-set; nothing else calls fsm.set_state.
            "set_state_modules": ["dragonfly2_tpu/rpc/scheduler_client.py"],
        },
        # -- scheduler task lifecycle (task.go:57-85) -----------------------
        "task": {
            "kind": "fsm",
            "file": "dragonfly2_tpu/scheduler/resource.py",
            "class": "Task",
            "attr": "fsm",
            "events_var": "TASK_EVENTS",
            "initial": "Pending",
            "states": ["Pending", "Running", "Succeeded", "Failed", "Leave"],
            "events": {
                "Download": [
                    ["Pending", "Running"], ["Succeeded", "Running"],
                    ["Failed", "Running"], ["Leave", "Running"],
                ],
                "DownloadSucceeded": [
                    ["Leave", "Succeeded"], ["Running", "Succeeded"],
                    ["Failed", "Succeeded"],
                ],
                "DownloadFailed": [["Running", "Failed"]],
                "Leave": [
                    ["Pending", "Leave"], ["Running", "Leave"],
                    ["Succeeded", "Leave"], ["Failed", "Leave"],
                ],
            },
            "mirrors": {},
            "set_state_modules": [],
        },
        # -- model version lifecycle (manager registry + rollout plane) -----
        "model_state": {
            "kind": "enum",
            "file": "dragonfly2_tpu/manager/registry.py",
            "enum": "ModelState",
            "owner_class": "Model",
            "state_attr": "state",
            # Direct `.state = ModelState.X` writes are legal ONLY here —
            # every other module must go through the registry gateways.
            "owner_modules": ["dragonfly2_tpu/manager/registry.py"],
            "states": ["active", "inactive", "shadow", "canary"],
            "edges": [
                ["inactive", "active"],    # activate (operator / promote)
                ["active", "inactive"],    # demotion half of the flip
                ["inactive", "shadow"],    # rollout begin
                ["shadow", "canary"],      # rollout advance
                ["shadow", "inactive"],    # rollback / displaced candidate
                ["canary", "active"],      # rollout promote
                ["canary", "inactive"],    # rollback / displaced candidate
            ],
            # Gateway calls (`registry.set_state(id, ModelState.X)` /
            # `registry.activate/deactivate`): which module may request
            # which target state.  The receiver is recognized by type
            # (ModelRegistry) or by the declared gateway attribute name.
            "gateway_attrs": ["registry"],
            "mutators": {
                "dragonfly2_tpu/manager/registry.py": [
                    "active", "inactive", "shadow", "canary",
                ],
                "dragonfly2_tpu/rollout/controller.py": [
                    "active", "inactive", "shadow", "canary",
                ],
                "dragonfly2_tpu/manager/rest.py": ["active", "inactive"],
                "dragonfly2_tpu/rpc/grpc_transport.py": ["active", "inactive"],
            },
        },
        # -- rollout phase machine (rollout/controller.py) ------------------
        "rollout_phase": {
            "kind": "enum",
            "file": "dragonfly2_tpu/rollout/controller.py",
            "enum": "RolloutPhase",
            "owner_class": "Rollout",
            "state_attr": "phase",
            "owner_modules": ["dragonfly2_tpu/rollout/controller.py"],
            "states": ["shadow", "canary", "active", "rolled_back"],
            "edges": [
                ["shadow", "canary"],
                ["canary", "active"],
                ["shadow", "rolled_back"],
                ["canary", "rolled_back"],
                ["active", "rolled_back"],
            ],
            "gateway_attrs": [],
            "mutators": {},
        },
    },
    "persistence": {
        "namespaces": {
            "models": {
                "owner": "dragonfly2_tpu/manager/registry.py",
                "lock": ["dragonfly2_tpu/manager/registry.py",
                         "ModelRegistry", "_mu"],
                "loader": "ModelRegistry.__init__",
                # The single-ACTIVE flip touches two rows; a crash
                # between separate commits would leave two ACTIVEs.
                "multi_row": ["ModelRegistry._persist"],
                "unlocked_ok": ["migrate_legacy_sqlite"],
                "invariant": "single_active",
            },
            "rollouts": {
                "owner": "dragonfly2_tpu/rollout/controller.py",
                "lock": ["dragonfly2_tpu/rollout/controller.py",
                         "RolloutController", "_mu"],
                "loader": "RolloutController.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "no_dangling_rollout",
            },
            "jobs": {
                "owner": "dragonfly2_tpu/jobs/queue.py",
                "lock": ["dragonfly2_tpu/jobs/queue.py", "JobQueue", "_mu"],
                "loader": "JobQueue._reload",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "jobs_absent_or_complete",
            },
            "job_groups": {
                "owner": "dragonfly2_tpu/jobs/queue.py",
                "lock": ["dragonfly2_tpu/jobs/queue.py", "JobQueue", "_mu"],
                "loader": "JobQueue._reload",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "jobs_absent_or_complete",
            },
            "users": {
                "owner": "dragonfly2_tpu/manager/users.py",
                "lock": ["dragonfly2_tpu/manager/users.py", "UserStore", "_mu"],
                "loader": "_BackendUserStore.load_all",
                "multi_row": [],
                "unlocked_ok": ["migrate_legacy_sqlite"],
                "invariant": "rows_load",
            },
            "pats": {
                "owner": "dragonfly2_tpu/manager/users.py",
                "lock": ["dragonfly2_tpu/manager/users.py", "UserStore", "_mu"],
                "loader": "_BackendUserStore.load_all",
                "multi_row": [],
                "unlocked_ok": ["migrate_legacy_sqlite"],
                "invariant": "rows_load",
            },
            "crud": {
                "owner": "dragonfly2_tpu/manager/crud.py",
                "lock": ["dragonfly2_tpu/manager/crud.py", "CrudStore", "_mu"],
                "loader": "CrudStore.__init__",
                "multi_row": [],
                "unlocked_ok": ["migrate_legacy_sqlite"],
                "invariant": "rows_load",
            },
            "topology": {
                "owner": "dragonfly2_tpu/manager/rest.py",
                "lock": ["dragonfly2_tpu/manager/rest.py",
                         "ManagerRESTServer", "_topology_mu"],
                "loader": "ManagerRESTServer.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "rows_load",
            },
            # Replicated artifact bytes (manager HA): one row per blob,
            # riding the same log as the registry rows they back.  No
            # lock of its own: put() is reached only from
            # ModelRegistry.create_model under ModelRegistry._mu (the
            # single writer), declared unlocked_ok accordingly.
            "blobs": {
                "owner": "dragonfly2_tpu/manager/registry.py",
                "lock": ["dragonfly2_tpu/manager/registry.py",
                         "ModelRegistry", "_mu"],
                "loader": "KVBlobStore.__init__",
                "multi_row": [],
                "unlocked_ok": ["KVBlobStore.put"],
                "invariant": "rows_load",
            },
            # Manager-HA write-ahead op log + (term, applied) watermark
            # (manager/replication.py, DESIGN.md §20).
            # ReplicationLog is owned by ONE ReplicatedStateBackend and
            # every mutator runs under that backend's commit lock (log
            # order IS commit order) — the declared lock reflects that.
            "replication_log": {
                "owner": "dragonfly2_tpu/manager/replication.py",
                "lock": ["dragonfly2_tpu/manager/replication.py",
                         "ReplicatedStateBackend", "_mu"],
                "loader": "ReplicationLog.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "log_monotonic",
            },
            "replication_meta": {
                "owner": "dragonfly2_tpu/manager/replication.py",
                "lock": ["dragonfly2_tpu/manager/replication.py",
                         "ReplicatedStateBackend", "_mu"],
                "loader": "ReplicationLog.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "rows_load",
            },
            # Sharded-fleet membership (scheduler/sharding.py, DESIGN.md
            # §24): one row per cluster — {version, members} — written on
            # membership change under the directory lock; on the
            # replicated backend the ring version survives a leader
            # bounce, so a promoted standby publishes ring continuity
            # instead of re-handing-off the whole fleet.
            "shard_membership": {
                "owner": "dragonfly2_tpu/scheduler/sharding.py",
                "lock": ["dragonfly2_tpu/scheduler/sharding.py",
                         "ShardDirectory", "_mu"],
                "loader": "ShardDirectory.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "rows_load",
            },
            # Lifecycle plane progress (lifecycle/state.py, DESIGN.md
            # §29): one row per model key — epoch counter, ingest
            # watermark, in-flight candidate identity, bounded decision
            # history — so a manager bounce mid-promotion resumes the
            # train→export→rollout loop instead of restarting it.
            "lifecycle": {
                "owner": "dragonfly2_tpu/lifecycle/state.py",
                "lock": ["dragonfly2_tpu/lifecycle/state.py",
                         "LifecycleStore", "_mu"],
                "loader": "LifecycleStore.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "rows_load",
            },
        },
        # Dynamic-namespace write paths: functions that legitimately
        # write ANY declared namespace through a variable ``.table(ns)``
        # binding — the replication layer's leader commit / follower
        # apply / snapshot paths and the one-transaction legacy
        # migration.  DF014 indexes their full spans as wildcard sites
        # so the runtime crash witness can attribute their writes, and
        # fails by name when an entry goes stale.
        "replicators": {
            "dragonfly2_tpu/manager/replication.py": [
                "_ReplicatedTable.put",
                "_ReplicatedTable.put_many",
                "_ReplicatedTable.delete",
                "ReplicatedStateBackend._apply_entry_locked",
                "ReplicatedStateBackend.apply_snapshot",
            ],
            "dragonfly2_tpu/manager/state.py": [
                "StateBackend.put_namespaces",
            ],
        },
        # A crash between the two writes must leave the REFERENCING row
        # absent (recoverable), never dangling: the job row commits
        # before the group row that names its id.
        "write_order": [["jobs", "job_groups"]],
        "foreign_keys": [
            {
                # Deleting a model must not strand its rollout row: the
                # controller's delete_model is the only legal entry.
                "parent": "models",
                "child": "rollouts",
                "primitive": "ModelRegistry.delete",
                "cleanup": "RolloutController.delete_model",
                "cleanup_file": "dragonfly2_tpu/rollout/controller.py",
            },
        ],
        "implementation": ["dragonfly2_tpu/manager/state.py"],
    },
    "rpc": {
        "scheduler": {
            "clients": {
                "dragonfly2_tpu/rpc/scheduler_client.py": ["RemoteScheduler"],
            },
            "server": ["dragonfly2_tpu/rpc/scheduler_server.py",
                       "SchedulerRPCAdapter", "METHODS"],
            "grpc": ["dragonfly2_tpu/rpc/grpc_transport.py",
                     "SCHEDULER_METHODS"],
            # Blind-retry-safe: the handler is an absolute upsert, a
            # first-writer-wins guard, or a pure read.
            "idempotent": [
                "announce_host", "set_task_info", "set_task_direct_piece",
                "sync_probes_start", "sync_probes_finished",
                "report_piece_failed", "topology_rtt",
            ],
            # Retried non-idempotent methods carry a named server-side
            # dedup seam (verified to exist by DF015).
            "deduped": {
                "register_peer": "SchedulerService.register_peer",
                "report_piece_finished": "Peer.finish_piece",
                # The batch is N singles server-side: the same per-piece
                # finish_piece dedup absorbs a blind-retried batch.
                "report_pieces_finished": "Peer.finish_piece",
                "report_peer_finished": "_try_event",
                "report_peer_failed": "_try_event",
                "mark_back_to_source": "_try_event",
                "leave_peer": "_try_event",
            },
            "seam_files": ["dragonfly2_tpu/scheduler/service.py",
                           "dragonfly2_tpu/scheduler/resource.py"],
        },
    },
}
