"""Buffered, size-rotated training-record store (reference: scheduler/storage/storage.go).

Same lifecycle as the reference's CSV store — in-memory buffer flushed at
``buffer_size`` records (storage.go:139-203), active file rotated once it
exceeds ``max_size`` with at most ``max_backups`` retained (storage.go:255+)
— but each logical record is written twice:

- ``<base>.jsonl``   full-fidelity record (audit / replay / re-featurize),
  the analog of the reference's CSV row;
- ``<base>.dfc``     featurized fixed-width float32 rows (columnar.py),
  which is what the trainer actually ingests.

``CreateDownload`` / ``CreateNetworkTopology`` mirror the reference's
Storage interface (storage.go:58-89); ``open_downloads()`` etc. hand the
shard list to the announcer for upload to the trainer.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from . import schema
from .columnar import ColumnarWriter
from .features import DOWNLOAD_COLUMNS, TOPO_COLUMNS, download_to_rows, topology_to_rows

DOWNLOAD_BASE = "download"
NETWORK_TOPOLOGY_BASE = "networktopology"

DEFAULT_BUFFER_SIZE = 100          # records buffered before flush
DEFAULT_MAX_SIZE = 100 << 20       # bytes before rotation
DEFAULT_MAX_BACKUPS = 10


def _make_columnar_writer(path: str, columns: Sequence[str]):
    """C++ engine when buildable, Python otherwise — same DFC1 format, so
    readers never care which wrote the shard (native/src/native.cpp)."""
    from .. import native

    if native.available():
        try:
            return native.NativeColumnarWriter(path, columns)
        except native.NativeError:
            pass
    return ColumnarWriter(path, columns)


class _RotatingRecordFile:
    def __init__(
        self,
        directory: str,
        base: str,
        columns: Sequence[str],
        featurize: Callable[[object], np.ndarray],
        buffer_size: int,
        max_size: int,
        max_backups: int,
    ) -> None:
        self._dir = directory
        self._base = base
        self._columns = columns
        self._featurize = featurize
        self._buffer_size = buffer_size
        self._max_size = max_size
        self._max_backups = max_backups
        self._mu = threading.Lock()
        self._buffer: List[dict] = []
        self._count = 0
        os.makedirs(directory, exist_ok=True)

    @property
    def _jsonl_path(self) -> str:
        return os.path.join(self._dir, f"{self._base}.jsonl")

    @property
    def _dfc_path(self) -> str:
        return os.path.join(self._dir, f"{self._base}.dfc")

    def create(self, record) -> None:
        with self._mu:
            self._buffer.append(record)
            self._count += 1
            if len(self._buffer) >= self._buffer_size:
                self._flush_locked()

    def flush(self) -> None:
        with self._mu:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buffer:
            return
        records, self._buffer = self._buffer, []
        with open(self._jsonl_path, "a") as f:
            for r in records:
                f.write(json.dumps(schema.to_dict(r), separators=(",", ":")))
                f.write("\n")
        rows = [self._featurize(r) for r in records]
        rows = [r for r in rows if r.shape[0] > 0]
        if rows:
            with _make_columnar_writer(self._dfc_path, self._columns) as w:
                w.append(np.concatenate(rows, axis=0))
        if os.path.getsize(self._jsonl_path) >= self._max_size:
            self._rotate_locked()

    def _rotate_locked(self) -> None:
        # Shift backups: base.N -> base.N+1, drop the oldest beyond max_backups.
        for ext in (".jsonl", ".dfc"):
            oldest = os.path.join(self._dir, f"{self._base}.{self._max_backups}{ext}")
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self._max_backups - 1, 0, -1):
                src = os.path.join(self._dir, f"{self._base}.{i}{ext}")
                if os.path.exists(src):
                    os.replace(src, os.path.join(self._dir, f"{self._base}.{i + 1}{ext}"))
            active = os.path.join(self._dir, f"{self._base}{ext}")
            if os.path.exists(active):
                os.replace(active, os.path.join(self._dir, f"{self._base}.1{ext}"))

    def shard_paths(self, ext: str) -> List[str]:
        """Active + backup files, newest first."""
        paths = []
        active = os.path.join(self._dir, f"{self._base}{ext}")
        if os.path.exists(active):
            paths.append(active)
        for i in range(1, self._max_backups + 1):
            p = os.path.join(self._dir, f"{self._base}.{i}{ext}")
            if os.path.exists(p):
                paths.append(p)
        return paths

    def iter_records(self, cls: type) -> Iterator[object]:
        self.flush()
        for path in self.shard_paths(".jsonl"):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield schema.from_dict(cls, json.loads(line))

    def clear(self) -> None:
        with self._mu:
            self._buffer.clear()
            for ext in (".jsonl", ".dfc"):
                for p in self.shard_paths(ext):
                    os.remove(p)

    @property
    def count(self) -> int:
        return self._count


class Storage:
    """Scheduler-side training record store (reference Storage iface, storage.go:58-89)."""

    def __init__(
        self,
        directory: str,
        buffer_size: int = DEFAULT_BUFFER_SIZE,
        max_size: int = DEFAULT_MAX_SIZE,
        max_backups: int = DEFAULT_MAX_BACKUPS,
    ) -> None:
        self.directory = directory
        self._download = _RotatingRecordFile(
            directory, DOWNLOAD_BASE, DOWNLOAD_COLUMNS, download_to_rows,
            buffer_size, max_size, max_backups,
        )
        self._topology = _RotatingRecordFile(
            directory, NETWORK_TOPOLOGY_BASE, TOPO_COLUMNS, topology_to_rows,
            buffer_size, max_size, max_backups,
        )

    # -- writes (hot path, called by the scheduler service) ------------------

    def create_download(self, record: schema.Download) -> None:
        self._download.create(record)

    def create_network_topology(self, record: schema.NetworkTopologyRecord) -> None:
        self._topology.create(record)

    def flush(self) -> None:
        self._download.flush()
        self._topology.flush()

    # -- reads (announcer upload + trainer local mode) -----------------------

    def list_download(self) -> List[schema.Download]:
        return list(self._download.iter_records(schema.Download))

    def list_network_topology(self) -> List[schema.NetworkTopologyRecord]:
        return list(self._topology.iter_records(schema.NetworkTopologyRecord))

    def download_columnar_paths(self) -> List[str]:
        self._download.flush()
        return self._download.shard_paths(".dfc")

    def network_topology_columnar_paths(self) -> List[str]:
        self._topology.flush()
        return self._topology.shard_paths(".dfc")

    def download_raw_paths(self) -> List[str]:
        self._download.flush()
        return self._download.shard_paths(".jsonl")

    def network_topology_raw_paths(self) -> List[str]:
        self._topology.flush()
        return self._topology.shard_paths(".jsonl")

    def clear_download(self) -> None:
        self._download.clear()

    def clear_network_topology(self) -> None:
        self._topology.clear()

    def clear(self) -> None:
        self.clear_download()
        self.clear_network_topology()

    @property
    def download_count(self) -> int:
        return self._download.count

    @property
    def network_topology_count(self) -> int:
        return self._topology.count
