"""The manager's durable-state seam (VERDICT r4 #5).

Reference: the manager spreads durable state across MySQL/Postgres +
Redis, each independently replaceable (manager/database/database.go:
50-59).  Here EVERY durable manager surface — model registry rows, CRUD
rows, users/PATs, the job broker, the shared topology cache — persists
through this one interface:

    StateBackend.table(namespace) -> KVTable (put/put_many/get/delete/
                                     load_all; put_many is atomic)

``SQLiteBackend`` is the embedded implementation (one file, one
physical table, WAL); ``MemoryBackend`` the ephemeral one.  An external
KV/SQL (the HA story) implements the same two classes — consumers never
see a connection, a dialect, or a file path.  ``make_state_backend``
maps a config string to a backend the way the reference's database.New
dispatches on its config (mysql/postgres).

Crash-safety contract consumers rely on (exercised by the
kill-the-manager-mid-preheat drill in tests/test_manager_recovery.py):
every committed ``put``/``put_many`` survives a SIGKILL; a torn write
never surfaces (sqlite journaling); ``load_all`` after restart returns
exactly the committed rows.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from ..utils import faultinject


class KVTable:
    """One namespace of JSON documents keyed by string."""

    def put(self, key: str, doc: dict) -> None:
        raise NotImplementedError

    def put_many(self, items: Dict[str, dict]) -> None:
        """All rows in ONE transaction — multi-row invariants (e.g. the
        registry's single-active flip) must not tear across a crash."""
        raise NotImplementedError

    def get(self, key: str) -> Optional[dict]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def load_all(self) -> Dict[str, dict]:
        raise NotImplementedError

    def load_range(self, start_key: str) -> Dict[str, dict]:
        """Rows with key > ``start_key`` (lexicographic).  Base form
        filters ``load_all``; the concrete tables override with direct
        range forms — the replication log's per-poll tail read must not
        scan (and copy) the whole table forever (manager/replication.py)."""
        return {k: v for k, v in self.load_all().items() if k > start_key}

    def delete_range(self, end_key: str) -> None:
        """Delete rows with key < ``end_key`` (log compaction)."""
        for k in self.load_all():
            if k < end_key:
                self.delete(k)


class StateBackend:
    def table(self, namespace: str) -> KVTable:
        raise NotImplementedError

    def namespaces(self) -> List[str]:
        """Every namespace holding rows — the replication layer's
        snapshot enumeration (manager/replication.py)."""
        raise NotImplementedError

    def put_namespaces(self, staged: Dict[str, Dict[str, dict]]) -> None:
        """Commit rows across namespaces; the base form is per-table
        transactions, SQLite overrides with ONE transaction so a crash
        mid-migration leaves nothing (migrate_legacy_sqlite's contract)."""
        for ns, rows in staged.items():
            if rows:
                self.table(ns).put_many(rows)

    def close(self) -> None:  # pragma: no cover - trivial default
        pass


# ---------------------------------------------------------------------------
# In-memory (tests / embedded runs)
# ---------------------------------------------------------------------------


class _MemTable(KVTable):
    def __init__(self, ns: str = "") -> None:
        self._ns = ns
        self._rows: Dict[str, dict] = {}
        self._mu = threading.Lock()

    def put(self, key: str, doc: dict) -> None:
        faultinject.fire(f"state.put.{self._ns}")
        with self._mu:
            self._rows[key] = json.loads(json.dumps(doc))  # force-serializable

    def put_many(self, items: Dict[str, dict]) -> None:
        faultinject.fire(f"state.put.{self._ns}")
        with self._mu:
            for k, v in items.items():
                self._rows[k] = json.loads(json.dumps(v))

    def get(self, key: str) -> Optional[dict]:
        faultinject.fire(f"state.get.{self._ns}")
        with self._mu:
            row = self._rows.get(key)
            return json.loads(json.dumps(row)) if row is not None else None

    def delete(self, key: str) -> None:
        faultinject.fire(f"state.delete.{self._ns}")
        with self._mu:
            self._rows.pop(key, None)

    def load_all(self) -> Dict[str, dict]:
        faultinject.fire(f"state.load_all.{self._ns}")
        with self._mu:
            return json.loads(json.dumps(self._rows))

    def load_range(self, start_key: str) -> Dict[str, dict]:
        faultinject.fire(f"state.load_all.{self._ns}")
        with self._mu:
            return {
                k: json.loads(json.dumps(v))
                for k, v in self._rows.items() if k > start_key
            }

    def delete_range(self, end_key: str) -> None:
        # Direct row mutation (not a self.delete loop): bulk log
        # compaction is backend maintenance, not a consumer write — it
        # must not surface per-row in the crash-witness inventory.
        faultinject.fire(f"state.delete.{self._ns}")
        with self._mu:
            for k in [k for k in self._rows if k < end_key]:
                del self._rows[k]


class MemoryBackend(StateBackend):
    def __init__(self) -> None:
        self._tables: Dict[str, _MemTable] = {}
        self._mu = threading.Lock()

    def table(self, namespace: str) -> KVTable:
        with self._mu:
            if namespace not in self._tables:
                self._tables[namespace] = _MemTable(namespace)
            return self._tables[namespace]

    def namespaces(self) -> List[str]:
        with self._mu:
            return sorted(self._tables)


# ---------------------------------------------------------------------------
# SQLite (the embedded durable backend)
# ---------------------------------------------------------------------------


class _SQLiteTable(KVTable):
    def __init__(self, backend: "SQLiteBackend", ns: str) -> None:
        self._b = backend
        self._ns = ns

    def put(self, key: str, doc: dict) -> None:
        self.put_many({key: doc})

    def put_many(self, items: Dict[str, dict]) -> None:
        # Chaos seam BEFORE the transaction: an injected failure means
        # the commit never happened — the atomicity contract holds.
        faultinject.fire(f"state.put.{self._ns}")
        rows = [(self._ns, k, json.dumps(v)) for k, v in items.items()]
        with self._b._mu:
            self._b._conn.executemany(
                "INSERT OR REPLACE INTO kv (ns, key, value) VALUES (?,?,?)",
                rows,
            )
            self._b._conn.commit()

    def get(self, key: str) -> Optional[dict]:
        faultinject.fire(f"state.get.{self._ns}")
        with self._b._mu:
            row = self._b._conn.execute(
                "SELECT value FROM kv WHERE ns=? AND key=?", (self._ns, key)
            ).fetchone()
        return json.loads(row[0]) if row else None

    def delete(self, key: str) -> None:
        faultinject.fire(f"state.delete.{self._ns}")
        with self._b._mu:
            self._b._conn.execute(
                "DELETE FROM kv WHERE ns=? AND key=?", (self._ns, key)
            )
            self._b._conn.commit()

    def load_all(self) -> Dict[str, dict]:
        faultinject.fire(f"state.load_all.{self._ns}")
        with self._b._mu:
            rows = self._b._conn.execute(
                "SELECT key, value FROM kv WHERE ns=?", (self._ns,)
            ).fetchall()
        return {k: json.loads(v) for k, v in rows}

    def load_range(self, start_key: str) -> Dict[str, dict]:
        faultinject.fire(f"state.load_all.{self._ns}")
        with self._b._mu:
            rows = self._b._conn.execute(
                "SELECT key, value FROM kv WHERE ns=? AND key>?",
                (self._ns, start_key),
            ).fetchall()
        return {k: json.loads(v) for k, v in rows}

    def delete_range(self, end_key: str) -> None:
        faultinject.fire(f"state.delete.{self._ns}")
        with self._b._mu:
            self._b._conn.execute(
                "DELETE FROM kv WHERE ns=? AND key<?", (self._ns, end_key)
            )
            self._b._conn.commit()


class SQLiteBackend(StateBackend):
    """One file for ALL manager state: a restart (or a crash) reloads
    everything from the same place, and swapping the HA backend swaps
    everything at once rather than chasing five files."""

    def __init__(self, path: str, *, busy_timeout_ms: int = 5000) -> None:
        import sqlite3

        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._mu = threading.Lock()
        self._closed = False
        with self._mu:
            # WAL: a reader (console listing jobs) must not block the
            # write path, and fsync'd commits survive SIGKILL.
            self._conn.execute("PRAGMA journal_mode=WAL")
            # A second connection on the same file (a replication-role
            # sidecar, an ops shell) must wait out a writer's commit,
            # not throw "database is locked" into the manager hot path.
            self._conn.execute(f"PRAGMA busy_timeout={int(busy_timeout_ms)}")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                "ns TEXT NOT NULL, key TEXT NOT NULL, value TEXT NOT NULL, "
                "PRIMARY KEY (ns, key))"
            )
            self._conn.commit()

    def table(self, namespace: str) -> KVTable:
        return _SQLiteTable(self, namespace)

    def namespaces(self) -> List[str]:
        with self._mu:
            rows = self._conn.execute("SELECT DISTINCT ns FROM kv").fetchall()
        return sorted(r[0] for r in rows)

    def put_namespaces(self, staged: Dict[str, Dict[str, dict]]) -> None:
        """All namespaces' rows in ONE transaction: a crash mid-way
        commits nothing — a partial legacy migration must never pass
        the crash witness as a complete one."""
        for ns in staged:
            faultinject.fire(f"state.put.{ns}")
        rows = [
            (ns, k, json.dumps(v))
            for ns, docs in staged.items()
            for k, v in docs.items()
        ]
        with self._mu:
            self._conn.executemany(
                "INSERT OR REPLACE INTO kv (ns, key, value) VALUES (?,?,?)",
                rows,
            )
            self._conn.commit()

    def close(self) -> None:
        # Idempotent: the replication role shares one backend between
        # the REST composition and the follower; both shut it down.
        with self._mu:
            if self._closed:
                return
            self._closed = True
            self._conn.close()


def make_state_backend(spec: Optional[str]) -> StateBackend:
    """Config string → backend: None/'' or 'mem://' → MemoryBackend;
    anything else is a sqlite file path.  An external backend plugs in
    here (the reference's database.New dispatch, database.go:50-59)."""
    if not spec or spec == "mem://":
        return MemoryBackend()
    return SQLiteBackend(spec)


def migrate_legacy_sqlite(
    backend: StateBackend,
    *,
    models_db: Optional[str] = None,
    crud_db: Optional[str] = None,
    users_db: Optional[str] = None,
) -> Dict[str, int]:
    """One-time import of the pre-seam sqlite layouts (per-store files
    with typed tables) into the unified kv backend.  Runs at manager
    boot; a namespace that already has rows is never touched, so this is
    idempotent and a no-op on fresh or already-migrated deployments.
    Legacy files are left in place (read-only safety net).  Returns
    per-namespace imported-row counts.

    Every namespace's rows land in ONE transaction
    (``StateBackend.put_namespaces``; SQLite commits them atomically):
    a crash mid-migration must leave the backend looking unmigrated —
    the next boot re-imports — never half-imported, which would make the
    already-has-rows idempotency check skip the missing half forever."""
    import base64
    import sqlite3

    def rows(path: Optional[str], query: str):
        if not path or not os.path.exists(path):
            return []
        try:
            conn = sqlite3.connect(path)
            try:
                return conn.execute(query).fetchall()
            finally:
                conn.close()
        except sqlite3.Error:
            return []  # no such table / not a legacy layout

    staged: Dict[str, Dict[str, dict]] = {}

    if not backend.table("models").load_all():
        found = rows(
            models_db,
            "SELECT id,name,type,version,scheduler_id,state,evaluation,"
            "blob_key,created_at,updated_at FROM models",
        )
        if found:
            staged["models"] = {
                r[0]: {
                    "id": r[0], "name": r[1], "type": r[2], "version": r[3],
                    "scheduler_id": r[4], "state": r[5],
                    "evaluation": json.loads(r[6]), "blob_key": r[7],
                    "created_at": r[8], "updated_at": r[9],
                }
                for r in found
            }

    if not backend.table("crud").load_all():
        found = rows(crud_db, "SELECT kind,id,value FROM crud_rows")
        if found:
            staged["crud"] = {
                f"{kind}:{id_}": json.loads(value)
                for kind, id_, value in found
            }

    if not backend.table("users").load_all():
        found = rows(
            users_db,
            "SELECT id,name,email,role,state,password_hash,salt,created_at "
            "FROM users",
        )
        if found:
            staged["users"] = {
                r[0]: {
                    "id": r[0], "name": r[1], "email": r[2],
                    "role": int(r[3]), "state": r[4],
                    "password_hash": base64.b64encode(r[5]).decode(),
                    "salt": base64.b64encode(r[6]).decode(),
                    "created_at": r[7],
                }
                for r in found
            }

    if not backend.table("pats").load_all():
        found = rows(
            users_db,
            "SELECT id,user_id,name,role,token_hash,expires_at,revoked,"
            "created_at FROM pats",
        )
        if found:
            staged["pats"] = {
                r[0]: {
                    "id": r[0], "user_id": r[1], "name": r[2],
                    "role": int(r[3]), "token_hash": r[4],
                    "expires_at": r[5], "revoked": bool(r[6]),
                    "created_at": r[7],
                }
                for r in found
            }

    if staged:
        backend.put_namespaces(staged)
    return {ns: len(rows_) for ns, rows_ in staged.items()}
