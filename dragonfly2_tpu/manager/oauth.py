"""OAuth2 sign-in seam (reference: manager/models/oauth.go + handlers —
configurable oauth providers backing console sign-in).

Standard authorization-code flow with an injectable transport: the
manager redirects to the provider's authorize URL, exchanges the
callback code for an access token, fetches the profile, and maps it to
a local user (get-or-create by email, READONLY by default — an admin
raises roles afterwards).
"""

from __future__ import annotations

import json
import secrets
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..security.tokens import Role
from .users import User, UserStore


def _default_transport(req: urllib.request.Request, timeout: float):
    return urllib.request.urlopen(req, timeout=timeout)


class OAuthUnavailable(RuntimeError):
    """The IdP was unreachable or errored (5xx/timeout) — a TRANSIENT
    outcome: callers must keep refresh grants intact and retry, never
    treat it as a revocation."""


@dataclass
class OAuthProvider:
    """One configured provider (oauth.go rows: name, client id/secret,
    endpoints)."""

    name: str
    client_id: str
    client_secret: str
    auth_url: str
    token_url: str
    profile_url: str
    scopes: str = "openid email"


class OAuthSignin:
    def __init__(
        self,
        users: UserStore,
        *,
        timeout: float = 15.0,
        transport: Optional[Callable] = None,
    ) -> None:
        self.users = users
        self.timeout = timeout
        self.transport = transport or _default_transport
        self._providers: Dict[str, OAuthProvider] = {}
        # state → (provider name, issued_at).  The authorize-url endpoint
        # is unauthenticated: entries expire and the map is pruned so it
        # can't be grown without bound remotely.
        self._states: Dict[str, tuple] = {}
        self.state_ttl_s = 600.0
        # refresh handle → (provider, user_id, provider refresh token,
        # issued_at); see refresh().  Guarded by _grants_mu: the REST
        # server handles requests on concurrent threads, and two
        # refreshes racing the same handle must not BOTH redeem the
        # provider token (rotation-strict IdPs invalidate the grant
        # family on the second redemption).
        self._grants: Dict[str, tuple] = {}
        import threading

        self._grants_mu = threading.Lock()

    def register(self, provider: OAuthProvider) -> None:
        self._providers[provider.name] = provider

    def providers(self):
        return sorted(self._providers)

    def _prune_states(self) -> None:
        import time

        cutoff = time.time() - self.state_ttl_s
        for s in [s for s, (_, t) in self._states.items() if t < cutoff]:
            self._states.pop(s, None)

    def authorize_url(self, provider_name: str, redirect_uri: str) -> str:
        import time

        self._prune_states()
        p = self._providers[provider_name]
        state = secrets.token_urlsafe(16)
        self._states[state] = (p.name, time.time())
        return p.auth_url + "?" + urllib.parse.urlencode(
            {
                "client_id": p.client_id,
                "redirect_uri": redirect_uri,
                "response_type": "code",
                "scope": p.scopes,
                "state": state,
            }
        )

    def _token_request(self, p: OAuthProvider, grant: Dict[str, str]) -> dict:
        body = urllib.parse.urlencode(
            {
                "client_id": p.client_id,
                "client_secret": p.client_secret,
                **grant,
            }
        ).encode()
        req = urllib.request.Request(
            p.token_url, data=body,
            headers={"Accept": "application/json"}, method="POST",
        )
        try:
            with self.transport(req, self.timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            if exc.code in (400, 401, 403):
                # The IdP REJECTED the grant (invalid/revoked) — an auth
                # outcome.
                return {}
            # 5xx/429: the IdP is having a moment, the grant may well be
            # fine — transient, never destroy state over it.
            raise OAuthUnavailable(
                f"provider {p.name} returned HTTP {exc.code}"
            ) from exc
        except (urllib.error.URLError, TimeoutError, OSError) as exc:
            raise OAuthUnavailable(
                f"provider {p.name} unreachable: {exc}"
            ) from exc

    def _map_profile(self, p: OAuthProvider, access_token: str) -> User:
        req = urllib.request.Request(
            p.profile_url, headers={"Authorization": f"Bearer {access_token}"}
        )
        with self.transport(req, self.timeout) as resp:
            profile = json.loads(resp.read())
        email = profile.get("email") or ""
        login = profile.get("login") or profile.get("name") or email
        if not login:
            raise PermissionError("oauth profile has no usable identity")
        username = f"{p.name}:{login}"
        existing = self.users.by_name(username)
        if existing is not None:
            # Same gate verify_password applies: a disabled account must
            # not regain access through the OAuth door.
            if existing.state != "enabled":
                raise PermissionError(f"account {username!r} is disabled")
            return existing
        # OAuth users get an unguessable local password (they sign in
        # through the provider, not with it).
        return self.users.create_user(
            username, secrets.token_urlsafe(24), email=email,
            role=Role.READONLY,
        )

    def signin(
        self, provider_name: str, code: str, state: str, redirect_uri: str
    ) -> User:
        """Code exchange → profile fetch → local user (get-or-create).
        Stores NO refresh grant — a caller that discards the handle must
        not leave orphan grants evicting live users' under the cap."""
        return self.signin_with_refresh(
            provider_name, code, state, redirect_uri, store_grant=False
        )[0]

    def signin_with_refresh(
        self, provider_name: str, code: str, state: str, redirect_uri: str,
        *, store_grant: bool = True,
    ):
        """The full flow, keeping the provider's refresh grant: returns
        (user, refresh_id) — refresh_id is an opaque manager-side handle
        (the provider refresh token itself never leaves the manager) the
        console presents to ``refresh`` for a new session without an
        interactive authorize round-trip; None when the IdP issued no
        refresh token."""
        self._prune_states()
        entry = self._states.pop(state, None)
        if entry is None or entry[0] != provider_name:
            raise PermissionError("oauth state mismatch (CSRF)")
        p = self._providers[provider_name]
        tokens = self._token_request(p, {
            "code": code,
            "grant_type": "authorization_code",
            "redirect_uri": redirect_uri,
        })
        access = tokens.get("access_token", "")
        if not access:
            raise PermissionError("oauth code exchange failed")
        user = self._map_profile(p, access)
        refresh_id = None
        if store_grant and tokens.get("refresh_token"):
            refresh_id = self._store_grant(
                p.name, user.id, tokens["refresh_token"]
            )
        return user, refresh_id

    # -- refresh (oauth.go refresh-token semantics) -------------------------

    # Stored provider refresh grants, keyed by the opaque handle the
    # console holds.  Bounded two ways: a TTL (a browser that never came
    # back holds no live grant) and a hard cap with oldest-first
    # eviction.
    MAX_GRANTS = 10_000
    GRANT_TTL_S = 30 * 86_400.0

    def _store_grant(self, provider: str, user_id: str, refresh_token: str) -> str:
        import time

        now = time.time()
        with self._grants_mu:
            for rid_ in [
                r for r, (_, _, _, t) in self._grants.items()
                if now - t > self.GRANT_TTL_S
            ]:
                self._grants.pop(rid_, None)
            rid = secrets.token_urlsafe(24)
            self._grants[rid] = (provider, user_id, refresh_token, now)
            while len(self._grants) > self.MAX_GRANTS:
                self._grants.pop(next(iter(self._grants)))
        return rid

    def refresh(self, refresh_id: str):
        """Renew a session from the stored provider refresh token:
        re-validates the identity against the IdP (a token the provider
        revoked — or a deleted/disabled account — degrades to
        re-authentication, never to a silent session).  Rotates both the
        handle and, when the IdP sends one, the provider refresh token.
        Returns (user, new_refresh_id).

        The handle is SINGLE-USE: popped under the lock before the IdP
        call, restored only on transient (OAuthUnavailable) outcomes.
        A concurrent refresh with the same handle finds it gone and
        degrades to re-authentication — never a double redemption that
        a rotation-strict IdP would treat as token theft."""
        with self._grants_mu:
            entry = self._grants.pop(refresh_id, None)
        if entry is None:
            raise PermissionError("unknown refresh handle; re-authenticate")
        provider, user_id, refresh_token, issued = entry

        def restore(rt: str) -> None:
            # setdefault: never clobber state a concurrent signin/evict
            # wrote under this handle while we held the IdP call open.
            with self._grants_mu:
                self._grants.setdefault(
                    refresh_id, (provider, user_id, rt, issued)
                )

        p = self._providers.get(provider)
        if p is None:
            raise PermissionError(f"provider {provider!r} no longer configured")
        try:
            # May raise OAuthUnavailable — grant restored, caller retries.
            tokens = self._token_request(p, {
                "refresh_token": refresh_token,
                "grant_type": "refresh_token",
            })
        except OAuthUnavailable:
            restore(refresh_token)
            raise
        access = tokens.get("access_token", "")
        if not access:
            # The IdP rejected (revoked/expired) the grant: it stays
            # destroyed — the console falls back to the authorize flow.
            raise PermissionError(
                "oauth refresh rejected by provider; re-authenticate"
            )
        # The IdP may have ROTATED the refresh token: record it under the
        # old handle immediately, so a crash/transport failure below
        # cannot strand the only copy of the rotated token.
        new_rt = tokens.get("refresh_token") or refresh_token
        restore(new_rt)
        try:
            user = self._map_profile(p, access)
        except urllib.error.HTTPError as exc:
            # HTTPError ⊂ URLError: without this arm a persistent 401/403
            # from the profile endpoint (access revoked at the IdP while
            # refresh still mints tokens, or a misconfigured profile_url)
            # would classify as transient forever — the console looping
            # 503s instead of degrading to re-authentication.
            if exc.code in (401, 403):
                with self._grants_mu:
                    self._grants.pop(refresh_id, None)
                raise PermissionError(
                    f"profile endpoint rejected token (HTTP {exc.code}); "
                    "re-authenticate"
                ) from exc
            raise OAuthUnavailable(
                f"provider {provider} profile endpoint HTTP {exc.code}"
            ) from exc
        except (urllib.error.URLError, TimeoutError, OSError) as exc:
            raise OAuthUnavailable(
                f"provider {provider} unreachable: {exc}"
            ) from exc
        except PermissionError:
            with self._grants_mu:
                self._grants.pop(refresh_id, None)  # disabled account
            raise
        with self._grants_mu:
            self._grants.pop(refresh_id, None)
        new_rid = self._store_grant(provider, user.id, new_rt)
        return user, new_rid
