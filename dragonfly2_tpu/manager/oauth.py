"""OAuth2 sign-in seam (reference: manager/models/oauth.go + handlers —
configurable oauth providers backing console sign-in).

Standard authorization-code flow with an injectable transport: the
manager redirects to the provider's authorize URL, exchanges the
callback code for an access token, fetches the profile, and maps it to
a local user (get-or-create by email, READONLY by default — an admin
raises roles afterwards).
"""

from __future__ import annotations

import json
import secrets
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..security.tokens import Role
from .users import User, UserStore


def _default_transport(req: urllib.request.Request, timeout: float):
    return urllib.request.urlopen(req, timeout=timeout)


@dataclass
class OAuthProvider:
    """One configured provider (oauth.go rows: name, client id/secret,
    endpoints)."""

    name: str
    client_id: str
    client_secret: str
    auth_url: str
    token_url: str
    profile_url: str
    scopes: str = "openid email"


class OAuthSignin:
    def __init__(
        self,
        users: UserStore,
        *,
        timeout: float = 15.0,
        transport: Optional[Callable] = None,
    ) -> None:
        self.users = users
        self.timeout = timeout
        self.transport = transport or _default_transport
        self._providers: Dict[str, OAuthProvider] = {}
        # state → (provider name, issued_at).  The authorize-url endpoint
        # is unauthenticated: entries expire and the map is pruned so it
        # can't be grown without bound remotely.
        self._states: Dict[str, tuple] = {}
        self.state_ttl_s = 600.0

    def register(self, provider: OAuthProvider) -> None:
        self._providers[provider.name] = provider

    def providers(self):
        return sorted(self._providers)

    def _prune_states(self) -> None:
        import time

        cutoff = time.time() - self.state_ttl_s
        for s in [s for s, (_, t) in self._states.items() if t < cutoff]:
            self._states.pop(s, None)

    def authorize_url(self, provider_name: str, redirect_uri: str) -> str:
        import time

        self._prune_states()
        p = self._providers[provider_name]
        state = secrets.token_urlsafe(16)
        self._states[state] = (p.name, time.time())
        return p.auth_url + "?" + urllib.parse.urlencode(
            {
                "client_id": p.client_id,
                "redirect_uri": redirect_uri,
                "response_type": "code",
                "scope": p.scopes,
                "state": state,
            }
        )

    def signin(
        self, provider_name: str, code: str, state: str, redirect_uri: str
    ) -> User:
        """Code exchange → profile fetch → local user (get-or-create)."""
        self._prune_states()
        entry = self._states.pop(state, None)
        if entry is None or entry[0] != provider_name:
            raise PermissionError("oauth state mismatch (CSRF)")
        p = self._providers[provider_name]
        body = urllib.parse.urlencode(
            {
                "client_id": p.client_id,
                "client_secret": p.client_secret,
                "code": code,
                "grant_type": "authorization_code",
                "redirect_uri": redirect_uri,
            }
        ).encode()
        req = urllib.request.Request(
            p.token_url, data=body,
            headers={"Accept": "application/json"}, method="POST",
        )
        with self.transport(req, self.timeout) as resp:
            token = json.loads(resp.read()).get("access_token", "")
        if not token:
            raise PermissionError("oauth code exchange failed")
        req = urllib.request.Request(
            p.profile_url, headers={"Authorization": f"Bearer {token}"}
        )
        with self.transport(req, self.timeout) as resp:
            profile = json.loads(resp.read())
        email = profile.get("email") or ""
        login = profile.get("login") or profile.get("name") or email
        if not login:
            raise PermissionError("oauth profile has no usable identity")
        username = f"{p.name}:{login}"
        existing = self.users.by_name(username)
        if existing is not None:
            # Same gate verify_password applies: a disabled account must
            # not regain access through the OAuth door.
            if existing.state != "enabled":
                raise PermissionError(f"account {username!r} is disabled")
            return existing
        # OAuth users get an unguessable local password (they sign in
        # through the provider, not with it).
        return self.users.create_user(
            username, secrets.token_urlsafe(24), email=email,
            role=Role.READONLY,
        )
