"""Cluster membership + keepalive (reference: manager CRUD + KeepAlive).

Tracks scheduler and seed-peer instances per cluster with last-keepalive
timestamps; instances past the TTL are reported inactive, mirroring the
manager's keepalive stream liveness (manager_server_v2.go:749) and the
active-scheduler filtering the searcher depends on (searcher.go:146-152).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

DEFAULT_KEEPALIVE_TTL = 60.0


@dataclass
class SchedulerInstance:
    id: str
    cluster_id: str
    hostname: str = ""
    ip: str = ""
    port: int = 8002
    state: str = "active"
    last_keepalive: float = field(default_factory=time.time)


@dataclass
class SeedPeerInstance:
    id: str
    cluster_id: str
    hostname: str = ""
    ip: str = ""
    port: int = 8001
    type: str = "super"
    state: str = "active"
    last_keepalive: float = field(default_factory=time.time)


class ClusterManager:
    def __init__(self, keepalive_ttl: float = DEFAULT_KEEPALIVE_TTL) -> None:
        self._mu = threading.RLock()
        self.ttl = keepalive_ttl
        self._schedulers: Dict[str, SchedulerInstance] = {}
        self._seed_peers: Dict[str, SeedPeerInstance] = {}

    def register_scheduler(self, inst: SchedulerInstance) -> SchedulerInstance:
        with self._mu:
            existing = self._schedulers.get(inst.id)
            if existing is not None:
                existing.last_keepalive = time.time()
                existing.state = "active"
                return existing
            self._schedulers[inst.id] = inst
            return inst

    def register_seed_peer(self, inst: SeedPeerInstance) -> SeedPeerInstance:
        with self._mu:
            existing = self._seed_peers.get(inst.id)
            if existing is not None:
                existing.last_keepalive = time.time()
                existing.state = "active"
                return existing
            self._seed_peers[inst.id] = inst
            return inst

    def keepalive(self, instance_id: str) -> bool:
        with self._mu:
            inst = self._schedulers.get(instance_id) or self._seed_peers.get(instance_id)
            if inst is None:
                return False
            inst.last_keepalive = time.time()
            inst.state = "active"
            return True

    def _expire_locked(self) -> None:
        now = time.time()
        for inst in list(self._schedulers.values()) + list(self._seed_peers.values()):
            if now - inst.last_keepalive > self.ttl:
                inst.state = "inactive"

    def active_schedulers(self, cluster_id: Optional[str] = None) -> List[SchedulerInstance]:
        with self._mu:
            self._expire_locked()
            return [
                s
                for s in self._schedulers.values()
                if s.state == "active"
                and (cluster_id is None or s.cluster_id == cluster_id)
            ]

    def active_seed_peers(self, cluster_id: Optional[str] = None) -> List[SeedPeerInstance]:
        with self._mu:
            self._expire_locked()
            return [
                s
                for s in self._seed_peers.values()
                if s.state == "active"
                and (cluster_id is None or s.cluster_id == cluster_id)
            ]
