"""Manager REST API (reference: manager/router + handlers — the gin REST
surface the console drives; swagger'd CRUD for models/clusters/schedulers).

Minimal JSON binding over stdlib HTTP:

  GET    /api/v1/models?scheduler_id=&name=      list models
  POST   /api/v1/models/<id>:activate            single-active activation
  POST   /api/v1/models/<id>:deactivate
  GET    /api/v1/schedulers                      active scheduler instances
  GET    /api/v1/clusters:search?ip=&hostname=&idc=&location=
  GET    /api/v1/healthy                         liveness
"""

from __future__ import annotations

import base64
import json
import urllib.parse
from http.server import BaseHTTPRequestHandler
from typing import List, Optional, Tuple

from ..rpc._server import ThreadedHTTPService

from .cluster import ClusterManager
from .registry import Model, ModelRegistry
from .searcher import SchedulerCluster, Searcher


def _model_to_json(m: Model) -> dict:
    return {
        "id": m.id,
        "name": m.name,
        "type": m.type,
        "version": m.version,
        "scheduler_id": m.scheduler_id,
        "state": m.state.value,
        "evaluation": m.evaluation,
    }


class ManagerRESTServer:
    def __init__(
        self,
        registry: ModelRegistry,
        clusters: ClusterManager,
        searcher: Optional[Searcher] = None,
        scheduler_clusters: Optional[List[SchedulerCluster]] = None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        token_verifier=None,
    ):
        self.registry = registry
        self.clusters = clusters
        self.searcher = searcher or Searcher()
        self.scheduler_clusters = scheduler_clusters or []
        # Optional RBAC: with a verifier configured, mutations require a
        # bearer token of sufficient role (security/tokens.py); reads stay
        # open (matching the reference's authenticated-writes posture).
        self.token_verifier = token_verifier
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urllib.parse.urlsplit(self.path)
                q = dict(urllib.parse.parse_qsl(parsed.query))
                path = parsed.path
                if path == "/api/v1/healthy":
                    self._json(200, {"ok": True})
                elif path == "/api/v1/models":
                    models = server.registry.list(
                        scheduler_id=q.get("scheduler_id") or None,
                        name=q.get("name") or None,
                    )
                    self._json(200, [_model_to_json(m) for m in models])
                elif path == "/api/v1/models:active":
                    m = server.registry.active_model(
                        q.get("scheduler_id", ""), q.get("name", "")
                    )
                    if m is None:
                        self._json(404, {"error": "no active model"})
                    else:
                        self._json(200, _model_to_json(m))
                elif path == "/api/v1/models:artifact":
                    m = server.registry.get(q.get("id", ""))
                    if m is None:
                        self._json(404, {"error": "model not found"})
                    else:
                        try:
                            blob = server.registry.load_artifact(m)
                        except (KeyError, OSError) as exc:
                            # Row exists but the blob is gone (mismatched
                            # blob dir after restart) — a clean 404 beats a
                            # dead handler thread + connection reset.
                            self._json(404, {"error": f"artifact missing: {exc}"})
                            return
                        self._json(
                            200, {"artifact_b64": base64.b64encode(blob).decode()}
                        )
                elif path == "/api/v1/models:get":
                    m = server.registry.get(q.get("id", ""))
                    if m is None:
                        self._json(404, {"error": "model not found"})
                    else:
                        self._json(200, _model_to_json(m))
                elif path == "/api/v1/schedulers":
                    self._json(
                        200,
                        [
                            {
                                "id": s.id,
                                "cluster_id": s.cluster_id,
                                "ip": s.ip,
                                "port": s.port,
                                "state": s.state,
                            }
                            for s in server.clusters.active_schedulers()
                        ],
                    )
                elif path == "/api/v1/clusters:search":
                    try:
                        ranked = server.searcher.find_scheduler_clusters(
                            server.scheduler_clusters,
                            ip=q.get("ip", ""),
                            hostname=q.get("hostname", ""),
                            conditions={
                                "idc": q.get("idc", ""),
                                "location": q.get("location", ""),
                            },
                        )
                        self._json(200, [c.id for c in ranked])
                    except LookupError as exc:
                        self._json(404, {"error": str(exc)})
                else:
                    self._json(404, {"error": "not found"})

            def _authorized(self, required_role) -> bool:
                if server.token_verifier is None:
                    return True
                auth = self.headers.get("Authorization", "")
                token = auth[len("Bearer ") :] if auth.startswith("Bearer ") else None
                return server.token_verifier.authorize(token, required_role) is not None

            def do_POST(self):
                from ..security.tokens import Role

                path = urllib.parse.urlsplit(self.path).path
                # Role per route, declared at the route (tokens.py tiers):
                # model CREATION is the trainer's automated flow → PEER;
                # activation/deactivation are operator decisions.
                if path == "/api/v1/models":
                    required = Role.PEER
                elif path.endswith(":activate") or path.endswith(":deactivate"):
                    required = Role.OPERATOR
                else:
                    required = Role.ADMIN  # unknown mutations: locked down
                if not self._authorized(required):
                    self._json(401, {"error": "unauthorized"})
                    return
                if path == "/api/v1/models":
                    # CreateModel (reference: manager_server_v1.go:802).
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                        req = json.loads(self.rfile.read(length) or b"{}")
                        m = server.registry.create_model(
                            name=req["name"],
                            type=req["type"],
                            scheduler_id=req["scheduler_id"],
                            artifact=base64.b64decode(req.get("artifact_b64", "")),
                            evaluation=req.get("evaluation") or {},
                        )
                        self._json(200, _model_to_json(m))
                    except (KeyError, ValueError) as exc:
                        self._json(400, {"error": str(exc)})
                    return
                if path.startswith("/api/v1/models/") and ":" in path:
                    model_id, _, action = path[len("/api/v1/models/") :].rpartition(":")
                    try:
                        if action == "activate":
                            m = server.registry.activate(model_id)
                        elif action == "deactivate":
                            m = server.registry.deactivate(model_id)
                        else:
                            self._json(404, {"error": f"unknown action {action}"})
                            return
                        self._json(200, _model_to_json(m))
                    except KeyError:
                        self._json(404, {"error": f"model {model_id} not found"})
                    return
                self._json(404, {"error": "not found"})

        self._svc = ThreadedHTTPService(Handler, host, port, "manager-rest")
        self.address: Tuple[str, int] = self._svc.address

    @property
    def url(self) -> str:
        return self._svc.url

    def serve(self) -> None:
        self._svc.serve()

    def stop(self) -> None:
        self._svc.stop()
