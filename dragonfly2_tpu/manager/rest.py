"""Manager REST API (reference: manager/router + handlers — the gin REST
surface the console drives; swagger'd CRUD for models/clusters/schedulers).

Minimal JSON binding over stdlib HTTP:

  GET    /api/v1/models?scheduler_id=&name=      list models
  POST   /api/v1/models/<id>:activate            single-active activation
  POST   /api/v1/models/<id>:deactivate
  POST   /api/v1/models/<id>:rollout             begin evidence-gated rollout (OPERATOR)
  GET    /api/v1/models:candidate?scheduler_id=&name=   the SHADOW/CANARY candidate
  GET    /api/v1/rollouts                        rollout state machines
  GET    /api/v1/rollouts:get?scheduler_id=&name=
  POST   /api/v1/rollouts:report                 scheduler evaluation report (PEER)
  GET    /api/v1/schedulers                      active scheduler instances
  POST   /api/v1/schedulers                      register a scheduler instance
  POST   /api/v1/schedulers/<id>:keepalive       liveness tick → {known}
  GET    /api/v1/clusters:search?ip=&hostname=&idc=&location=
  GET    /api/v1/healthy                         liveness

CRUD resources (manager/handlers/application.go, scheduler_cluster.go;
rows in manager/crud.py CrudStore, sqlite write-through):

  GET    /api/v1/applications                    list
  POST   /api/v1/applications                    create        (OPERATOR)
  POST   /api/v1/applications/<id>:update        partial update (OPERATOR)
  POST   /api/v1/applications/<id>:delete                       (OPERATOR)
  GET    /api/v1/clusters                        list scheduler clusters
  POST   /api/v1/clusters                        create        (OPERATOR)
  POST   /api/v1/clusters/<id>:update            partial update (OPERATOR)
  POST   /api/v1/clusters/<id>:delete                           (OPERATOR)
  GET    /api/v1/clusters/<id>:config            the dynconfig payload a
         scheduler polls (scheduling.go:404-410 limit consumption)
  GET    /api/v1/buckets                         list (needs a configured
  POST   /api/v1/buckets                          object-storage backend —
  POST   /api/v1/buckets/<name>:delete            handlers/bucket.go proxy)

User/RBAC surface (manager/handlers/user.go + personal access tokens):

  POST   /api/v1/users:signup                    open signup (READONLY)
  POST   /api/v1/users:signin                    {name,password} → token
  GET    /api/v1/users                           ADMIN
  POST   /api/v1/users/<id>:role                 ADMIN
  POST   /api/v1/users/<id>:state                ADMIN enable/disable
  POST   /api/v1/users/<id>:reset-password       self or ADMIN
  POST   /api/v1/pats                            create PAT (raw shown once)
  GET    /api/v1/pats                            own tokens (ADMIN: ?user_id=)
  POST   /api/v1/pats/<id>:revoke                owner or ADMIN
  GET    /api/v1/oauth:providers
  GET    /api/v1/oauth/<name>:authorize-url?redirect_uri=
  POST   /api/v1/oauth/<name>:signin             {code,state,redirect_uri} → token

Authorization accepts EITHER a manager-issued HMAC session token or a
raw personal access token in ``Authorization: Bearer ...``.
"""

from __future__ import annotations

import base64
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler
from typing import List, Optional, Tuple

from ..rpc._server import ThreadedHTTPService
from ..security.tokens import Role

from .cluster import ClusterManager
from .crud import CrudStore
from .registry import Model, ModelRegistry
from .searcher import SchedulerCluster, Searcher


def _user_to_json(u) -> dict:
    return {
        "id": u.id,
        "name": u.name,
        "email": u.email,
        "role": u.role.name.lower(),
        "state": u.state,
    }


def _pat_to_json(p) -> dict:
    return {
        "id": p.id,
        "user_id": p.user_id,
        "name": p.name,
        "role": p.role.name.lower(),
        "expires_at": p.expires_at,
        "revoked": p.revoked,
    }


def _model_to_json(m: Model) -> dict:
    return {
        "id": m.id,
        "name": m.name,
        "type": m.type,
        "version": m.version,
        "scheduler_id": m.scheduler_id,
        "state": m.state.value,
        "evaluation": m.evaluation,
        "artifact_digest": m.artifact_digest,
    }


class ManagerRESTServer:
    def __init__(
        self,
        registry: ModelRegistry,
        clusters: ClusterManager,
        searcher: Optional[Searcher] = None,
        scheduler_clusters: Optional[List[SchedulerCluster]] = None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        token_verifier=None,
        token_issuer=None,
        users=None,
        oauth=None,
        jobqueue=None,
        crud: Optional[CrudStore] = None,
        objectstorage=None,
        rate_limit=None,
        ca=None,
        state_backend=None,
        jobs_min_requeue_s: float = 30.0,
        rollout=None,
        ha=None,
    ):
        self.registry = registry
        self.clusters = clusters
        # Replication role holder (manager/replication.py
        # ReplicatedStateBackend): serves /api/v1/replication:* and, in
        # the standby role, 503+Retry-After's every write until
        # promotion (clients fail over via rpc/resolver.ManagerEndpoints).
        self.ha = ha
        # Rollout controller (rollout/controller.py): serves the
        # candidate poll + evaluation-report routes; None → 404s.
        self.rollout = rollout
        # Cluster CA (security/ca.py CertificateAuthority): with one
        # attached, peers self-provision their mTLS identity over the
        # wire at boot — POST /api/v1/certs:issue (the reference's
        # certify flow, pkg/issuer + scheduler.go:186-222).  None → 404.
        self.ca = ca
        self.searcher = searcher or Searcher()
        self.scheduler_clusters = scheduler_clusters or []
        # CRUD resources (applications + scheduler-cluster records whose
        # config blobs feed the schedulers' dynconfig).  The default
        # cluster always exists — dynconfig consumers need one to poll.
        # A STANDBY never seeds it: the row replicates from the leader
        # (writes are gated until promotion).
        self.crud = crud or CrudStore()
        if ha is None or ha.role == "leader":
            self.crud.ensure_default_cluster()
        # Optional ObjectStorageBackend the bucket routes proxy to
        # (manager/handlers/bucket.go semantics); None → 404s.
        self.objectstorage = objectstorage
        # Token-bucket middleware (manager/middlewares rate limiter): one
        # bucket bounds the whole REST surface; None = off.
        self.rate_limit = rate_limit
        # Shared topology cache (the Redis analog for the probe graph,
        # network_topology.go:55-88): scheduler_id → its pushed edge
        # summaries.  Replicas pull everyone else's edges; a scheduler
        # restart re-pushes within one sync interval.  Entries whose
        # pusher went quiet past the TTL are evicted on read — a
        # decommissioned scheduler's stale RTTs must not skew rankings
        # forever (live schedulers re-push every ~30 s).
        self.topology_shared: dict = {}
        self.topology_ttl_s = 600.0
        self._topology_mu = threading.Lock()
        # With the manager state seam attached, pushed topology survives
        # a manager crash: replicas keep pulling the merged graph after
        # a restart instead of waiting a full re-push cycle.
        self._topology_table = (
            state_backend.table("topology") if state_backend is not None
            else None
        )
        if self._topology_table is not None:
            self.topology_shared = self._topology_table.load_all()
        # Sharded-fleet membership directory (scheduler/sharding.py,
        # DESIGN.md §24): the ACTIVE scheduler set per cluster, versioned
        # and persisted (on the replicated backend it survives a leader
        # bounce), published with the cluster dynconfig so every client
        # converges on the same ring.  Without a state seam the ring is
        # still published, from an in-memory backend (versions restart).
        from ..manager.state import MemoryBackend
        from ..scheduler.sharding import ShardDirectory

        self.shards = ShardDirectory(
            state_backend if state_backend is not None else MemoryBackend()
        )
        # Job broker (machinery-over-Redis analog, jobs/remote.py): the
        # manager hosts the queues; remote scheduler workers poll them
        # over this REST surface.
        if jobqueue is None:
            from ..jobs.queue import JobQueue

            jobqueue = JobQueue()
        self.jobqueue = jobqueue
        # Optional RBAC: with a verifier configured, mutations require a
        # bearer token of sufficient role (security/tokens.py); reads stay
        # open (matching the reference's authenticated-writes posture).
        # With a UserStore attached, PATs authenticate too and the user/
        # PAT/oauth routes come alive.
        self.jobs_min_requeue_s = jobs_min_requeue_s
        self.token_verifier = token_verifier
        self.token_issuer = token_issuer
        self.users = users
        self.oauth = oauth
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, payload, headers=None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _replication_auth_rejected(self, path: str) -> bool:
                """The :log/:snapshot routes dump every namespace —
                users/PATs credential rows included on default
                deployments — so they demand proof of the shared
                lease_secret (the follower signs each fetch); anything
                else 403s instead of bypassing the ADMIN-gated user
                routes."""
                from .replication import (
                    REPLICATION_AUTH_HEADER,
                    verify_replication_request,
                )

                token = self.headers.get(REPLICATION_AUTH_HEADER, "")
                if verify_replication_request(
                    server.ha.lease_secret, path, token
                ):
                    return False
                self._json(403, {
                    "error": "replication fetch requires the shared "
                    "lease_secret (X-DF-Replication-Auth)",
                })
                return True

            def _standby_rejected(self) -> bool:
                """Standby role: every mutation 503s with Retry-After
                until promotion — a client that cannot fail over knows
                exactly when to knock again (one follower poll)."""
                ha = server.ha
                if ha is None or ha.role == "leader":
                    return False
                self._json(
                    503,
                    {
                        "error": "manager is a standby replica "
                        f"(term {ha.term}); writes go to the leader",
                        "role": ha.role,
                    },
                    headers={"Retry-After": "1"},
                )
                return True

            def _rate_limited(self) -> bool:
                # Liveness-class routes stay exempt: the limiter must not
                # convert overload into an outage — 429ing health probes
                # gets the manager restarted, and 429ing scheduler
                # keepalives expires HEALTHY schedulers out of the active
                # set exactly when the cluster is busiest.
                path = urllib.parse.urlsplit(self.path).path
                if path == "/api/v1/healthy" or path.endswith(":keepalive"):
                    return False
                if server.rate_limit is not None and not server.rate_limit.take():
                    from ..rpc.metrics import RATE_LIMITED_TOTAL

                    RATE_LIMITED_TOTAL.inc(transport="manager-rest")
                    self._json(429, {"error": "rate limit exceeded"})
                    return True
                return False

            def do_GET(self):
                # Request span linked to the caller's trace (otelgrpc
                # server-interceptor analog for the REST plane): the
                # route rides as an attribute, not the span name, so
                # cardinality stays bounded.
                from ..utils.tracing import TRACEPARENT_HEADER, default_tracer

                with default_tracer.remote_span(
                    "manager/GET",
                    self.headers.get(TRACEPARENT_HEADER),
                    path=urllib.parse.urlsplit(self.path).path,
                    transport="rest",
                ):
                    self._handle_GET()

            def _handle_GET(self):
                if self._rate_limited():
                    return
                parsed = urllib.parse.urlsplit(self.path)
                q = dict(urllib.parse.parse_qsl(parsed.query))
                path = parsed.path
                if path in ("/", "/console", "/console/"):
                    # Embedded console SPA (manager.go:61-62 analog).
                    from .console import CONSOLE_HTML

                    body = CONSOLE_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/api/v1/healthy":
                    payload = {"ok": True}
                    if server.ha is not None:
                        payload["role"] = server.ha.role
                        payload["term"] = server.ha.term
                    self._json(200, payload)
                elif path == "/metrics":
                    # Prometheus text exposition — the same diagnostics
                    # surface the scheduler/daemon serve via
                    # utils/diagnostics.py (DESIGN.md §21).
                    from ..utils.metrics import default_registry

                    body = default_registry.expose_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/debug/spans":
                    # Recent-span ring as one OTLP/JSON request.
                    from ..utils.tracing import recent_spans_otlp

                    self._json(200, recent_spans_otlp())
                elif path == "/debug/exemplars":
                    # Histogram exemplars: last trace id per bucket, so a
                    # slow-bucket latency joins to its trace.
                    from ..utils.metrics import default_registry

                    self._json(200, default_registry.exemplars())
                elif path == "/debug/slo":
                    # SLO burn rates + breach verdicts (DESIGN.md §23) —
                    # the same surface the scheduler/daemon diagnostics
                    # sidecar serves.
                    from ..utils.slo import debug_state

                    self._json(200, debug_state())
                elif path == "/api/v1/replication:status":
                    # Follower poll target: log frontier + the signed
                    # lease (manager/replication.py LogFollower).
                    if server.ha is None:
                        self._json(404, {"error": "replication not configured"})
                    else:
                        status = server.ha.status()
                        if server.ha.role == "leader":
                            status["lease"] = server.ha.lease_payload()
                        self._json(200, status)
                elif path == "/api/v1/replication:log":
                    if server.ha is None:
                        self._json(404, {"error": "replication not configured"})
                    elif not self._replication_auth_rejected(path):
                        try:
                            from_seq = int(q.get("from_seq", 0))
                            limit = min(int(q.get("limit", 500)), 2000)
                        except ValueError as exc:
                            self._json(400, {"error": str(exc)})
                            return
                        # Read under the commit lock (log_entries): a
                        # concurrent append-then-discard must never ship.
                        self._json(
                            200, server.ha.log_entries(from_seq, limit)
                        )
                elif path == "/api/v1/replication:snapshot":
                    # Follower bootstrap: full data-state snapshot for
                    # rows that predate the log (legacy migrations,
                    # pre-HA deployments) or that compacted out of it.
                    if server.ha is None:
                        self._json(404, {"error": "replication not configured"})
                    elif not self._replication_auth_rejected(path):
                        self._json(200, server.ha.snapshot())
                elif path == "/api/v1/certs:ca":
                    # Trust-root fetch (open read: peers need the root
                    # BEFORE they can build a verified TLS context).
                    if server.ca is None:
                        self._json(404, {"error": "no cluster CA configured"})
                    else:
                        self._json(200, {"ca_pem": server.ca.cert_pem.decode()})
                elif path in ("/swagger.json", "/api/v1/openapi"):
                    # The swagger export (api/manager/swagger.json analog).
                    from .openapi import spec

                    self._json(200, spec())
                elif path == "/api/v1/models":
                    models = server.registry.list(
                        scheduler_id=q.get("scheduler_id") or None,
                        name=q.get("name") or None,
                    )
                    self._json(200, [_model_to_json(m) for m in models])
                elif path == "/api/v1/models:active":
                    m = server.registry.active_model(
                        q.get("scheduler_id", ""), q.get("name", "")
                    )
                    if m is None:
                        self._json(404, {"error": "no active model"})
                    else:
                        self._json(200, _model_to_json(m))
                elif path == "/api/v1/models:artifact":
                    m = server.registry.get(q.get("id", ""))
                    if m is None:
                        self._json(404, {"error": "model not found"})
                    else:
                        try:
                            blob = server.registry.load_artifact(m)
                        except (KeyError, OSError, ValueError) as exc:
                            # Row exists but the blob is gone (mismatched
                            # blob dir after restart) or fails its digest
                            # check (ArtifactDigestError) — a clean 404
                            # beats a dead handler thread + connection
                            # reset, and no client ever receives bytes
                            # the manager itself cannot verify.
                            self._json(404, {"error": f"artifact unavailable: {exc}"})
                            return
                        self._json(
                            200, {"artifact_b64": base64.b64encode(blob).decode()}
                        )
                elif path == "/api/v1/models:get":
                    m = server.registry.get(q.get("id", ""))
                    if m is None:
                        self._json(404, {"error": "model not found"})
                    else:
                        self._json(200, _model_to_json(m))
                elif path == "/api/v1/models:candidate":
                    # The scheduler's rollout poll: the version under
                    # evaluation (SHADOW/CANARY) + its routing percent.
                    m = server.registry.candidate_model(
                        q.get("scheduler_id", ""), q.get("name", "")
                    )
                    if m is None:
                        self._json(404, {"error": "no candidate model"})
                    else:
                        rollout = (
                            server.rollout.get(m.scheduler_id, m.name)
                            if server.rollout is not None
                            else None
                        )
                        self._json(200, {
                            "model": _model_to_json(m),
                            "phase": m.state.value,
                            "canary_percent": (
                                rollout.canary_percent if rollout else 0
                            ),
                        })
                elif path == "/api/v1/rollouts":
                    if server.rollout is None:
                        self._json(404, {"error": "rollout controller not configured"})
                    else:
                        self._json(200, [
                            server.rollout.to_json(r)
                            for r in server.rollout.list()
                        ])
                elif path == "/api/v1/rollouts:get":
                    r = (
                        server.rollout.get(
                            q.get("scheduler_id", ""), q.get("name", "")
                        )
                        if server.rollout is not None
                        else None
                    )
                    if r is None:
                        self._json(404, {"error": "no such rollout"})
                    else:
                        self._json(200, server.rollout.to_json(r))
                elif path == "/api/v1/schedulers":
                    self._json(
                        200,
                        [
                            {
                                "id": s.id,
                                "cluster_id": s.cluster_id,
                                "ip": s.ip,
                                "port": s.port,
                                "state": s.state,
                            }
                            for s in server.clusters.active_schedulers()
                        ],
                    )
                elif path == "/api/v1/users" and server.users is not None:
                    if not self._authorized(Role.ADMIN):
                        self._json(403, {"error": "forbidden"})
                        return
                    self._json(200, [_user_to_json(u) for u in server.users.list_users()])
                elif path == "/api/v1/pats" and server.users is not None:
                    ident = self._identity()
                    if ident is None:
                        self._json(401, {"error": "unauthorized"})
                        return
                    subject, role, _kind = ident
                    target = q.get("user_id") or subject
                    if target != subject and role < Role.ADMIN:
                        self._json(403, {"error": "forbidden"})
                        return
                    self._json(
                        200, [_pat_to_json(p) for p in server.users.list_pats(target)]
                    )
                elif path == "/api/v1/oauth:providers" and server.oauth is not None:
                    self._json(200, server.oauth.providers())
                elif (
                    path.startswith("/api/v1/oauth/")
                    and path.endswith(":authorize-url")
                    and server.oauth is not None
                ):
                    name = path[len("/api/v1/oauth/") : -len(":authorize-url")]
                    try:
                        self._json(
                            200,
                            {"url": server.oauth.authorize_url(
                                name, q.get("redirect_uri", "")
                            )},
                        )
                    except KeyError:
                        self._json(404, {"error": f"no provider {name!r}"})
                elif path == "/api/v1/jobs":
                    # Recent group jobs (console view; handlers/job.go list).
                    self._json(200, server.jobqueue.list_groups())
                elif path.startswith("/api/v1/jobs/"):
                    gid = path[len("/api/v1/jobs/"):]
                    try:
                        self._json(200, server.jobqueue.group_snapshot(gid))
                    except KeyError:
                        self._json(404, {"error": f"no group {gid!r}"})
                elif path == "/api/v1/topology":
                    # Cross-replica pull: every LIVE pusher's edges EXCEPT
                    # the caller's own (it already has those, fresher).
                    import time as _time

                    exclude = q.get("exclude", "")
                    now = _time.time()
                    with server._topology_mu:
                        dead = [
                            sid
                            for sid, entry in server.topology_shared.items()
                            if now - entry["pushed_at"] > server.topology_ttl_s
                        ]
                        for sid in dead:
                            del server.topology_shared[sid]
                            if server._topology_table is not None:
                                from .replication import NotLeaderError

                                try:
                                    server._topology_table.delete(sid)
                                except NotLeaderError:
                                    # Standby: evict from memory only —
                                    # the leader's replicated delete is
                                    # the durable one.
                                    pass
                        edges = [
                            e
                            for sid, entry in server.topology_shared.items()
                            if sid != exclude
                            for e in entry["edges"]
                        ]
                    self._json(200, {"edges": edges})
                elif path == "/api/v1/buckets":
                    # handlers/bucket.go GetBuckets: list through the
                    # configured object-storage backend.
                    if server.objectstorage is None:
                        self._json(404, {"error": "object storage not configured"})
                        return
                    try:
                        names = server.objectstorage.list_buckets()
                    except Exception as exc:  # noqa: BLE001 — backend boundary
                        self._json(502, {"error": str(exc)})
                        return
                    self._json(200, [{"name": n} for n in names])
                elif path == "/api/v1/applications":
                    from dataclasses import asdict

                    self._json(
                        200, [asdict(a) for a in server.crud.list("application")]
                    )
                elif path == "/api/v1/configs":
                    from dataclasses import asdict

                    self._json(200, [asdict(c) for c in server.crud.list("config")])
                elif path == "/api/v1/clusters":
                    from dataclasses import asdict

                    self._json(200, [asdict(c) for c in server.crud.list("cluster")])
                elif path.startswith("/api/v1/clusters/") and path.endswith(
                    ":config"
                ):
                    # The dynconfig payload a scheduler polls for its live
                    # scheduling limits (scheduling.go:404-410).
                    cid = path[len("/api/v1/clusters/"):-len(":config")]
                    try:
                        payload = server.crud.cluster_config(cid)
                        # Tenant identity derivation (DESIGN.md §26): an
                        # authenticated poll (PAT or session token) gets
                        # its tenant id derived from the credential's
                        # subject — the SAME derivation every service
                        # applies, so one identity maps to one tenant
                        # fleet-wide.  Unauthenticated clusters fall back
                        # to their declared DaemonConfig.tenant.
                        ident = self._identity()
                        if ident is not None:
                            from ..qos.policy import derive_tenant

                            payload["tenant_id"] = derive_tenant(ident[0])
                        # The shard ring rides the cluster dynconfig
                        # (DESIGN.md §24): membership is the ACTIVE
                        # scheduler set; a set change bumps the durable
                        # ring version and every poller re-routes.
                        payload["scheduler_ring"] = server.shards.publish(
                            cid,
                            [
                                (s.id, f"http://{s.ip}:{s.port}")
                                for s in server.clusters.active_schedulers(cid)
                            ],
                        )
                        self._json(200, payload)
                    except KeyError as exc:
                        self._json(404, {"error": str(exc)})
                elif path == "/api/v1/clusters:search":
                    try:
                        ranked = server.searcher.find_scheduler_clusters(
                            server.search_clusters(),
                            ip=q.get("ip", ""),
                            hostname=q.get("hostname", ""),
                            conditions={
                                "idc": q.get("idc", ""),
                                "location": q.get("location", ""),
                            },
                        )
                        self._json(200, [c.id for c in ranked])
                    except LookupError as exc:
                        self._json(404, {"error": str(exc)})
                else:
                    self._json(404, {"error": "not found"})

            def _identity(self):
                """→ (subject, Role, kind) from a session token OR a PAT;
                None when unauthenticated.  kind ∈ {"session", "pat"} —
                credential-management routes require a session.  One
                shared resolver with the gRPC port (tokens.
                resolve_credential): disables/demotions bite everywhere
                immediately."""
                from ..security.tokens import resolve_credential

                auth = self.headers.get("Authorization", "")
                token = auth[len("Bearer ") :] if auth.startswith("Bearer ") else None
                return resolve_credential(
                    token, server.token_verifier, server.users
                )

            def _authorized(self, required_role) -> bool:
                if server.token_verifier is None and server.users is None:
                    return True
                ident = self._identity()
                return ident is not None and ident[1] >= required_role

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(length) or b"{}")

            def do_POST(self):
                from ..utils.tracing import TRACEPARENT_HEADER, default_tracer

                with default_tracer.remote_span(
                    "manager/POST",
                    self.headers.get(TRACEPARENT_HEADER),
                    path=urllib.parse.urlsplit(self.path).path,
                    transport="rest",
                ):
                    self._handle_POST()

            def _handle_POST(self):
                if self._rate_limited():
                    return
                if self._standby_rejected():
                    return
                path = urllib.parse.urlsplit(self.path).path
                if (
                    path.startswith("/api/v1/users")
                    or path.startswith("/api/v1/pats")
                    or path.startswith("/api/v1/oauth/")
                    or path == "/api/v1/oauth:refresh"
                ):
                    self._user_routes(path)
                    return
                # Role per route, declared at the route (tokens.py tiers):
                # model CREATION is the trainer's automated flow → PEER;
                # activation/deactivation are operator decisions; job
                # CREATION is an operator action while poll/result are the
                # scheduler workers' automated flow → PEER.
                if path == "/api/v1/models":
                    required = Role.PEER
                elif path == "/api/v1/rollouts:report":
                    # Shadow/canary evaluation reports are the scheduler's
                    # automated flow (like keepalive/job-poll) → PEER.
                    required = Role.PEER
                elif (
                    path.endswith(":activate")
                    or path.endswith(":deactivate")
                    or path.endswith(":rollout")
                    or (path.startswith("/api/v1/models/")
                        and path.endswith(":delete"))
                ):
                    required = Role.OPERATOR
                elif path == "/api/v1/jobs":
                    required = Role.OPERATOR
                elif path == "/api/v1/jobs:poll" or (
                    path.startswith("/api/v1/jobs/") and path.endswith(":result")
                ):
                    required = Role.PEER
                elif path == "/api/v1/schedulers" or (
                    path.startswith("/api/v1/schedulers/")
                    and path.endswith(":keepalive")
                ):
                    # Scheduler instances self-register and tick liveness —
                    # the automated service flow (UpdateScheduler /
                    # KeepAlive in manager_server_v1.go run on mTLS'd
                    # service identities) → PEER.
                    required = Role.PEER
                elif path == "/api/v1/topology":
                    required = Role.PEER  # scheduler service flow
                elif path == "/api/v1/certs:issue":
                    # Service-identity bootstrap (certify analog) — the
                    # automated peer flow, like registration/keepalive.
                    required = Role.PEER
                elif (
                    path.startswith("/api/v1/applications")
                    or path.startswith("/api/v1/clusters")
                    or path.startswith("/api/v1/buckets")
                    or path.startswith("/api/v1/configs")
                ):
                    # CRUD mutations are operator console actions.
                    required = Role.OPERATOR
                else:
                    required = Role.ADMIN  # unknown mutations: locked down
                if not self._authorized(required):
                    self._json(401, {"error": "unauthorized"})
                    return
                if path.startswith("/api/v1/jobs"):
                    self._job_routes(path)
                    return
                if (
                    path.startswith("/api/v1/applications")
                    or path.startswith("/api/v1/configs")
                    or (
                        path.startswith("/api/v1/clusters")
                        and not path.startswith("/api/v1/clusters:")
                    )
                ):
                    self._crud_routes(path)
                    return
                if path == "/api/v1/certs:issue":
                    # CSR in, cluster-CA-signed cert out (pkg/issuer /
                    # security_server.go IssueCertificate analog).
                    if server.ca is None:
                        self._json(404, {"error": "no cluster CA configured"})
                        return
                    try:
                        from ..security.ca import clamp_ttl

                        req = self._body()
                        csr_pem = req["csr_pem"].encode()
                        ttl = clamp_ttl(int(req.get("ttl_hours") or 0))
                        cert_pem = server.ca.sign_csr(csr_pem, ttl=ttl)
                        self._json(200, {
                            "cert_pem": cert_pem.decode(),
                            "ca_pem": server.ca.cert_pem.decode(),
                        })
                    except (KeyError, ValueError, TypeError) as exc:
                        self._json(400, {"error": str(exc)})
                    except Exception as exc:  # noqa: BLE001 — x509 parse
                        self._json(400, {"error": f"bad csr: {exc}"})
                    return
                if path == "/api/v1/topology":
                    # Scheduler push: replace this scheduler's edge set.
                    try:
                        req = self._body()
                        sid = req["scheduler_id"]
                        # Validate edge shape at the WRITE boundary: one
                        # malformed push must not poison every replica's
                        # merge on pull.
                        edges = [
                            e for e in (req.get("edges") or [])
                            if isinstance(e, dict)
                            and e.get("src") and e.get("dst")
                            and isinstance(e.get("average_rtt_ns"), int)
                        ]
                        import time as _time

                        with server._topology_mu:
                            server.topology_shared[sid] = {
                                "edges": edges, "pushed_at": _time.time(),
                            }
                            if server._topology_table is not None:
                                server._topology_table.put(
                                    sid, server.topology_shared[sid]
                                )
                        self._json(200, {"ok": True, "edges": len(edges)})
                    except (KeyError, ValueError, TypeError) as exc:
                        self._json(400, {"error": str(exc)})
                    return
                if path.startswith("/api/v1/buckets"):
                    # handlers/bucket.go CreateBucket / DestroyBucket —
                    # proxied to the configured backend.
                    if server.objectstorage is None:
                        self._json(404, {"error": "object storage not configured"})
                        return
                    try:
                        if path == "/api/v1/buckets":
                            name = self._body()["name"]
                            if not name or not isinstance(name, str):
                                raise ValueError("bucket name required")
                            server.objectstorage.create_bucket(name)
                            self._json(200, {"name": name})
                        elif path.endswith(":delete"):
                            name = path[len("/api/v1/buckets/"):-len(":delete")]
                            if not name:
                                raise ValueError("bucket name required")
                            server.objectstorage.delete_bucket(name)
                            self._json(200, {"ok": True})
                        else:
                            self._json(404, {"error": "not found"})
                    except (KeyError, ValueError, TypeError) as exc:
                        self._json(400, {"error": str(exc)})
                    except Exception as exc:  # noqa: BLE001 — backend boundary
                        self._json(502, {"error": str(exc)})
                    return
                if path == "/api/v1/schedulers":
                    # Scheduler instance registration over REST — the wire
                    # the CLI uses so sync_peers fan-out (jobs/sync_peers.py
                    # enqueues to f"scheduler:{sched.id}" for every ACTIVE
                    # registered scheduler) reaches the instance's job queue.
                    from .cluster import SchedulerInstance

                    try:
                        req = self._body()
                        inst = server.clusters.register_scheduler(
                            SchedulerInstance(
                                id=req["id"],
                                cluster_id=req.get("cluster_id", "default"),
                                hostname=req.get("hostname", ""),
                                ip=req.get("ip", ""),
                                port=int(req.get("port", 8002)),
                            )
                        )
                        self._json(200, {
                            "id": inst.id, "cluster_id": inst.cluster_id,
                            "state": inst.state,
                        })
                    except (KeyError, ValueError, TypeError) as exc:
                        # TypeError: int(None)/int([]) from malformed port —
                        # a 400, not a dropped connection.
                        self._json(400, {"error": str(exc)})
                    return
                if path.startswith("/api/v1/schedulers/") and path.endswith(
                    ":keepalive"
                ):
                    inst_id = path[len("/api/v1/schedulers/"):-len(":keepalive")]
                    # known=False tells the instance the manager lost it
                    # (restart) and it must re-register.
                    self._json(200, {"known": server.clusters.keepalive(inst_id)})
                    return
                if path == "/api/v1/models":
                    # CreateModel (reference: manager_server_v1.go:802).
                    try:
                        req = self._body()
                        m = server.registry.create_model(
                            name=req["name"],
                            type=req["type"],
                            scheduler_id=req["scheduler_id"],
                            artifact=base64.b64decode(req.get("artifact_b64", "")),
                            evaluation=req.get("evaluation") or {},
                        )
                        self._json(200, _model_to_json(m))
                    except (KeyError, ValueError) as exc:
                        self._json(400, {"error": str(exc)})
                    return
                if path == "/api/v1/rollouts:report":
                    # One evaluation report from a scheduler → the
                    # controller's decision (rollout/controller.py).
                    if server.rollout is None:
                        self._json(404, {"error": "rollout controller not configured"})
                        return
                    try:
                        req = self._body()
                        decision = server.rollout.report(
                            req["scheduler_id"], req["name"],
                            dict(req.get("report") or {}),
                        )
                        self._json(200, decision)
                    except KeyError as exc:
                        self._json(404, {"error": str(exc)})
                    except (ValueError, TypeError) as exc:
                        self._json(400, {"error": str(exc)})
                    return
                if path.startswith("/api/v1/models/") and ":" in path:
                    model_id, _, action = path[len("/api/v1/models/") :].rpartition(":")
                    try:
                        if action == "activate":
                            m = server.registry.activate(model_id)
                        elif action == "deactivate":
                            m = server.registry.deactivate(model_id)
                        elif action == "rollout":
                            # Begin the evidence-gated rollout for this
                            # version (CANDIDATE → SHADOW).
                            if server.rollout is None:
                                self._json(
                                    404,
                                    {"error": "rollout controller not configured"},
                                )
                                return
                            req = self._body()
                            r = server.rollout.begin(
                                model_id,
                                canary_percent=req.get("canary_percent"),
                            )
                            self._json(200, server.rollout.to_json(r))
                            return
                        elif action == "delete":
                            # Model deletes flow through the rollout
                            # controller's guarded cleanup (DF014 foreign
                            # key models→rollouts): rollout rows must not
                            # outlive the model row they reference.  An ad
                            # hoc controller covers managers without a
                            # rollout plane configured (no rows to strand,
                            # same guarded path).
                            controller = server.rollout
                            if controller is None:
                                from ..rollout.controller import (
                                    RolloutController,
                                )

                                controller = RolloutController(server.registry)
                            if server.registry.get(model_id) is None:
                                self._json(
                                    404,
                                    {"error": f"model {model_id} not found"},
                                )
                                return
                            controller.delete_model(model_id)
                            self._json(200, {"deleted": model_id})
                            return
                        else:
                            self._json(404, {"error": f"unknown action {action}"})
                            return
                        self._json(200, _model_to_json(m))
                    except KeyError:
                        self._json(404, {"error": f"model {model_id} not found"})
                    except ValueError as exc:
                        self._json(400, {"error": str(exc)})
                    return
                self._json(404, {"error": "not found"})

            def _crud_routes(self, path: str) -> None:
                """Applications + scheduler-cluster CRUD
                (manager/handlers/application.go, scheduler_cluster.go)."""
                from dataclasses import asdict

                if path.startswith("/api/v1/applications"):
                    kind, base = "application", "/api/v1/applications"
                elif path.startswith("/api/v1/configs"):
                    kind, base = "config", "/api/v1/configs"
                else:
                    kind, base = "cluster", "/api/v1/clusters"
                try:
                    if path == base:
                        obj = server.crud.create(kind, **self._body())
                        self._json(200, asdict(obj))
                        return
                    rest = path[len(base) + 1:]
                    row_id, _, action = rest.rpartition(":")
                    if action == "update":
                        obj = server.crud.update(kind, row_id, **self._body())
                        self._json(200, asdict(obj))
                    elif action == "delete":
                        server.crud.delete(kind, row_id)
                        self._json(200, {"ok": True})
                    else:
                        self._json(404, {"error": f"unknown action {action!r}"})
                except KeyError as exc:
                    self._json(404, {"error": str(exc)})
                except (ValueError, TypeError) as exc:
                    self._json(400, {"error": str(exc)})

            def _job_routes(self, path: str) -> None:
                """Job broker wire (jobs/remote.py contract)."""
                from ..jobs.queue import JobState

                try:
                    if path == "/api/v1/jobs":
                        req = self._body()
                        queues = req.get("queues") or []
                        if not queues or "type" not in req:
                            self._json(400, {"error": "type and queues required"})
                            return
                        group = server.jobqueue.create_group_job(
                            req["type"],
                            {q: dict(req.get("args") or {}) for q in queues},
                        )
                        self._json(200, server.jobqueue.group_snapshot(group.id))
                    elif path == "/api/v1/jobs:poll":
                        req = self._body()
                        queue_name = req.get("queue", "")
                        if not queue_name:
                            self._json(400, {"error": "queue required"})
                            return
                        timeout = min(float(req.get("timeout_s") or 5.0), 30.0)
                        # Visibility window override (machinery's
                        # visibility-timeout analog) — floored by the
                        # operator's jobs_min_requeue_s: an impatient
                        # worker must not force-redeliver every job
                        # another worker is still executing.
                        requeue_after = max(
                            float(req.get("requeue_started_after_s") or 120.0),
                            server.jobs_min_requeue_s,
                        )
                        job = server.jobqueue.poll(
                            queue_name, timeout=timeout,
                            requeue_started_after_s=requeue_after,
                        )
                        if job is None:
                            self._json(200, {})  # empty poll (204 bodies confuse keep-alive)
                            return
                        self._json(200, {
                            "id": job.id, "type": job.type,
                            "args": job.args, "group_id": job.group_id,
                        })
                    elif path.startswith("/api/v1/jobs/") and path.endswith(":result"):
                        job_id = path[len("/api/v1/jobs/"):-len(":result")]
                        req = self._body()
                        state = JobState(req.get("state", "FAILURE"))
                        if state not in (JobState.SUCCESS, JobState.FAILURE):
                            self._json(400, {"error": f"bad state {state}"})
                            return
                        server.jobqueue.set_result(
                            job_id, state,
                            result=req.get("result"),
                            error=req.get("error", ""),
                        )
                        self._json(200, {"ok": True})
                    else:
                        self._json(404, {"error": "not found"})
                except KeyError as exc:
                    self._json(404, {"error": str(exc)})
                except ValueError as exc:
                    self._json(400, {"error": str(exc)})

            def _user_routes(self, path: str) -> None:
                """User / PAT / oauth mutations (handlers/user.go)."""
                if server.users is None:
                    self._json(404, {"error": "user store not configured"})
                    return
                try:
                    if path == "/api/v1/users:signup":
                        req = self._body()
                        u = server.users.create_user(
                            req["name"], req["password"],
                            email=req.get("email", ""),
                        )
                        self._json(200, _user_to_json(u))
                    elif path == "/api/v1/users:signin":
                        req = self._body()
                        u = server.users.verify_password(
                            req.get("name", ""), req.get("password", "")
                        )
                        if u is None or server.token_issuer is None:
                            self._json(401, {"error": "bad credentials"})
                            return
                        token = server.token_issuer.issue(u.id, u.role)
                        self._json(200, {"token": token, "role": u.role.name.lower()})
                    elif path.startswith("/api/v1/users/") and ":" in path:
                        user_id, _, action = path[len("/api/v1/users/") :].rpartition(":")
                        ident = self._identity()
                        if ident is None:
                            self._json(401, {"error": "unauthorized"})
                            return
                        subject, role, kind = ident
                        if action == "reset-password":
                            # Sessions only: a leaked low-role PAT must not
                            # be able to rotate its owner's password and
                            # re-signin at the owner's full role.
                            if kind != "session":
                                self._json(403, {"error": "session token required"})
                                return
                            if subject != user_id and role < Role.ADMIN:
                                self._json(403, {"error": "forbidden"})
                                return
                            server.users.reset_password(
                                user_id, self._body()["password"]
                            )
                            self._json(200, {"ok": True})
                        elif action in ("role", "state"):
                            if role < Role.ADMIN:
                                self._json(403, {"error": "forbidden"})
                                return
                            if action == "role":
                                u = server.users.set_role(
                                    user_id, Role[self._body()["role"].upper()]
                                )
                            else:
                                u = server.users.set_state(
                                    user_id, self._body()["state"]
                                )
                            self._json(200, _user_to_json(u))
                        else:
                            self._json(404, {"error": f"unknown action {action}"})
                    elif path == "/api/v1/pats":
                        ident = self._identity()
                        if ident is None:
                            self._json(401, {"error": "unauthorized"})
                            return
                        subject, effective, _kind = ident
                        req = self._body()
                        requested = (
                            Role[req["role"].upper()] if req.get("role")
                            else effective
                        )
                        # Cap at the CALLER's effective role (a READONLY-
                        # capped PAT must not mint tokens at its owner's
                        # full role), on top of create_pat's owner cap.
                        kwargs = {"role": min(requested, effective)}
                        if req.get("ttl_s"):
                            kwargs["ttl_s"] = float(req["ttl_s"])
                        pat, raw = server.users.create_pat(
                            subject, req.get("name", ""), **kwargs
                        )
                        payload = _pat_to_json(pat)
                        payload["token"] = raw  # shown exactly once
                        self._json(200, payload)
                    elif path.startswith("/api/v1/pats/") and path.endswith(":revoke"):
                        pat_id = path[len("/api/v1/pats/") : -len(":revoke")]
                        ident = self._identity()
                        if ident is None:
                            self._json(401, {"error": "unauthorized"})
                            return
                        subject, role, _kind = ident
                        owned = {p.id for p in server.users.list_pats(subject)}
                        if pat_id not in owned and role < Role.ADMIN:
                            self._json(403, {"error": "forbidden"})
                            return
                        server.users.revoke_pat(pat_id)
                        self._json(200, {"ok": True})
                    elif (
                        path.startswith("/api/v1/oauth/")
                        and path.endswith(":signin")
                        and server.oauth is not None
                    ):
                        name = path[len("/api/v1/oauth/") : -len(":signin")]
                        # Issuer check FIRST: consuming the single-use
                        # code/grant and then 500ing would strand it.
                        if server.token_issuer is None:
                            self._json(500, {"error": "no token issuer"})
                            return
                        req = self._body()
                        u, refresh_id = server.oauth.signin_with_refresh(
                            name, req.get("code", ""), req.get("state", ""),
                            req.get("redirect_uri", ""),
                        )
                        token = server.token_issuer.issue(u.id, u.role)
                        self._json(200, {
                            "token": token, "role": u.role.name.lower(),
                            "user": u.name, "refresh_id": refresh_id,
                        })
                    elif (
                        path == "/api/v1/oauth:refresh"
                        and server.oauth is not None
                    ):
                        # Session renewal WITHOUT an interactive authorize
                        # round-trip; a provider-revoked refresh token
                        # 403s here and the console re-authenticates.
                        if server.token_issuer is None:
                            self._json(500, {"error": "no token issuer"})
                            return
                        req = self._body()
                        u, refresh_id = server.oauth.refresh(
                            req.get("refresh_id", "")
                        )
                        token = server.token_issuer.issue(u.id, u.role)
                        self._json(200, {
                            "token": token, "role": u.role.name.lower(),
                            "user": u.name, "refresh_id": refresh_id,
                        })
                    else:
                        self._json(404, {"error": "not found"})
                except PermissionError as exc:
                    self._json(403, {"error": str(exc)})
                except (KeyError, ValueError) as exc:
                    self._json(400, {"error": str(exc)})
                except Exception as exc:  # noqa: BLE001 — IdP outage etc.
                    from .oauth import OAuthUnavailable

                    if isinstance(exc, OAuthUnavailable):
                        # Transient provider failure: the grant is
                        # intact server-side; the console retries.
                        self._json(503, {"error": str(exc)})
                    else:
                        raise

        self._svc = ThreadedHTTPService(Handler, host, port, "manager-rest")
        self.address: Tuple[str, int] = self._svc.address

    def search_clusters(self) -> List[SchedulerCluster]:
        """The searcher's candidate set: the constructor-injected list when
        provided (tests, static deployments), else the CRUD cluster rows —
        ONE cluster model, so a cluster created over REST is immediately
        searchable, with live scheduler ids from the keepalive table."""
        if self.scheduler_clusters:
            return self.scheduler_clusters
        from .searcher import ClusterScopes

        out = []
        for rec in self.crud.list("cluster"):
            scopes = rec.scopes or {}
            out.append(SchedulerCluster(
                id=rec.id,
                name=rec.name,
                is_default=rec.is_default,
                scopes=ClusterScopes(
                    idc=scopes.get("idc", ""),
                    location=scopes.get("location", ""),
                    cidrs=tuple(scopes.get("cidrs", ())),
                    hostnames=tuple(scopes.get("hostnames", ())),
                ),
                scheduler_ids=[
                    s.id for s in self.clusters.active_schedulers(rec.id)
                ],
            ))
        return out

    @property
    def url(self) -> str:
        return self._svc.url

    def serve(self) -> None:
        self._svc.serve()

    def stop(self) -> None:
        self._svc.stop()
