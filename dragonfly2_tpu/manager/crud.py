"""Manager CRUD resources: applications + scheduler-cluster records.

Reference: the manager's GORM models and REST handlers
(manager/handlers/application.go, scheduler_cluster.go,
models/application.go, models/scheduler_cluster.go) — applications tag
traffic for per-app policy; scheduler-cluster rows carry the CLUSTER
CONFIG (candidate/filter parent limits, client load limits) that
schedulers consume through dynconfig (scheduler/scheduling/
scheduling.go:404-410 reads the limits per scheduling pass).

Storage: one sqlite table of JSON rows (or memory when no db_path) —
the write-through pattern `_SQLiteModelStore` uses.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Application:
    """models/application.go row: per-application traffic identity."""

    id: str
    name: str
    url: str = ""
    bio: str = ""
    priority: int = 0


@dataclass
class ClusterRecord:
    """models/scheduler_cluster.go row + its config blobs.

    ``scheduler_cluster_config`` carries the scheduling limits the
    scheduler's dynconfig applies live (candidate_parent_limit,
    filter_parent_limit); ``client_config`` the daemon-side knobs
    (load_limit); ``scopes`` the searcher's affinity inputs.
    """

    id: str
    name: str = ""
    is_default: bool = False
    scheduler_cluster_config: Dict[str, Any] = field(default_factory=dict)
    client_config: Dict[str, Any] = field(default_factory=dict)
    scopes: Dict[str, Any] = field(default_factory=dict)


_KINDS = {"application": Application, "cluster": ClusterRecord}


class CrudStore:
    """JSON-row store for the manager's CRUD resources."""

    def __init__(self, db_path: Optional[str] = None) -> None:
        self._mu = threading.RLock()
        self._rows: Dict[str, Dict[str, dict]] = {k: {} for k in _KINDS}
        self._db: Optional[sqlite3.Connection] = None
        if db_path:
            self._db = sqlite3.connect(db_path, check_same_thread=False)
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS crud_rows ("
                "kind TEXT, id TEXT, value TEXT, PRIMARY KEY (kind, id))"
            )
            for kind, id_, value in self._db.execute(
                "SELECT kind, id, value FROM crud_rows"
            ):
                if kind in self._rows:
                    self._rows[kind][id_] = json.loads(value)

    def _persist(self, kind: str, id_: str, row: Optional[dict]) -> None:
        if self._db is None:
            return
        with self._db:
            if row is None:
                self._db.execute(
                    "DELETE FROM crud_rows WHERE kind=? AND id=?", (kind, id_)
                )
            else:
                self._db.execute(
                    "INSERT OR REPLACE INTO crud_rows (kind, id, value) "
                    "VALUES (?, ?, ?)",
                    (kind, id_, json.dumps(row)),
                )

    # -- generic ops ---------------------------------------------------------

    def create(self, kind: str, **fields: Any):
        cls = _KINDS[kind]
        with self._mu:
            row_id = fields.pop("id", None) or uuid.uuid4().hex[:12]
            if row_id in self._rows[kind]:
                raise ValueError(f"{kind} {row_id!r} already exists")
            obj = cls(id=row_id, **fields)
            self._rows[kind][row_id] = asdict(obj)
            self._persist(kind, row_id, self._rows[kind][row_id])
            return obj

    def get(self, kind: str, row_id: str):
        cls = _KINDS[kind]
        with self._mu:
            row = self._rows[kind].get(row_id)
            return cls(**row) if row else None

    def list(self, kind: str) -> List[Any]:
        cls = _KINDS[kind]
        with self._mu:
            return [cls(**r) for r in self._rows[kind].values()]

    def update(self, kind: str, row_id: str, **fields: Any):
        cls = _KINDS[kind]
        with self._mu:
            row = self._rows[kind].get(row_id)
            if row is None:
                raise KeyError(f"{kind} {row_id!r} not found")
            allowed = {f for f in row.keys() if f != "id"}
            for k, v in fields.items():
                if k not in allowed:
                    raise ValueError(f"unknown field {k!r} for {kind}")
                row[k] = v
            self._persist(kind, row_id, row)
            return cls(**row)

    def delete(self, kind: str, row_id: str) -> None:
        with self._mu:
            if self._rows[kind].pop(row_id, None) is None:
                raise KeyError(f"{kind} {row_id!r} not found")
            self._persist(kind, row_id, None)

    # -- cluster conveniences ------------------------------------------------

    def ensure_default_cluster(self) -> ClusterRecord:
        """The reference seeds a default scheduler cluster at migration
        time; dynconfig consumers need it to exist."""
        with self._mu:
            for row in self._rows["cluster"].values():
                if row.get("is_default"):
                    return ClusterRecord(**row)
        return self.create(
            "cluster", id="default", name="default", is_default=True,
            scheduler_cluster_config={
                "candidate_parent_limit": 4,
                "filter_parent_limit": 15,
            },
            client_config={"load_limit": 50},
        )

    def cluster_config(self, cluster_id: str) -> Dict[str, Any]:
        """The dynconfig payload a scheduler polls
        (scheduling.go:404-410 limit consumption)."""
        cluster = self.get("cluster", cluster_id)
        if cluster is None:
            raise KeyError(f"cluster {cluster_id!r} not found")
        return {
            "cluster_id": cluster.id,
            "scheduler_cluster_config": dict(cluster.scheduler_cluster_config),
            "client_config": dict(cluster.client_config),
        }
