"""Manager CRUD resources: applications + scheduler-cluster records.

Reference: the manager's GORM models and REST handlers
(manager/handlers/application.go, scheduler_cluster.go,
models/application.go, models/scheduler_cluster.go) — applications tag
traffic for per-app policy; scheduler-cluster rows carry the CLUSTER
CONFIG (candidate/filter parent limits, client load limits) that
schedulers consume through dynconfig (scheduler/scheduling/
scheduling.go:404-410 reads the limits per scheduling pass).

Storage: JSON rows behind the manager's state seam
(manager/state.StateBackend — sqlite embedded, external SQL/KV for HA),
write-through with in-memory reads.
"""

from __future__ import annotations

import re
import threading
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Application:
    """models/application.go row: per-application traffic identity."""

    id: str
    name: str
    url: str = ""
    bio: str = ""
    priority: int = 0


@dataclass
class ClusterRecord:
    """models/scheduler_cluster.go row + its config blobs.

    ``scheduler_cluster_config`` carries the scheduling limits the
    scheduler's dynconfig applies live (candidate_parent_limit,
    filter_parent_limit); ``client_config`` the daemon-side knobs
    (load_limit); ``scopes`` the searcher's affinity inputs;
    ``tenant_qos`` the per-tenant QoS table (DESIGN.md §26: priority
    class, weight, upload-bandwidth cap, announce-rate cap per tenant)
    published with the cluster dynconfig and re-published by schedulers
    on announce answers.
    """

    id: str
    name: str = ""
    is_default: bool = False
    scheduler_cluster_config: Dict[str, Any] = field(default_factory=dict)
    client_config: Dict[str, Any] = field(default_factory=dict)
    scopes: Dict[str, Any] = field(default_factory=dict)
    tenant_qos: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ConfigRecord:
    """models/config.go row: a named operator key-value setting."""

    id: str
    name: str
    value: str = ""
    bio: str = ""


_KINDS = {
    "application": Application,
    "cluster": ClusterRecord,
    "config": ConfigRecord,
}

# Row ids appear in URLs, sqlite keys, and the console DOM — keep them
# boring.  (Client-supplied ids with quotes were an XSS vector through the
# console's inline handlers.)
_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

# The scheduling limits a cluster row may carry; values must be ints —
# a half-applied config on the scheduler side (int("oops") mid-loop) is
# worse than a rejected write, so validation lives on the WRITE path.
_CLUSTER_INT_KEYS = (
    "candidate_parent_limit",
    "filter_parent_limit",
    "retry_limit",
    "retry_back_to_source_limit",
    "load_limit",
)


def _validate_cluster_blobs(fields: Dict[str, Any]) -> None:
    for blob_key in ("scheduler_cluster_config", "client_config", "scopes"):
        blob = fields.get(blob_key)
        if blob is None:
            continue
        if not isinstance(blob, dict):
            raise ValueError(f"{blob_key} must be an object, got {type(blob).__name__}")
        for k in _CLUSTER_INT_KEYS:
            if k in blob and not isinstance(blob[k], int):
                raise ValueError(f"{blob_key}.{k} must be an integer")
    qos = fields.get("tenant_qos")
    if qos is not None:
        # Validation lives on the WRITE path (the scheduler/daemon side
        # skips malformed payloads silently — a rejected write is loud,
        # a half-applied policy is not).
        from ..qos.policy import parse_tenant_qos

        parse_tenant_qos(qos)


class CrudStore:
    """JSON-row store for the manager's CRUD resources."""

    def __init__(self, db_path: Optional[str] = None, *, backend=None) -> None:
        self._mu = threading.RLock()
        self._rows: Dict[str, Dict[str, dict]] = {k: {} for k in _KINDS}
        self._table = None
        if backend is None and db_path:
            from .state import SQLiteBackend

            backend = SQLiteBackend(db_path)
        if backend is not None:
            self._table = backend.table("crud")
            for key, row in self._table.load_all().items():
                kind, _, id_ = key.partition(":")
                if kind in self._rows:
                    self._rows[kind][id_] = row

    def _persist(self, kind: str, id_: str, row: Optional[dict]) -> None:
        if self._table is None:
            return
        if row is None:
            self._table.delete(f"{kind}:{id_}")
        else:
            self._table.put(f"{kind}:{id_}", row)

    # -- generic ops ---------------------------------------------------------

    def create(self, kind: str, **fields: Any):
        cls = _KINDS[kind]
        if kind == "cluster":
            _validate_cluster_blobs(fields)
        if kind == "config":
            # models/config.go declares name UNIQUE — a duplicate-named
            # setting would resolve ambiguously by consumer ordering.
            name = fields.get("name")
            if not name:
                raise ValueError("config name required")
            with self._mu:
                if any(
                    r.get("name") == name
                    for r in self._rows["config"].values()
                ):
                    raise ValueError(f"config {name!r} already exists")
        with self._mu:
            # str-coerce BEFORE storing: a JSON-integer id would otherwise
            # live under an int key the string-keyed REST routes miss.
            row_id = str(fields.pop("id", None) or uuid.uuid4().hex[:12])
            if not _ID_RE.match(row_id):
                raise ValueError(f"invalid {kind} id {row_id!r}")
            if row_id in self._rows[kind]:
                raise ValueError(f"{kind} {row_id!r} already exists")
            obj = cls(id=row_id, **fields)
            self._rows[kind][row_id] = asdict(obj)
            self._persist(kind, row_id, self._rows[kind][row_id])
            return obj

    def get(self, kind: str, row_id: str):
        cls = _KINDS[kind]
        with self._mu:
            row = self._rows[kind].get(row_id)
            return cls(**row) if row else None

    def list(self, kind: str) -> List[Any]:
        cls = _KINDS[kind]
        with self._mu:
            return [cls(**r) for r in self._rows[kind].values()]

    def update(self, kind: str, row_id: str, **fields: Any):
        cls = _KINDS[kind]
        if kind == "cluster":
            _validate_cluster_blobs(fields)
        with self._mu:
            row = self._rows[kind].get(row_id)
            if row is None:
                raise KeyError(f"{kind} {row_id!r} not found")
            # Declared fields, not the row's keys: a row persisted before
            # a schema gained a field (e.g. tenant_qos) must still accept
            # updates to it.
            import dataclasses as _dc

            allowed = {f.name for f in _dc.fields(cls)} - {"id"}
            for k, v in fields.items():
                if k not in allowed:
                    raise ValueError(f"unknown field {k!r} for {kind}")
                row[k] = v
            self._persist(kind, row_id, row)
            return cls(**row)

    def delete(self, kind: str, row_id: str) -> None:
        with self._mu:
            if self._rows[kind].pop(row_id, None) is None:
                raise KeyError(f"{kind} {row_id!r} not found")
            self._persist(kind, row_id, None)

    # -- cluster conveniences ------------------------------------------------

    def ensure_default_cluster(self) -> ClusterRecord:
        """The reference seeds a default scheduler cluster at migration
        time; dynconfig consumers need it to exist."""
        with self._mu:
            for row in self._rows["cluster"].values():
                if row.get("is_default"):
                    return ClusterRecord(**row)
            # An id="default" row whose is_default flag was cleared by an
            # update still satisfies the invariant — re-creating it would
            # raise "already exists" on every boot (a restart crash loop).
            row = self._rows["cluster"].get("default")
            if row is not None:
                return ClusterRecord(**row)
        return self.create(
            "cluster", id="default", name="default", is_default=True,
            scheduler_cluster_config={
                "candidate_parent_limit": 4,
                "filter_parent_limit": 15,
            },
            client_config={"load_limit": 50},
        )

    def cluster_config(self, cluster_id: str) -> Dict[str, Any]:
        """The dynconfig payload a scheduler polls
        (scheduling.go:404-410 limit consumption)."""
        cluster = self.get("cluster", cluster_id)
        if cluster is None:
            raise KeyError(f"cluster {cluster_id!r} not found")
        return {
            "cluster_id": cluster.id,
            "scheduler_cluster_config": dict(cluster.scheduler_cluster_config),
            "client_config": dict(cluster.client_config),
            "tenant_qos": dict(cluster.tenant_qos),
        }
