"""Versioned model registry with single-active activation.

Reference semantics (manager/):
- models are immutable versioned objects keyed (scheduler_id, name, type,
  version); CreateModel writes the artifact to object storage and records
  a DB row with evaluation metrics, state=inactive
  (manager_server_v1.go:802-901, models/model.go:35-46);
- activation is transactional and single-active per scheduler: activating
  version V first deactivates the currently-active version, then flips V
  (service/model.go:103-190 — the config.pbtxt version-policy rewrite
  becomes a pointer update here);
- model types: ``gnn`` | ``mlp`` (models/model.go).

The artifact bytes here are trainer/export.py scorer blobs (npz), stored
in a content-addressed blob store (filesystem dir or in-memory), replacing
the reference's S3/OSS Triton layout (types/model.go:66-73).
"""

from __future__ import annotations

import enum
import os
import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, List, Optional

if TYPE_CHECKING:  # state seam type (no runtime import needed)
    from .state import StateBackend

class ModelState(str, enum.Enum):
    """Version lifecycle.  The reference knows only active/inactive
    (models/model.go); SHADOW and CANARY are the rollout plane's
    intermediate gates (rollout/controller.py): a SHADOW version is
    re-scored against the active one off the hot path, a CANARY version
    serves a deterministic hash-bucketed slice of announces.  At most
    one version per (scheduler_id, name) holds each of ACTIVE / SHADOW /
    CANARY."""

    ACTIVE = "active"
    INACTIVE = "inactive"
    SHADOW = "shadow"
    CANARY = "canary"


# States a rollout candidate occupies while under evaluation.
CANDIDATE_STATES = (ModelState.SHADOW, ModelState.CANARY)


class ArtifactDigestError(ValueError):
    """Stored blob bytes do not hash to the digest recorded at
    create_model — the artifact was corrupted or swapped in place."""


@dataclass
class Model:
    """One model version (manager/models/model.go:35-46)."""

    id: str
    name: str
    type: str                      # "gnn" | "mlp"
    version: int
    scheduler_id: str
    state: ModelState = ModelState.INACTIVE
    evaluation: Dict[str, float] = field(default_factory=dict)
    blob_key: str = ""
    # sha256 hex of the artifact bytes, recorded at create_model and
    # verified on every load_artifact (rows predating the field carry "").
    artifact_digest: str = ""
    created_at: float = field(default_factory=time.time)
    updated_at: float = field(default_factory=time.time)


class BlobStore:
    """Content-addressed artifact store (objectstorage replacement).

    ``directory=None`` keeps blobs in memory (tests / embedded runs).
    """

    def __init__(self, directory: Optional[str] = None) -> None:
        self._dir = directory
        self._mem: Dict[str, bytes] = {}
        if directory:
            os.makedirs(directory, exist_ok=True)

    def put(self, key: str, data: bytes) -> None:
        if self._dir:
            path = os.path.join(self._dir, key)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)  # atomic: readers never see partial blobs
        else:
            self._mem[key] = data

    def get(self, key: str) -> bytes:
        if self._dir:
            with open(os.path.join(self._dir, key), "rb") as f:
                return f.read()
        return self._mem[key]

    def exists(self, key: str) -> bool:
        if self._dir:
            return os.path.exists(os.path.join(self._dir, key))
        return key in self._mem


class KVBlobStore:
    """Artifact store riding the manager's StateBackend (one row per
    blob, base64 docs).  The HA composition uses this instead of a blob
    directory so artifacts flow through the SAME replication log as
    their registry rows — a promoted standby can serve
    ``models:artifact`` without a shared filesystem (the reference
    stores artifacts in S3/OSS, which is externally HA the same way).

    Single-writer discipline: ``put`` is only reached from
    ``ModelRegistry.create_model`` under ``ModelRegistry._mu`` (the
    registry row and its blob row are one logical write); no lock of
    its own, so the lock hierarchy stays flat (§16)."""

    def __init__(self, backend) -> None:
        import base64 as _b64

        self._b64 = _b64
        self._table = backend.table("blobs")
        # Recovery loader (DF014): blobs are fetched by key on demand;
        # the boot-time load only proves the table reads back.
        self._known = set(self._table.load_all())

    def put(self, key: str, data: bytes) -> None:
        self._table.put(key, {"b64": self._b64.b64encode(data).decode()})
        self._known.add(key)

    def get(self, key: str) -> bytes:
        doc = self._table.get(key)
        if doc is None:
            raise KeyError(key)
        return self._b64.b64decode(doc["b64"])

    def exists(self, key: str) -> bool:
        return self._table.get(key) is not None


def _model_to_doc(m: Model) -> dict:
    return {
        "id": m.id, "name": m.name, "type": m.type, "version": m.version,
        "scheduler_id": m.scheduler_id, "state": m.state.value,
        "evaluation": m.evaluation, "blob_key": m.blob_key,
        "artifact_digest": m.artifact_digest,
        "created_at": m.created_at, "updated_at": m.updated_at,
    }


def _model_from_doc(d: dict) -> Model:
    return Model(
        id=d["id"], name=d["name"], type=d["type"], version=d["version"],
        scheduler_id=d["scheduler_id"], state=ModelState(d["state"]),
        evaluation=dict(d["evaluation"]), blob_key=d["blob_key"],
        artifact_digest=d.get("artifact_digest", ""),  # pre-digest rows
        created_at=d["created_at"], updated_at=d["updated_at"],
    )


class ModelRegistry:
    """The registry service (manager CreateModel + model REST CRUD).

    Durable rows live behind the manager's state seam
    (manager/state.StateBackend — sqlite embedded, external SQL/KV for
    HA): every mutation writes through and a restart reloads the table —
    models survive the manager the way the reference's DB rows do.
    ``db_path`` is the convenience form (a private SQLiteBackend).
    """

    def __init__(
        self,
        blob_store: Optional[BlobStore] = None,
        *,
        db_path: Optional[str] = None,
        backend=None,
    ) -> None:
        self._mu = threading.RLock()
        self._models: Dict[str, Model] = {}
        self.blobs = blob_store or BlobStore()
        self._table = None
        if backend is None and db_path:
            from .state import SQLiteBackend

            backend = SQLiteBackend(db_path)
        if backend is not None:
            self._table = backend.table("models")
            self._models = {
                k: _model_from_doc(d) for k, d in self._table.load_all().items()
            }

    def _persist(self, *models: Model) -> None:
        if self._table is not None:
            # ONE transaction: activation flips two rows and a crash
            # between separate commits would leave two ACTIVE versions.
            self._table.put_many({m.id: _model_to_doc(m) for m in models})

    # -- CreateModel (manager_server_v1.go:802-901) -------------------------

    def create_model(
        self,
        *,
        name: str,
        type: str,
        scheduler_id: str,
        artifact: bytes,
        evaluation: Optional[Dict[str, float]] = None,
        ip: str = "",
        hostname: str = "",
    ) -> Model:
        # mlp_int8 / mlp_bf16: post-training-quantized serving variants
        # (trainer/export.quantize_scorer) — registered as CANDIDATEs and
        # admitted to ACTIVE only through the rollout plane's replay
        # gates (DESIGN.md §18).
        if type not in ("gnn", "mlp", "mlp_int8", "mlp_bf16"):
            raise ValueError(f"unknown model type {type!r}")
        with self._mu:
            version = (
                max(
                    (
                        m.version
                        for m in self._models.values()
                        if m.scheduler_id == scheduler_id and m.name == name
                    ),
                    default=0,
                )
                + 1
            )
            # Model identity is keyed by (scheduler_id, name): hashing only
            # ip/hostname would let two schedulers on one machine overwrite
            # each other's registry rows.  Full-id hash (no prefix
            # truncation) for the blob key too.
            from ..utils.digest import sha256_from_strings

            model_id = sha256_from_strings(scheduler_id, name)[:32]
            sched_key = sha256_from_strings(scheduler_id)[:24]
            blob_key = f"{name}-{sched_key}-v{version}.npz"
            self.blobs.put(blob_key, artifact)
            import hashlib

            model = Model(
                id=f"{model_id}-v{version}",
                name=name,
                type=type,
                version=version,
                scheduler_id=scheduler_id,
                evaluation=dict(evaluation or {}),
                blob_key=blob_key,
                # Content address for REAL: the row pins the bytes it was
                # created with, and load_artifact refuses anything else.
                artifact_digest=hashlib.sha256(artifact).hexdigest(),
            )
            self._models[model.id] = model
            self._persist(model)
            return model

    # -- activation (service/model.go:103-190) ------------------------------

    def activate(self, model_id: str) -> Model:
        """Single-active per (scheduler, name): flips the previous active
        version to inactive and the named version to active, atomically."""
        with self._mu:
            model = self._models.get(model_id)
            if model is None:
                raise KeyError(model_id)
            changed = [model]
            for other in self._models.values():
                if (
                    other.scheduler_id == model.scheduler_id
                    and other.name == model.name
                    and other.state is ModelState.ACTIVE
                ):
                    other.state = ModelState.INACTIVE
                    other.updated_at = time.time()
                    changed.append(other)
            model.state = ModelState.ACTIVE
            model.updated_at = time.time()
            self._persist(*changed)
            return model

    def deactivate(self, model_id: str) -> Model:
        with self._mu:
            model = self._models[model_id]
            model.state = ModelState.INACTIVE
            model.updated_at = time.time()
            self._persist(model)
            return model

    def set_state(self, model_id: str, state: ModelState) -> Model:
        """Rollout-plane transitions (SHADOW/CANARY/INACTIVE).  Like
        ``activate``, the flip is exclusive per (scheduler_id, name) for
        SHADOW and CANARY — one candidate at a time — and all touched
        rows persist in ONE transaction.  ACTIVE must go through
        ``activate`` (it owns the single-active flip)."""
        if state is ModelState.ACTIVE:
            return self.activate(model_id)
        with self._mu:
            model = self._models.get(model_id)
            if model is None:
                raise KeyError(model_id)
            changed = [model]
            if state in CANDIDATE_STATES:
                for other in self._models.values():
                    if (
                        other is not model
                        and other.scheduler_id == model.scheduler_id
                        and other.name == model.name
                        and other.state in CANDIDATE_STATES
                    ):
                        other.state = ModelState.INACTIVE
                        other.updated_at = time.time()
                        changed.append(other)
            model.state = state
            model.updated_at = time.time()
            self._persist(*changed)
            return model

    def delete(self, model_id: str) -> None:
        with self._mu:
            self._models.pop(model_id, None)
            if self._table is not None:
                self._table.delete(model_id)

    # -- reads ---------------------------------------------------------------

    def get(self, model_id: str) -> Optional[Model]:
        with self._mu:
            return self._models.get(model_id)

    def list(
        self,
        *,
        scheduler_id: Optional[str] = None,
        name: Optional[str] = None,
        type: Optional[str] = None,
        state: Optional[ModelState] = None,
    ) -> List[Model]:
        with self._mu:
            out = []
            for m in self._models.values():
                if scheduler_id is not None and m.scheduler_id != scheduler_id:
                    continue
                if name is not None and m.name != name:
                    continue
                if type is not None and m.type != type:
                    continue
                if state is not None and m.state is not state:
                    continue
                out.append(m)
            return sorted(out, key=lambda m: (m.name, m.version))

    def active_model(self, scheduler_id: str, name: str) -> Optional[Model]:
        """What the scheduler's dynconfig poll asks: the active version."""
        with self._mu:
            for m in self._models.values():
                if (
                    m.scheduler_id == scheduler_id
                    and m.name == name
                    and m.state is ModelState.ACTIVE
                ):
                    return m
            return None

    def candidate_model(self, scheduler_id: str, name: str) -> Optional[Model]:
        """The version under rollout evaluation (SHADOW or CANARY), if
        any — what the scheduler's candidate poll asks."""
        with self._mu:
            for m in self._models.values():
                if (
                    m.scheduler_id == scheduler_id
                    and m.name == name
                    and m.state in CANDIDATE_STATES
                ):
                    return m
            return None

    def load_artifact(self, model: Model) -> bytes:
        data = self.blobs.get(model.blob_key)
        if model.artifact_digest:
            import hashlib

            got = hashlib.sha256(data).hexdigest()
            if got != model.artifact_digest:
                raise ArtifactDigestError(
                    f"{model.id}: artifact sha256 {got[:12]}… != recorded "
                    f"{model.artifact_digest[:12]}… — blob corrupted or swapped"
                )
        return data
