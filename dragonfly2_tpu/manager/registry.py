"""Versioned model registry with single-active activation.

Reference semantics (manager/):
- models are immutable versioned objects keyed (scheduler_id, name, type,
  version); CreateModel writes the artifact to object storage and records
  a DB row with evaluation metrics, state=inactive
  (manager_server_v1.go:802-901, models/model.go:35-46);
- activation is transactional and single-active per scheduler: activating
  version V first deactivates the currently-active version, then flips V
  (service/model.go:103-190 — the config.pbtxt version-policy rewrite
  becomes a pointer update here);
- model types: ``gnn`` | ``mlp`` (models/model.go).

The artifact bytes here are trainer/export.py scorer blobs (npz), stored
in a content-addressed blob store (filesystem dir or in-memory), replacing
the reference's S3/OSS Triton layout (types/model.go:66-73).
"""

from __future__ import annotations

import enum
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

class ModelState(str, enum.Enum):
    ACTIVE = "active"
    INACTIVE = "inactive"


@dataclass
class Model:
    """One model version (manager/models/model.go:35-46)."""

    id: str
    name: str
    type: str                      # "gnn" | "mlp"
    version: int
    scheduler_id: str
    state: ModelState = ModelState.INACTIVE
    evaluation: Dict[str, float] = field(default_factory=dict)
    blob_key: str = ""
    created_at: float = field(default_factory=time.time)
    updated_at: float = field(default_factory=time.time)


class BlobStore:
    """Content-addressed artifact store (objectstorage replacement).

    ``directory=None`` keeps blobs in memory (tests / embedded runs).
    """

    def __init__(self, directory: Optional[str] = None) -> None:
        self._dir = directory
        self._mem: Dict[str, bytes] = {}
        if directory:
            os.makedirs(directory, exist_ok=True)

    def put(self, key: str, data: bytes) -> None:
        if self._dir:
            path = os.path.join(self._dir, key)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)  # atomic: readers never see partial blobs
        else:
            self._mem[key] = data

    def get(self, key: str) -> bytes:
        if self._dir:
            with open(os.path.join(self._dir, key), "rb") as f:
                return f.read()
        return self._mem[key]

    def exists(self, key: str) -> bool:
        if self._dir:
            return os.path.exists(os.path.join(self._dir, key))
        return key in self._mem


class _SQLiteModelStore:
    """Durable model rows (reference: manager/models + database — GORM over
    MySQL/Postgres; sqlite is the embedded equivalent).  The registry is
    the source of truth in memory; every mutation writes through, and a
    restarted manager reloads the full model table."""

    def __init__(self, path: str) -> None:
        import sqlite3

        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._mu = threading.Lock()
        with self._mu:
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS models (
                    id TEXT PRIMARY KEY,
                    name TEXT NOT NULL,
                    type TEXT NOT NULL,
                    version INTEGER NOT NULL,
                    scheduler_id TEXT NOT NULL,
                    state TEXT NOT NULL,
                    evaluation TEXT NOT NULL,
                    blob_key TEXT NOT NULL,
                    created_at REAL NOT NULL,
                    updated_at REAL NOT NULL
                )"""
            )
            self._conn.commit()

    def upsert_many(self, models) -> None:
        """All rows in ONE transaction — activation flips two rows and a
        crash between separate commits would leave two ACTIVE versions."""
        import json

        rows = [
            (
                m.id, m.name, m.type, m.version, m.scheduler_id,
                m.state.value, json.dumps(m.evaluation), m.blob_key,
                m.created_at, m.updated_at,
            )
            for m in models
        ]
        with self._mu:
            self._conn.executemany(
                "INSERT OR REPLACE INTO models VALUES (?,?,?,?,?,?,?,?,?,?)", rows
            )
            self._conn.commit()

    def upsert(self, m: Model) -> None:
        self.upsert_many([m])

    def delete(self, model_id: str) -> None:
        with self._mu:
            self._conn.execute("DELETE FROM models WHERE id = ?", (model_id,))
            self._conn.commit()

    def load_all(self) -> Dict[str, Model]:
        import json

        with self._mu:
            rows = self._conn.execute("SELECT * FROM models").fetchall()
        out: Dict[str, Model] = {}
        for r in rows:
            out[r[0]] = Model(
                id=r[0], name=r[1], type=r[2], version=r[3], scheduler_id=r[4],
                state=ModelState(r[5]), evaluation=json.loads(r[6]),
                blob_key=r[7], created_at=r[8], updated_at=r[9],
            )
        return out

    def close(self) -> None:
        with self._mu:
            self._conn.close()


class ModelRegistry:
    """The registry service (manager CreateModel + model REST CRUD).

    ``db_path`` enables durable rows (sqlite): every mutation writes
    through and a restart reloads the table — models survive the manager
    the way the reference's DB rows do.
    """

    def __init__(
        self,
        blob_store: Optional[BlobStore] = None,
        *,
        db_path: Optional[str] = None,
    ) -> None:
        self._mu = threading.RLock()
        self._models: Dict[str, Model] = {}
        self.blobs = blob_store or BlobStore()
        self._db: Optional[_SQLiteModelStore] = None
        if db_path:
            self._db = _SQLiteModelStore(db_path)
            self._models = self._db.load_all()

    def _persist(self, *models: Model) -> None:
        if self._db is not None:
            self._db.upsert_many(models)

    # -- CreateModel (manager_server_v1.go:802-901) -------------------------

    def create_model(
        self,
        *,
        name: str,
        type: str,
        scheduler_id: str,
        artifact: bytes,
        evaluation: Optional[Dict[str, float]] = None,
        ip: str = "",
        hostname: str = "",
    ) -> Model:
        if type not in ("gnn", "mlp"):
            raise ValueError(f"unknown model type {type!r}")
        with self._mu:
            version = (
                max(
                    (
                        m.version
                        for m in self._models.values()
                        if m.scheduler_id == scheduler_id and m.name == name
                    ),
                    default=0,
                )
                + 1
            )
            # Model identity is keyed by (scheduler_id, name): hashing only
            # ip/hostname would let two schedulers on one machine overwrite
            # each other's registry rows.  Full-id hash (no prefix
            # truncation) for the blob key too.
            from ..utils.digest import sha256_from_strings

            model_id = sha256_from_strings(scheduler_id, name)[:32]
            sched_key = sha256_from_strings(scheduler_id)[:24]
            blob_key = f"{name}-{sched_key}-v{version}.npz"
            self.blobs.put(blob_key, artifact)
            model = Model(
                id=f"{model_id}-v{version}",
                name=name,
                type=type,
                version=version,
                scheduler_id=scheduler_id,
                evaluation=dict(evaluation or {}),
                blob_key=blob_key,
            )
            self._models[model.id] = model
            self._persist(model)
            return model

    # -- activation (service/model.go:103-190) ------------------------------

    def activate(self, model_id: str) -> Model:
        """Single-active per (scheduler, name): flips the previous active
        version to inactive and the named version to active, atomically."""
        with self._mu:
            model = self._models.get(model_id)
            if model is None:
                raise KeyError(model_id)
            changed = [model]
            for other in self._models.values():
                if (
                    other.scheduler_id == model.scheduler_id
                    and other.name == model.name
                    and other.state is ModelState.ACTIVE
                ):
                    other.state = ModelState.INACTIVE
                    other.updated_at = time.time()
                    changed.append(other)
            model.state = ModelState.ACTIVE
            model.updated_at = time.time()
            self._persist(*changed)
            return model

    def deactivate(self, model_id: str) -> Model:
        with self._mu:
            model = self._models[model_id]
            model.state = ModelState.INACTIVE
            model.updated_at = time.time()
            self._persist(model)
            return model

    def delete(self, model_id: str) -> None:
        with self._mu:
            self._models.pop(model_id, None)
            if self._db is not None:
                self._db.delete(model_id)

    # -- reads ---------------------------------------------------------------

    def get(self, model_id: str) -> Optional[Model]:
        with self._mu:
            return self._models.get(model_id)

    def list(
        self,
        *,
        scheduler_id: Optional[str] = None,
        name: Optional[str] = None,
        type: Optional[str] = None,
        state: Optional[ModelState] = None,
    ) -> List[Model]:
        with self._mu:
            out = []
            for m in self._models.values():
                if scheduler_id is not None and m.scheduler_id != scheduler_id:
                    continue
                if name is not None and m.name != name:
                    continue
                if type is not None and m.type != type:
                    continue
                if state is not None and m.state is not state:
                    continue
                out.append(m)
            return sorted(out, key=lambda m: (m.name, m.version))

    def active_model(self, scheduler_id: str, name: str) -> Optional[Model]:
        """What the scheduler's dynconfig poll asks: the active version."""
        with self._mu:
            for m in self._models.values():
                if (
                    m.scheduler_id == scheduler_id
                    and m.name == name
                    and m.state is ModelState.ACTIVE
                ):
                    return m
            return None

    def load_artifact(self, model: Model) -> bytes:
        return self.blobs.get(model.blob_key)
