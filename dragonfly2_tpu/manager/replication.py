"""Manager HA: log-shipping replication over the StateBackend seam.

The reference gets control-plane HA for free from Redis+MySQL (the
manager sits on externally HA-able stores, database.go:50-59); our
embedded manager concentrates every durable surface behind ONE seam —
``manager/state.py``'s ``StateBackend`` — which makes that seam the
right place to replicate.  Three pieces (DESIGN.md §20):

- **write-ahead op log** (``ReplicationLog``): every ``put``/
  ``put_many``/``delete`` a leader commits is first appended to a
  monotonic (term, seq) log riding two reserved namespaces of the same
  backend (``replication_log`` / ``replication_meta``), THEN applied to
  the data namespace.  Ops are absolute upserts/deletes, so boot-time
  replay of the unapplied tail is idempotent — a crash between the log
  append and the data commit converges on restart.

- **roles + lease fencing** (``ReplicatedStateBackend``): a leader may
  commit only while its lease (renewed every ``ttl/3`` by
  ``LeaseKeeper``) is unexpired; an expired or fenced leader's writes
  raise ``NotLeaderError`` — the zombie cannot commit.  The lease is
  HMAC-signed with the shared ``lease_secret`` so a follower only
  honours (and only defers to) a leader that holds the secret; terms
  are fenced monotonically — observing a higher term permanently
  demotes this node for that term.

- **follower tailing + takeover** (``LogFollower``): a standby tails
  the leader's ``/api/v1/replication:*`` REST surface (snapshot
  bootstrap for pre-log rows, then incremental log pulls), applies ops
  into its OWN backend, answers lag/health probes, and — when the last
  fresh lease it saw has aged past expiry — promotes itself with
  ``term+1``.  After promotion it rejects ops from any lower term
  (``StaleTermError``), which is what makes a partitioned old leader's
  history unshippable.

The data-bearing routes (``:log``/``:snapshot``) carry every namespace
of the backend — including users/PATs credential rows on default
deployments — so they require proof of the shared ``lease_secret``: an
HMAC request token (:func:`sign_replication_request`) in the
``X-DF-Replication-Auth`` header.  The log is compacted: entries far
enough below the applied watermark truncate away, and a follower that
has fallen behind the retained floor re-bootstraps from a snapshot.

Every network/commit edge here is a DF004 chaos seam
(``state.replicate.*`` / ``manager.lease.*``) and every write path is
inventoried in ``records/state_contracts.py`` (the ``replicators``
section covers the dynamic-namespace apply sites) so the DF014 static
pass and the runtime crash witness gate this subsystem like any other.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Set

from ..utils import faultinject
from .state import KVTable, StateBackend

logger = logging.getLogger(__name__)

# Namespaces reserved for the replication machinery itself: never
# shipped in snapshots, never re-replicated.
REPLICATION_NAMESPACES = ("replication_log", "replication_meta")

# How many lease intervals of silence a follower tolerates beyond the
# advertised expiry before taking over (absorbs one lost poll).
DEFAULT_TAKEOVER_GRACE = 0.5


class NotLeaderError(RuntimeError):
    """Write rejected: this node is a standby or its lease expired."""


class StaleTermError(NotLeaderError):
    """Op or write carries a term older than one already observed —
    the sender is a fenced zombie leader."""


def sign_lease(secret: str, leader_id: str, term: int) -> str:
    """HMAC-SHA256 over the lease identity.  The signature authenticates
    WHO holds WHICH term (a forged lease cannot defer a follower);
    freshness is the transport's job — ``expires_in_s`` is relative to
    the fetch that returned it, so no cross-host clock is compared."""
    msg = f"{leader_id}:{term}".encode()
    return hmac.new(secret.encode(), msg, hashlib.sha256).hexdigest()


def verify_lease(secret: str, lease: dict) -> bool:
    try:
        want = sign_lease(secret, str(lease["leader_id"]), int(lease["term"]))
        return hmac.compare_digest(want, str(lease.get("sig", "")))
    except (KeyError, TypeError, ValueError):
        return False


# Header carrying the replication-fetch auth token.  The ``:log`` and
# ``:snapshot`` routes dump every namespace of the backend — users/PATs
# credential rows included on default deployments — so they are gated
# on possession of the shared ``lease_secret`` rather than left open
# like the role/term health probe (``:status``).
REPLICATION_AUTH_HEADER = "X-DF-Replication-Auth"


def sign_replication_request(secret: str, path: str) -> str:
    """HMAC-SHA256 token a replica presents to fetch ``path`` (the
    route path, query excluded).  Proves possession of ``lease_secret``;
    the routes are read-only, so there is no replay surface to bind —
    an observer close enough to replay could read the response anyway."""
    msg = f"replication-fetch:{path}".encode()
    return hmac.new(secret.encode(), msg, hashlib.sha256).hexdigest()


def verify_replication_request(secret: str, path: str, token: str) -> bool:
    want = sign_replication_request(secret, path)
    return hmac.compare_digest(want, str(token or ""))


def probe_peer_term(urls, *, timeout: float = 3.0):
    """Best-effort sweep of peer replicas' ``:status`` probes; returns
    ``(term, url)`` for the highest term observed (``(0, "")`` when no
    peer answers).  A node configured as leader calls this at boot so a
    restarted fenced leader discovers the successor's term and rejoins
    as a standby instead of resurrecting its stale term."""
    best_term, best_url = 0, ""
    for url in urls:
        url = str(url).rstrip("/")
        if not url:
            continue
        try:
            faultinject.fire(f"state.replicate.{'probe'}")
            with urllib.request.urlopen(
                url + "/api/v1/replication:status", timeout=timeout
            ) as resp:
                status = json.loads(resp.read())
            term = int(status.get("term", 0))
        except Exception as exc:  # noqa: BLE001 — a dead peer is no vote
            logger.debug("peer probe %s unreachable: %s", url, exc)
            continue
        if term > best_term:
            best_term, best_url = term, url
    return best_term, best_url


class ReplicationLog:
    """The durable op log + term/applied watermark, riding two reserved
    namespaces of the inner backend.

    ``append`` is the write-ahead half of every replicated commit; the
    applied watermark is flushed lazily (every ``APPLIED_FLUSH_EVERY``
    ops and at ``flush``) because replaying an already-applied absolute
    op at boot is a no-op — lag in the watermark costs replay work,
    never correctness.

    Locking: this object is owned by ONE ``ReplicatedStateBackend`` and
    every mutator runs under that backend's ``_mu`` (log order must BE
    commit order, so a separate log lock could only reorder or
    deadlock); ``seq``/``term``/``applied`` are single int reads (GIL
    atomic) safe for health probes.
    """

    APPLIED_FLUSH_EVERY = 64

    def __init__(self, backend: StateBackend) -> None:
        self._log = backend.table("replication_log")
        self._meta = backend.table("replication_meta")
        rows = self._log.load_all()
        self._seq = max((int(k) for k in rows), default=0)
        state = self._meta.load_all().get("state") or {}
        self._term = int(state.get("term", 1))
        self._applied = int(state.get("applied", 0))
        # Lowest seq still retained: entries below it were compacted
        # away (a follower that far behind re-bootstraps via snapshot).
        self._floor = int(state.get("floor", 1))
        self._unflushed = 0

    @staticmethod
    def _key(seq: int) -> str:
        return f"{seq:020d}"

    def append(self, entry: dict) -> int:
        """Assign the next seq and durably append ``entry`` (must carry
        ``term``/``ns``/``op`` + payload).  Returns the assigned seq."""
        self._seq += 1
        entry = dict(entry, seq=self._seq)
        self._log.put(self._key(self._seq), entry)
        return self._seq

    def discard(self, seq: int) -> None:
        """Remove a just-appended entry whose data commit FAILED: the
        caller was told the write failed, so the WAL row must not ship
        to followers or replay at boot as a write that never happened.
        The seq stays consumed (a gap) — reusing it could alias two
        different ops at one position."""
        self._log.delete(self._key(seq))

    def append_at(self, entry: dict) -> None:
        """Follower-side copy of a leader-assigned entry (keeps this
        node's log shippable to a cascading follower after promotion)."""
        seq = int(entry["seq"])
        self._log.put(self._key(seq), entry)
        if seq > self._seq:
            self._seq = seq

    def mark_applied(self, seq: int) -> None:
        if seq > self._applied:
            self._applied = seq
        self._unflushed += 1
        if self._unflushed >= self.APPLIED_FLUSH_EVERY:
            self.flush()

    def set_term(self, term: int) -> None:
        self._term = int(term)
        self.flush()

    def flush(self) -> None:
        self._meta.put(
            "state",
            {"term": self._term, "applied": self._applied,
             "floor": self._floor},
        )
        self._unflushed = 0

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def term(self) -> int:
        return self._term

    @property
    def applied(self) -> int:
        return self._applied

    @property
    def floor(self) -> int:
        return self._floor

    def entries_since(self, from_seq: int, limit: int = 500) -> List[dict]:
        """Entries with seq > ``from_seq``, ascending, at most ``limit``.
        Keys are zero-padded, so the lexicographic range scan IS the
        numeric one (SQLite serves it as an indexed WHERE key > ?)."""
        rows = self._log.load_range(self._key(max(from_seq, 0)))
        out = sorted(rows.values(), key=lambda e: int(e["seq"]))
        return out[:limit]

    def truncate_below(self, seq: int) -> None:
        """Compact: drop entries with seq < ``seq``, never past one
        beyond the applied watermark (the unapplied tail is the boot
        replay's crash-recovery record).  Growth stays bounded over a
        deployment's lifetime; a follower behind the new floor falls
        back to snapshot bootstrap."""
        seq = min(int(seq), self._applied + 1)
        if seq <= self._floor:
            return
        self._log.delete_range(self._key(seq))
        self._floor = seq
        self.flush()

    def pending(self) -> List[dict]:
        """The unapplied tail (crash between log append and data
        commit): replayed idempotently at boot."""
        return self.entries_since(self.applied)


class ReplicatedStateBackend(StateBackend):
    """StateBackend wrapper that write-ahead-logs every mutation and
    enforces leader/lease/term fencing at the commit point.

    Reads always pass through.  Writes require a live leader role
    unless issued inside :meth:`applying` (the follower's apply path
    and standby boot-time reconciliation)."""

    def __init__(
        self,
        inner: StateBackend,
        *,
        node_id: str = "manager",
        role: str = "leader",
        lease_ttl_s: float = 10.0,
        lease_secret: str = "dragonfly-manager-lease",
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if role not in ("leader", "standby"):
            raise ValueError(f"unknown replication role {role!r}")
        self._inner = inner
        self.node_id = node_id
        self.lease_ttl_s = float(lease_ttl_s)
        self.lease_secret = lease_secret
        self._clock = clock
        self._mu = threading.RLock()
        self._local = threading.local()
        self.log = ReplicationLog(inner)
        self._role = role
        self._term = self.log.term
        self._lease_expires_at: Optional[float] = None
        self.failovers = 0
        if role == "leader":
            self._lease_expires_at = self._clock() + self.lease_ttl_s
            self._replay_pending()
        self._set_role_metric()

    # -- role / lease ---------------------------------------------------

    def _set_role_metric(self) -> None:
        from ..rpc.metrics import MANAGER_ROLE

        for role in ("leader", "standby"):
            MANAGER_ROLE.set(1.0 if role == self._role else 0.0, role=role)

    @property
    def role(self) -> str:
        with self._mu:
            return self._role

    @property
    def term(self) -> int:
        with self._mu:
            return self._term

    def renew_lease(self) -> dict:
        """Extend this leader's lease by one TTL; raises if no longer
        leader (a fenced node cannot resurrect itself by renewing).

        An ALREADY-EXPIRED lease cannot be renewed either: past expiry a
        standby may have promoted at ``term+1``, and since followers
        pull (nothing pushes the successor's term back here), a paused/
        partitioned leader that resumed would otherwise re-extend its
        stale-term lease and keep committing forever — the split brain
        the lease exists to prevent.  Instead the node steps down; it
        rejoins via ``--replicate-from`` (or the ``ha.peers`` probe at
        next boot)."""
        faultinject.fire(f"manager.lease.{'renew'}")
        with self._mu:
            if self._role != "leader":
                raise NotLeaderError(
                    f"{self.node_id}: cannot renew lease in role {self._role}"
                )
            now = self._clock()
            if (
                self._lease_expires_at is not None
                and now >= self._lease_expires_at
            ):
                self._role = "standby"
                self._lease_expires_at = None
                self._set_role_metric()
                logger.warning(
                    "%s: lease expired before renewal at term %d — "
                    "stepping down (a successor may hold a higher term)",
                    self.node_id, self._term,
                )
                raise NotLeaderError(
                    f"{self.node_id}: lease expired at term {self._term}; "
                    "refusing to resurrect it — stepped down"
                )
            self._lease_expires_at = now + self.lease_ttl_s
            return self._lease_payload_locked()

    def _lease_payload_locked(self) -> dict:
        expires_in = 0.0
        if self._lease_expires_at is not None:
            expires_in = max(self._lease_expires_at - self._clock(), 0.0)
        return {
            "leader_id": self.node_id,
            "term": self._term,
            "ttl_s": self.lease_ttl_s,
            "expires_in_s": expires_in,
            "sig": sign_lease(self.lease_secret, self.node_id, self._term),
        }

    def lease_payload(self) -> dict:
        with self._mu:
            return self._lease_payload_locked()

    def promote(self, term: Optional[int] = None) -> int:
        """Standby → leader at ``term`` (default: observed term + 1).
        Replays any unapplied log tail, persists the new term, and
        starts a fresh lease."""
        faultinject.fire(f"manager.lease.{'promote'}")
        with self._mu:
            new_term = int(term) if term is not None else self._term + 1
            if new_term <= self._term and self._role == "leader":
                return self._term
            if new_term < self._term:
                raise StaleTermError(
                    f"promotion to term {new_term} below observed {self._term}"
                )
            self._term = new_term
            self._role = "leader"
            self._lease_expires_at = self._clock() + self.lease_ttl_s
            self.log.set_term(new_term)
            self.failovers += 1
            self._replay_pending_locked()
            self._set_role_metric()
        from ..rpc.metrics import MANAGER_FAILOVERS_TOTAL

        MANAGER_FAILOVERS_TOTAL.inc(node=self.node_id)
        logger.warning(
            "%s: promoted to leader (term %d)", self.node_id, new_term
        )
        return new_term

    def step_down(self) -> None:
        """Leader → standby (tests / graceful handover)."""
        with self._mu:
            self._role = "standby"
            self._lease_expires_at = None
            self._set_role_metric()

    def observe_term(self, term: int) -> None:
        """Fence: once a higher term is seen, this node can never commit
        under its old term again."""
        with self._mu:
            if term > self._term:
                if self._role == "leader":
                    logger.warning(
                        "%s: fenced by term %d (was leader at term %d)",
                        self.node_id, term, self._term,
                    )
                self._term = term
                self._role = "standby"
                self._lease_expires_at = None
                self.log.set_term(term)
                self._set_role_metric()

    # -- the write gate -------------------------------------------------

    def applying(self) -> "_Applying":
        """``with backend.applying(): ...`` — writes inside the block
        bypass the leader gate (the follower's apply path and standby
        boot-time reconciliation write replicated/derived state, not
        new client mutations)."""
        return _Applying(self)

    def _is_applying(self) -> bool:
        return getattr(self._local, "apply_depth", 0) > 0

    def _check_writable_locked(self) -> None:
        faultinject.fire(f"manager.lease.{'check'}")
        if self._role != "leader":
            raise NotLeaderError(
                f"{self.node_id}: standby (term {self._term}) rejects writes"
            )
        if (
            self._lease_expires_at is not None
            and self._clock() >= self._lease_expires_at
        ):
            raise NotLeaderError(
                f"{self.node_id}: lease expired at term {self._term} — "
                "a successor may hold a higher term; refusing to commit"
            )

    # Every COMPACT_EVERY commits, truncate log entries more than
    # RETAIN_OPS below the applied watermark (followers further behind
    # re-bootstrap via snapshot) — the log must not grow without bound
    # when whole artifacts ride it (KVBlobStore).
    COMPACT_EVERY = 256
    RETAIN_OPS = 1024

    def _commit_op(
        self, ns: str, op: str, payload: dict, fn: Callable[[], None]
    ) -> None:
        """Write-ahead append (term+seq) then the data commit, under one
        lock so the log order IS the commit order."""
        from ..utils.tracing import default_tracer

        faultinject.fire(f"state.replicate.{op}")
        if self._is_applying():
            fn()
            return
        # Span OUTSIDE the commit lock: a span closing while a project
        # lock is held would hand the lock witness a lock→exporter edge
        # the static graph (which doesn't traverse generator
        # contextmanagers) can never corroborate.  Same rule for the
        # commit-lag sketch observe below.
        from ..rpc.metrics import REPLICATION_COMMIT_SECONDS

        t0 = time.monotonic()
        with default_tracer.span(
            "manager/replicate.commit", ns=ns, op=op
        ) as span:
            self._commit_op_locked(ns, op, payload, fn, span)
        REPLICATION_COMMIT_SECONDS.observe(time.monotonic() - t0)

    def _commit_op_locked(
        self, ns: str, op: str, payload: dict, fn: Callable[[], None], span
    ) -> None:
        with self._mu:
            self._check_writable_locked()
            entry = dict(payload, term=self._term, ns=ns, op=op)
            seq = self.log.append(entry)
            span.set(seq=seq, term=self._term)
            try:
                fn()
            except BaseException:
                # The caller is told this write FAILED: the WAL row must
                # not outlive it — left in place it would ship to
                # followers (and replay at boot) as a write the leader's
                # own table never took, and the next successful commit
                # would advance the watermark past it, making the
                # divergence permanent.  A genuine crash (process death
                # between append and commit) still replays at boot: the
                # caller never got an answer there, so applying is the
                # correct resolution of the ambiguity.
                self.log.discard(seq)
                raise
            self.log.mark_applied(seq)
            if seq % self.COMPACT_EVERY == 0:
                self.log.truncate_below(self.log.applied - self.RETAIN_OPS + 1)

    def log_entries(self, from_seq: int, limit: int = 500) -> dict:
        """The ``:log`` route's payload, read under the commit lock so a
        concurrent commit's append-then-discard (failed data commit)
        can never be observed half-done by a polling follower."""
        with self._mu:
            return {
                "entries": self.log.entries_since(from_seq, limit),
                "seq": self.log.seq,
                "term": self._term,
                "floor": self.log.floor,
            }

    # -- follower application ------------------------------------------

    def _apply_entry_locked(self, entry: dict) -> None:
        table = self._inner.table(entry["ns"])
        if entry["op"] == "delete":
            table.delete(entry["key"])
        else:
            table.put_many(dict(entry["items"]))

    def _replay_pending_locked(self) -> None:
        replayed = 0
        for entry in self.log.pending():
            self._apply_entry_locked(entry)
            self.log.mark_applied(int(entry["seq"]))
            replayed += 1
        if replayed:
            self.log.flush()
            logger.info(
                "%s: replayed %d unapplied log entries at boot",
                self.node_id, replayed,
            )

    def _replay_pending(self) -> None:
        with self._mu:
            self._replay_pending_locked()

    def apply_ops(self, entries: List[dict]) -> Set[str]:
        """Apply leader-shipped entries in seq order; returns the set of
        touched namespaces.  Rejects any entry from a term below this
        node's (the zombie fence) and skips already-applied seqs."""
        faultinject.fire(f"state.replicate.{'apply'}")
        touched: Set[str] = set()
        with self._mu:
            for entry in sorted(entries, key=lambda e: int(e["seq"])):
                term = int(entry.get("term", 0))
                if term < self._term:
                    raise StaleTermError(
                        f"op seq={entry.get('seq')} term={term} below "
                        f"observed term {self._term} — rejecting zombie write"
                    )
                seq = int(entry["seq"])
                if seq <= self.log.applied:
                    continue
                self._apply_entry_locked(entry)
                self.log.append_at(entry)
                self.log.mark_applied(seq)
                touched.add(entry["ns"])
        return touched

    # -- snapshot bootstrap ---------------------------------------------

    def snapshot(self) -> dict:
        """Consistent full-state snapshot for follower bootstrap: every
        data namespace's rows + the (term, seq) frontier, assembled
        under the commit lock so no append interleaves."""
        faultinject.fire(f"state.replicate.{'snapshot'}")
        with self._mu:
            namespaces = {}
            for ns in self._inner.namespaces():
                if ns in REPLICATION_NAMESPACES:
                    continue
                namespaces[ns] = self._inner.table(ns).load_all()
            return {
                "term": self._term,
                "seq": self.log.seq,
                "namespaces": namespaces,
            }

    def apply_snapshot(self, snapshot: dict) -> Set[str]:
        """Replace local data state with the leader's snapshot (rows
        absent from the snapshot are deleted — a leader-side delete must
        not survive locally), and fast-forward the applied watermark to
        the snapshot frontier."""
        faultinject.fire(f"state.replicate.{'snapshot'}")
        incoming = snapshot.get("namespaces", {})
        touched: Set[str] = set()
        with self._mu:
            self.observe_term(int(snapshot.get("term", self._term)))
            locals_ = set(self._inner.namespaces()) - set(
                REPLICATION_NAMESPACES
            )
            for ns in sorted(locals_ | set(incoming)):
                table = self._inner.table(ns)
                rows = incoming.get(ns, {})
                stale = set(table.load_all()) - set(rows)
                for key in stale:
                    table.delete(key)
                if rows:
                    table.put_many(dict(rows))
                touched.add(ns)
            seq = int(snapshot.get("seq", 0))
            if seq > self.log.applied:
                self.log.mark_applied(seq)
            self.log.flush()
        return touched

    def status(self) -> dict:
        with self._mu:
            return {
                "node_id": self.node_id,
                "role": self._role,
                "term": self._term,
                "seq": self.log.seq,
                "applied_seq": self.log.applied,
                "failovers": self.failovers,
            }

    # -- StateBackend surface -------------------------------------------

    def table(self, namespace: str) -> KVTable:
        return _ReplicatedTable(self, namespace)

    def namespaces(self) -> List[str]:
        return self._inner.namespaces()

    def close(self) -> None:
        with self._mu:
            self.log.flush()
        self._inner.close()


class _Applying:
    """Thread-local re-entrant apply scope (see
    :meth:`ReplicatedStateBackend.applying`)."""

    def __init__(self, backend: "ReplicatedStateBackend") -> None:
        self._b = backend

    def __enter__(self) -> "ReplicatedStateBackend":
        local = self._b._local
        local.apply_depth = getattr(local, "apply_depth", 0) + 1
        return self._b

    def __exit__(self, *exc) -> None:
        self._b._local.apply_depth -= 1


class _ReplicatedTable(KVTable):
    """One namespace viewed through the replication gate."""

    def __init__(self, backend: ReplicatedStateBackend, ns: str) -> None:
        self._b = backend
        self._ns = ns
        self._table = backend._inner.table(ns)

    def put(self, key: str, doc: dict) -> None:
        self._b._commit_op(
            self._ns, "put_many", {"items": {key: doc}},
            lambda: self._table.put(key, doc),
        )

    def put_many(self, items: Dict[str, dict]) -> None:
        self._b._commit_op(
            self._ns, "put_many", {"items": dict(items)},
            lambda: self._table.put_many(items),
        )

    def delete(self, key: str) -> None:
        self._b._commit_op(
            self._ns, "delete", {"key": key},
            lambda: self._table.delete(key),
        )

    def get(self, key: str) -> Optional[dict]:
        return self._table.get(key)

    def load_all(self) -> Dict[str, dict]:
        return self._table.load_all()


class LeaseKeeper:
    """Leader-side lease renewal loop (ttl/3 cadence, so two missed
    renewals still leave headroom before followers take over)."""

    def __init__(self, backend: ReplicatedStateBackend) -> None:
        self._b = backend
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def serve(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self._b.lease_ttl_s / 3.0):
                try:
                    self._b.renew_lease()
                except NotLeaderError:
                    logger.warning("lease keeper: no longer leader; stopping")
                    return
                except Exception:  # noqa: BLE001 — renewal loop is forever
                    logger.exception("lease renewal failed")

        self._thread = threading.Thread(
            target=loop, name="manager-lease-keeper", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class LogFollower:
    """Standby-side tailer: snapshot bootstrap, incremental log pulls,
    lease watching, and lease-expiry takeover.

    ``on_apply(namespaces)`` fires after each batch that changed data
    namespaces (the standby composition rebuilds its in-memory
    consumers); ``on_promote()`` fires once after takeover."""

    def __init__(
        self,
        backend: ReplicatedStateBackend,
        leader_url: str,
        *,
        poll_interval_s: float = 1.0,
        timeout: float = 10.0,
        takeover_grace: float = DEFAULT_TAKEOVER_GRACE,
        on_apply: Optional[Callable[[Set[str]], None]] = None,
        on_promote: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.backend = backend
        self.leader_url = leader_url.rstrip("/")
        self.poll_interval_s = poll_interval_s
        self.timeout = timeout
        self.takeover_grace = takeover_grace
        self.on_apply = on_apply
        self.on_promote = on_promote
        self._clock = clock
        self._mu = threading.Lock()
        # Until the first fresh lease arrives, grant the leader one full
        # TTL of benefit-of-the-doubt from follower boot.
        self._lease_deadline = clock() + backend.lease_ttl_s * (
            1.0 + takeover_grace
        )
        self._bootstrapped = False
        self._last_caught_up = clock()
        self._leader_seq = 0
        self.promoted = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wire -----------------------------------------------------------

    def _get_json(self, path: str) -> dict:
        faultinject.fire(f"state.replicate.{'fetch'}")
        # Auth: the data-bearing routes demand proof of the shared
        # lease_secret (the token is over the route path, query aside).
        route = path.split("?", 1)[0]
        req = urllib.request.Request(self.leader_url + path, headers={
            REPLICATION_AUTH_HEADER: sign_replication_request(
                self.backend.lease_secret, route
            ),
        })
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    # -- one poll -------------------------------------------------------

    def poll_once(self) -> int:
        """Fetch leader status + new log entries, apply them, track the
        lease.  Returns the number of entries applied; raises nothing —
        an unreachable leader just lets the lease age toward takeover."""
        if self.promoted:
            return 0
        try:
            status = self._get_json("/api/v1/replication:status")
        except Exception as exc:  # noqa: BLE001 — outage ages the lease
            logger.debug("follower poll: leader unreachable: %s", exc)
            self._maybe_promote()
            return 0
        lease = status.get("lease") or {}
        now = self._clock()
        if verify_lease(self.backend.lease_secret, lease):
            term = int(lease.get("term", 0))
            self.backend.observe_term(term)
            expires_in = float(lease.get("expires_in_s", 0.0))
            ttl = float(lease.get("ttl_s", self.backend.lease_ttl_s))
            with self._mu:
                self._lease_deadline = now + expires_in + ttl * self.takeover_grace
        applied = 0
        try:
            self._leader_seq = int(status.get("seq", 0))
            if not self._bootstrapped:
                self._bootstrap_snapshot()
            while self.backend.log.applied < self._leader_seq:
                from_seq = self.backend.log.applied
                resp = self._get_json(
                    f"/api/v1/replication:log?from_seq={from_seq}"
                )
                if int(resp.get("floor", 1)) > from_seq + 1:
                    # Behind the leader's compaction floor: entries
                    # between our watermark and the floor were truncated
                    # away, and applying the retained tail would
                    # silently skip them — re-bootstrap via snapshot
                    # (fast-forwards the watermark past the gap).
                    self._bootstrap_snapshot()
                    continue
                batch = resp.get("entries", [])
                if not batch:
                    # Nothing retained beyond our watermark: the head of
                    # the leader's log is a gap (a discarded failed
                    # commit) — we ARE caught up, don't report lag.
                    self._leader_seq = from_seq
                    break
                touched = self.backend.apply_ops(batch)
                applied += len(batch)
                if touched and self.on_apply is not None:
                    self.on_apply(touched)
        except StaleTermError:
            raise
        except Exception as exc:  # noqa: BLE001 — retry next poll
            logger.warning("follower poll: log pull failed: %s", exc)
        if self.backend.log.applied >= self._leader_seq:
            with self._mu:
                self._last_caught_up = self._clock()
        self._export_lag()
        return applied

    def _bootstrap_snapshot(self) -> None:
        snap = self._get_json("/api/v1/replication:snapshot")
        touched = self.backend.apply_snapshot(snap)
        self._bootstrapped = True
        if touched and self.on_apply is not None:
            self.on_apply(touched)

    def _export_lag(self) -> None:
        from ..rpc.metrics import REPLICATION_LAG

        REPLICATION_LAG.set(self.lag_seconds())

    def lag_seconds(self) -> float:
        """Seconds since this follower last matched the leader's log
        frontier (≈0 while caught up; grows through an outage)."""
        with self._mu:
            if self.backend.log.applied >= self._leader_seq:
                return 0.0
            return max(self._clock() - self._last_caught_up, 0.0)

    def health(self) -> dict:
        with self._mu:
            lease_remaining = self._lease_deadline - self._clock()
        return {
            "role": self.backend.role,
            "term": self.backend.term,
            "applied_seq": self.backend.log.applied,
            "leader_seq": self._leader_seq,
            "lag_seconds": self.lag_seconds(),
            "lease_remaining_s": lease_remaining,
            "promoted": self.promoted,
        }

    def _maybe_promote(self) -> bool:
        with self._mu:
            expired = self._clock() >= self._lease_deadline
        if not expired or self.promoted:
            return self.promoted
        self.backend.promote()
        self.promoted = True
        if self.on_promote is not None:
            self.on_promote()
        return True

    # -- background serve ----------------------------------------------

    def serve(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self.poll_interval_s):
                try:
                    if self.poll_once() == 0:
                        self._maybe_promote()
                    if self.promoted:
                        return
                except Exception:  # noqa: BLE001 — the tail loop is forever
                    logger.exception("follower poll failed")

        self._thread = threading.Thread(
            target=loop, name="manager-log-follower", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
