"""Manager HA: log-shipping replication over the StateBackend seam.

The reference gets control-plane HA for free from Redis+MySQL (the
manager sits on externally HA-able stores, database.go:50-59); our
embedded manager concentrates every durable surface behind ONE seam —
``manager/state.py``'s ``StateBackend`` — which makes that seam the
right place to replicate.  Three pieces (DESIGN.md §20):

- **write-ahead op log** (``ReplicationLog``): every ``put``/
  ``put_many``/``delete`` a leader commits is first appended to a
  monotonic (term, seq) log riding two reserved namespaces of the same
  backend (``replication_log`` / ``replication_meta``), THEN applied to
  the data namespace.  Ops are absolute upserts/deletes, so boot-time
  replay of the unapplied tail is idempotent — a crash between the log
  append and the data commit converges on restart.

- **roles + lease fencing** (``ReplicatedStateBackend``): a leader may
  commit only while its lease (renewed every ``ttl/3`` by
  ``LeaseKeeper``) is unexpired; an expired or fenced leader's writes
  raise ``NotLeaderError`` — the zombie cannot commit.  The lease is
  HMAC-signed with the shared ``lease_secret`` so a follower only
  honours (and only defers to) a leader that holds the secret; terms
  are fenced monotonically — observing a higher term permanently
  demotes this node for that term.

- **follower tailing + takeover** (``LogFollower``): a standby tails
  the leader's ``/api/v1/replication:*`` REST surface (snapshot
  bootstrap for pre-log rows, then incremental log pulls), applies ops
  into its OWN backend, answers lag/health probes, and — when the last
  fresh lease it saw has aged past expiry — promotes itself with
  ``term+1``.  After promotion it rejects ops from any lower term
  (``StaleTermError``), which is what makes a partitioned old leader's
  history unshippable.

Every network/commit edge here is a DF004 chaos seam
(``state.replicate.*`` / ``manager.lease.*``) and every write path is
inventoried in ``records/state_contracts.py`` (the ``replicators``
section covers the dynamic-namespace apply sites) so the DF014 static
pass and the runtime crash witness gate this subsystem like any other.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Set

from ..utils import faultinject
from .state import KVTable, StateBackend

logger = logging.getLogger(__name__)

# Namespaces reserved for the replication machinery itself: never
# shipped in snapshots, never re-replicated.
REPLICATION_NAMESPACES = ("replication_log", "replication_meta")

# How many lease intervals of silence a follower tolerates beyond the
# advertised expiry before taking over (absorbs one lost poll).
DEFAULT_TAKEOVER_GRACE = 0.5


class NotLeaderError(RuntimeError):
    """Write rejected: this node is a standby or its lease expired."""


class StaleTermError(NotLeaderError):
    """Op or write carries a term older than one already observed —
    the sender is a fenced zombie leader."""


def sign_lease(secret: str, leader_id: str, term: int) -> str:
    """HMAC-SHA256 over the lease identity.  The signature authenticates
    WHO holds WHICH term (a forged lease cannot defer a follower);
    freshness is the transport's job — ``expires_in_s`` is relative to
    the fetch that returned it, so no cross-host clock is compared."""
    msg = f"{leader_id}:{term}".encode()
    return hmac.new(secret.encode(), msg, hashlib.sha256).hexdigest()


def verify_lease(secret: str, lease: dict) -> bool:
    try:
        want = sign_lease(secret, str(lease["leader_id"]), int(lease["term"]))
        return hmac.compare_digest(want, str(lease.get("sig", "")))
    except (KeyError, TypeError, ValueError):
        return False


class ReplicationLog:
    """The durable op log + term/applied watermark, riding two reserved
    namespaces of the inner backend.

    ``append`` is the write-ahead half of every replicated commit; the
    applied watermark is flushed lazily (every ``APPLIED_FLUSH_EVERY``
    ops and at ``flush``) because replaying an already-applied absolute
    op at boot is a no-op — lag in the watermark costs replay work,
    never correctness.

    Locking: this object is owned by ONE ``ReplicatedStateBackend`` and
    every mutator runs under that backend's ``_mu`` (log order must BE
    commit order, so a separate log lock could only reorder or
    deadlock); ``seq``/``term``/``applied`` are single int reads (GIL
    atomic) safe for health probes.
    """

    APPLIED_FLUSH_EVERY = 64

    def __init__(self, backend: StateBackend) -> None:
        self._log = backend.table("replication_log")
        self._meta = backend.table("replication_meta")
        rows = self._log.load_all()
        self._seq = max((int(k) for k in rows), default=0)
        state = self._meta.load_all().get("state") or {}
        self._term = int(state.get("term", 1))
        self._applied = int(state.get("applied", 0))
        self._unflushed = 0

    @staticmethod
    def _key(seq: int) -> str:
        return f"{seq:020d}"

    def append(self, entry: dict) -> int:
        """Assign the next seq and durably append ``entry`` (must carry
        ``term``/``ns``/``op`` + payload).  Returns the assigned seq."""
        self._seq += 1
        entry = dict(entry, seq=self._seq)
        self._log.put(self._key(self._seq), entry)
        return self._seq

    def append_at(self, entry: dict) -> None:
        """Follower-side copy of a leader-assigned entry (keeps this
        node's log shippable to a cascading follower after promotion)."""
        seq = int(entry["seq"])
        self._log.put(self._key(seq), entry)
        if seq > self._seq:
            self._seq = seq

    def mark_applied(self, seq: int) -> None:
        if seq > self._applied:
            self._applied = seq
        self._unflushed += 1
        if self._unflushed >= self.APPLIED_FLUSH_EVERY:
            self.flush()

    def set_term(self, term: int) -> None:
        self._term = int(term)
        self.flush()

    def flush(self) -> None:
        self._meta.put(
            "state", {"term": self._term, "applied": self._applied}
        )
        self._unflushed = 0

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def term(self) -> int:
        return self._term

    @property
    def applied(self) -> int:
        return self._applied

    def entries_since(self, from_seq: int, limit: int = 500) -> List[dict]:
        """Entries with seq > ``from_seq``, ascending, at most ``limit``.
        Full-table scan per call — the log is an embedded test/deploy
        scale structure, not a WAN-scale stream."""
        rows = self._log.load_all()
        out = [e for k, e in rows.items() if int(k) > from_seq]
        out.sort(key=lambda e: int(e["seq"]))
        return out[:limit]

    def pending(self) -> List[dict]:
        """The unapplied tail (crash between log append and data
        commit): replayed idempotently at boot."""
        return self.entries_since(self.applied)


class ReplicatedStateBackend(StateBackend):
    """StateBackend wrapper that write-ahead-logs every mutation and
    enforces leader/lease/term fencing at the commit point.

    Reads always pass through.  Writes require a live leader role
    unless issued inside :meth:`applying` (the follower's apply path
    and standby boot-time reconciliation)."""

    def __init__(
        self,
        inner: StateBackend,
        *,
        node_id: str = "manager",
        role: str = "leader",
        lease_ttl_s: float = 10.0,
        lease_secret: str = "dragonfly-manager-lease",
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if role not in ("leader", "standby"):
            raise ValueError(f"unknown replication role {role!r}")
        self._inner = inner
        self.node_id = node_id
        self.lease_ttl_s = float(lease_ttl_s)
        self.lease_secret = lease_secret
        self._clock = clock
        self._mu = threading.RLock()
        self._local = threading.local()
        self.log = ReplicationLog(inner)
        self._role = role
        self._term = self.log.term
        self._lease_expires_at: Optional[float] = None
        self.failovers = 0
        if role == "leader":
            self._lease_expires_at = self._clock() + self.lease_ttl_s
            self._replay_pending()
        self._set_role_metric()

    # -- role / lease ---------------------------------------------------

    def _set_role_metric(self) -> None:
        from ..rpc.metrics import MANAGER_ROLE

        for role in ("leader", "standby"):
            MANAGER_ROLE.set(1.0 if role == self._role else 0.0, role=role)

    @property
    def role(self) -> str:
        with self._mu:
            return self._role

    @property
    def term(self) -> int:
        with self._mu:
            return self._term

    def renew_lease(self) -> dict:
        """Extend this leader's lease by one TTL; raises if no longer
        leader (a fenced node cannot resurrect itself by renewing)."""
        faultinject.fire(f"manager.lease.{'renew'}")
        with self._mu:
            if self._role != "leader":
                raise NotLeaderError(
                    f"{self.node_id}: cannot renew lease in role {self._role}"
                )
            self._lease_expires_at = self._clock() + self.lease_ttl_s
            return self._lease_payload_locked()

    def _lease_payload_locked(self) -> dict:
        expires_in = 0.0
        if self._lease_expires_at is not None:
            expires_in = max(self._lease_expires_at - self._clock(), 0.0)
        return {
            "leader_id": self.node_id,
            "term": self._term,
            "ttl_s": self.lease_ttl_s,
            "expires_in_s": expires_in,
            "sig": sign_lease(self.lease_secret, self.node_id, self._term),
        }

    def lease_payload(self) -> dict:
        with self._mu:
            return self._lease_payload_locked()

    def promote(self, term: Optional[int] = None) -> int:
        """Standby → leader at ``term`` (default: observed term + 1).
        Replays any unapplied log tail, persists the new term, and
        starts a fresh lease."""
        faultinject.fire(f"manager.lease.{'promote'}")
        with self._mu:
            new_term = int(term) if term is not None else self._term + 1
            if new_term <= self._term and self._role == "leader":
                return self._term
            if new_term < self._term:
                raise StaleTermError(
                    f"promotion to term {new_term} below observed {self._term}"
                )
            self._term = new_term
            self._role = "leader"
            self._lease_expires_at = self._clock() + self.lease_ttl_s
            self.log.set_term(new_term)
            self.failovers += 1
            self._replay_pending_locked()
            self._set_role_metric()
        from ..rpc.metrics import MANAGER_FAILOVERS_TOTAL

        MANAGER_FAILOVERS_TOTAL.inc(node=self.node_id)
        logger.warning(
            "%s: promoted to leader (term %d)", self.node_id, new_term
        )
        return new_term

    def step_down(self) -> None:
        """Leader → standby (tests / graceful handover)."""
        with self._mu:
            self._role = "standby"
            self._lease_expires_at = None
            self._set_role_metric()

    def observe_term(self, term: int) -> None:
        """Fence: once a higher term is seen, this node can never commit
        under its old term again."""
        with self._mu:
            if term > self._term:
                if self._role == "leader":
                    logger.warning(
                        "%s: fenced by term %d (was leader at term %d)",
                        self.node_id, term, self._term,
                    )
                self._term = term
                self._role = "standby"
                self._lease_expires_at = None
                self.log.set_term(term)
                self._set_role_metric()

    # -- the write gate -------------------------------------------------

    def applying(self) -> "_Applying":
        """``with backend.applying(): ...`` — writes inside the block
        bypass the leader gate (the follower's apply path and standby
        boot-time reconciliation write replicated/derived state, not
        new client mutations)."""
        return _Applying(self)

    def _is_applying(self) -> bool:
        return getattr(self._local, "apply_depth", 0) > 0

    def _check_writable_locked(self) -> None:
        faultinject.fire(f"manager.lease.{'check'}")
        if self._role != "leader":
            raise NotLeaderError(
                f"{self.node_id}: standby (term {self._term}) rejects writes"
            )
        if (
            self._lease_expires_at is not None
            and self._clock() >= self._lease_expires_at
        ):
            raise NotLeaderError(
                f"{self.node_id}: lease expired at term {self._term} — "
                "a successor may hold a higher term; refusing to commit"
            )

    def _commit_op(
        self, ns: str, op: str, payload: dict, fn: Callable[[], None]
    ) -> None:
        """Write-ahead append (term+seq) then the data commit, under one
        lock so the log order IS the commit order."""
        faultinject.fire(f"state.replicate.{op}")
        if self._is_applying():
            fn()
            return
        with self._mu:
            self._check_writable_locked()
            entry = dict(payload, term=self._term, ns=ns, op=op)
            seq = self.log.append(entry)
            fn()
            self.log.mark_applied(seq)

    # -- follower application ------------------------------------------

    def _apply_entry_locked(self, entry: dict) -> None:
        table = self._inner.table(entry["ns"])
        if entry["op"] == "delete":
            table.delete(entry["key"])
        else:
            table.put_many(dict(entry["items"]))

    def _replay_pending_locked(self) -> None:
        replayed = 0
        for entry in self.log.pending():
            self._apply_entry_locked(entry)
            self.log.mark_applied(int(entry["seq"]))
            replayed += 1
        if replayed:
            self.log.flush()
            logger.info(
                "%s: replayed %d unapplied log entries at boot",
                self.node_id, replayed,
            )

    def _replay_pending(self) -> None:
        with self._mu:
            self._replay_pending_locked()

    def apply_ops(self, entries: List[dict]) -> Set[str]:
        """Apply leader-shipped entries in seq order; returns the set of
        touched namespaces.  Rejects any entry from a term below this
        node's (the zombie fence) and skips already-applied seqs."""
        faultinject.fire(f"state.replicate.{'apply'}")
        touched: Set[str] = set()
        with self._mu:
            for entry in sorted(entries, key=lambda e: int(e["seq"])):
                term = int(entry.get("term", 0))
                if term < self._term:
                    raise StaleTermError(
                        f"op seq={entry.get('seq')} term={term} below "
                        f"observed term {self._term} — rejecting zombie write"
                    )
                seq = int(entry["seq"])
                if seq <= self.log.applied:
                    continue
                self._apply_entry_locked(entry)
                self.log.append_at(entry)
                self.log.mark_applied(seq)
                touched.add(entry["ns"])
        return touched

    # -- snapshot bootstrap ---------------------------------------------

    def snapshot(self) -> dict:
        """Consistent full-state snapshot for follower bootstrap: every
        data namespace's rows + the (term, seq) frontier, assembled
        under the commit lock so no append interleaves."""
        faultinject.fire(f"state.replicate.{'snapshot'}")
        with self._mu:
            namespaces = {}
            for ns in self._inner.namespaces():
                if ns in REPLICATION_NAMESPACES:
                    continue
                namespaces[ns] = self._inner.table(ns).load_all()
            return {
                "term": self._term,
                "seq": self.log.seq,
                "namespaces": namespaces,
            }

    def apply_snapshot(self, snapshot: dict) -> Set[str]:
        """Replace local data state with the leader's snapshot (rows
        absent from the snapshot are deleted — a leader-side delete must
        not survive locally), and fast-forward the applied watermark to
        the snapshot frontier."""
        faultinject.fire(f"state.replicate.{'snapshot'}")
        incoming = snapshot.get("namespaces", {})
        touched: Set[str] = set()
        with self._mu:
            self.observe_term(int(snapshot.get("term", self._term)))
            locals_ = set(self._inner.namespaces()) - set(
                REPLICATION_NAMESPACES
            )
            for ns in sorted(locals_ | set(incoming)):
                table = self._inner.table(ns)
                rows = incoming.get(ns, {})
                stale = set(table.load_all()) - set(rows)
                for key in stale:
                    table.delete(key)
                if rows:
                    table.put_many(dict(rows))
                touched.add(ns)
            seq = int(snapshot.get("seq", 0))
            if seq > self.log.applied:
                self.log.mark_applied(seq)
            self.log.flush()
        return touched

    def status(self) -> dict:
        with self._mu:
            return {
                "node_id": self.node_id,
                "role": self._role,
                "term": self._term,
                "seq": self.log.seq,
                "applied_seq": self.log.applied,
                "failovers": self.failovers,
            }

    # -- StateBackend surface -------------------------------------------

    def table(self, namespace: str) -> KVTable:
        return _ReplicatedTable(self, namespace)

    def namespaces(self) -> List[str]:
        return self._inner.namespaces()

    def close(self) -> None:
        with self._mu:
            self.log.flush()
        self._inner.close()


class _Applying:
    """Thread-local re-entrant apply scope (see
    :meth:`ReplicatedStateBackend.applying`)."""

    def __init__(self, backend: "ReplicatedStateBackend") -> None:
        self._b = backend

    def __enter__(self) -> "ReplicatedStateBackend":
        local = self._b._local
        local.apply_depth = getattr(local, "apply_depth", 0) + 1
        return self._b

    def __exit__(self, *exc) -> None:
        self._b._local.apply_depth -= 1


class _ReplicatedTable(KVTable):
    """One namespace viewed through the replication gate."""

    def __init__(self, backend: ReplicatedStateBackend, ns: str) -> None:
        self._b = backend
        self._ns = ns
        self._table = backend._inner.table(ns)

    def put(self, key: str, doc: dict) -> None:
        self._b._commit_op(
            self._ns, "put_many", {"items": {key: doc}},
            lambda: self._table.put(key, doc),
        )

    def put_many(self, items: Dict[str, dict]) -> None:
        self._b._commit_op(
            self._ns, "put_many", {"items": dict(items)},
            lambda: self._table.put_many(items),
        )

    def delete(self, key: str) -> None:
        self._b._commit_op(
            self._ns, "delete", {"key": key},
            lambda: self._table.delete(key),
        )

    def get(self, key: str) -> Optional[dict]:
        return self._table.get(key)

    def load_all(self) -> Dict[str, dict]:
        return self._table.load_all()


class LeaseKeeper:
    """Leader-side lease renewal loop (ttl/3 cadence, so two missed
    renewals still leave headroom before followers take over)."""

    def __init__(self, backend: ReplicatedStateBackend) -> None:
        self._b = backend
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def serve(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self._b.lease_ttl_s / 3.0):
                try:
                    self._b.renew_lease()
                except NotLeaderError:
                    logger.warning("lease keeper: no longer leader; stopping")
                    return
                except Exception:  # noqa: BLE001 — renewal loop is forever
                    logger.exception("lease renewal failed")

        self._thread = threading.Thread(
            target=loop, name="manager-lease-keeper", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class LogFollower:
    """Standby-side tailer: snapshot bootstrap, incremental log pulls,
    lease watching, and lease-expiry takeover.

    ``on_apply(namespaces)`` fires after each batch that changed data
    namespaces (the standby composition rebuilds its in-memory
    consumers); ``on_promote()`` fires once after takeover."""

    def __init__(
        self,
        backend: ReplicatedStateBackend,
        leader_url: str,
        *,
        poll_interval_s: float = 1.0,
        timeout: float = 10.0,
        takeover_grace: float = DEFAULT_TAKEOVER_GRACE,
        on_apply: Optional[Callable[[Set[str]], None]] = None,
        on_promote: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.backend = backend
        self.leader_url = leader_url.rstrip("/")
        self.poll_interval_s = poll_interval_s
        self.timeout = timeout
        self.takeover_grace = takeover_grace
        self.on_apply = on_apply
        self.on_promote = on_promote
        self._clock = clock
        self._mu = threading.Lock()
        # Until the first fresh lease arrives, grant the leader one full
        # TTL of benefit-of-the-doubt from follower boot.
        self._lease_deadline = clock() + backend.lease_ttl_s * (
            1.0 + takeover_grace
        )
        self._bootstrapped = False
        self._last_caught_up = clock()
        self._leader_seq = 0
        self.promoted = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wire -----------------------------------------------------------

    def _get_json(self, path: str) -> dict:
        faultinject.fire(f"state.replicate.{'fetch'}")
        with urllib.request.urlopen(
            self.leader_url + path, timeout=self.timeout
        ) as resp:
            return json.loads(resp.read())

    # -- one poll -------------------------------------------------------

    def poll_once(self) -> int:
        """Fetch leader status + new log entries, apply them, track the
        lease.  Returns the number of entries applied; raises nothing —
        an unreachable leader just lets the lease age toward takeover."""
        if self.promoted:
            return 0
        try:
            status = self._get_json("/api/v1/replication:status")
        except Exception as exc:  # noqa: BLE001 — outage ages the lease
            logger.debug("follower poll: leader unreachable: %s", exc)
            self._maybe_promote()
            return 0
        lease = status.get("lease") or {}
        now = self._clock()
        if verify_lease(self.backend.lease_secret, lease):
            term = int(lease.get("term", 0))
            self.backend.observe_term(term)
            expires_in = float(lease.get("expires_in_s", 0.0))
            ttl = float(lease.get("ttl_s", self.backend.lease_ttl_s))
            with self._mu:
                self._lease_deadline = now + expires_in + ttl * self.takeover_grace
        applied = 0
        try:
            self._leader_seq = int(status.get("seq", 0))
            if not self._bootstrapped:
                snap = self._get_json("/api/v1/replication:snapshot")
                touched = self.backend.apply_snapshot(snap)
                self._bootstrapped = True
                if touched and self.on_apply is not None:
                    self.on_apply(touched)
            while self.backend.log.applied < self._leader_seq:
                batch = self._get_json(
                    "/api/v1/replication:log?from_seq="
                    f"{self.backend.log.applied}"
                ).get("entries", [])
                if not batch:
                    break
                touched = self.backend.apply_ops(batch)
                applied += len(batch)
                if touched and self.on_apply is not None:
                    self.on_apply(touched)
        except StaleTermError:
            raise
        except Exception as exc:  # noqa: BLE001 — retry next poll
            logger.warning("follower poll: log pull failed: %s", exc)
        if self.backend.log.applied >= self._leader_seq:
            with self._mu:
                self._last_caught_up = self._clock()
        self._export_lag()
        return applied

    def _export_lag(self) -> None:
        from ..rpc.metrics import REPLICATION_LAG

        REPLICATION_LAG.set(self.lag_seconds())

    def lag_seconds(self) -> float:
        """Seconds since this follower last matched the leader's log
        frontier (≈0 while caught up; grows through an outage)."""
        with self._mu:
            if self.backend.log.applied >= self._leader_seq:
                return 0.0
            return max(self._clock() - self._last_caught_up, 0.0)

    def health(self) -> dict:
        with self._mu:
            lease_remaining = self._lease_deadline - self._clock()
        return {
            "role": self.backend.role,
            "term": self.backend.term,
            "applied_seq": self.backend.log.applied,
            "leader_seq": self._leader_seq,
            "lag_seconds": self.lag_seconds(),
            "lease_remaining_s": lease_remaining,
            "promoted": self.promoted,
        }

    def _maybe_promote(self) -> bool:
        with self._mu:
            expired = self._clock() >= self._lease_deadline
        if not expired or self.promoted:
            return self.promoted
        self.backend.promote()
        self.promoted = True
        if self.on_promote is not None:
            self.on_promote()
        return True

    # -- background serve ----------------------------------------------

    def serve(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self.poll_interval_s):
                try:
                    if self.poll_once() == 0:
                        self._maybe_promote()
                    if self.promoted:
                        return
                except Exception:  # noqa: BLE001 — the tail loop is forever
                    logger.exception("follower poll failed")

        self._thread = threading.Thread(
            target=loop, name="manager-log-follower", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
