"""Embedded operator console (reference: manager/manager.go:61-62 embeds
the console SPA; manager/router serves it at /).

A single self-contained HTML page driving the REST API with vanilla JS:
sign-in (token kept in localStorage), model list with activate /
deactivate, scheduler liveness, users and personal access tokens.  No
build step, no assets, no external fetches — the whole console is this
string.
"""

CONSOLE_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>dragonfly2-tpu manager</title>
<style>
  :root { color-scheme: light dark; }
  body { font: 14px/1.5 system-ui, sans-serif; margin: 2rem auto; max-width: 72rem; padding: 0 1rem; }
  h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 2rem; }
  table { border-collapse: collapse; width: 100%; margin: .5rem 0; }
  th, td { text-align: left; padding: .3rem .6rem; border-bottom: 1px solid #8884; }
  th { font-weight: 600; }
  button { cursor: pointer; padding: .15rem .6rem; margin-right: .3rem; }
  input { padding: .25rem .4rem; margin-right: .4rem; }
  .pill { padding: .05rem .5rem; border-radius: 999px; font-size: .8rem; }
  .active { background: #16a34a33; } .inactive { background: #8883; }
  .err { color: #dc2626; } .ok { color: #16a34a; }
  #signin, #app { margin-top: 1rem; }
  .muted { opacity: .65; }
  code { font-size: .85em; }
</style>
</head>
<body>
<h1>dragonfly2-tpu manager console</h1>
<div id="signin">
  <input id="u" placeholder="username"><input id="p" type="password" placeholder="password">
  <button onclick="signin()">Sign in</button>
  <span id="oauth-buttons"></span>
  <span id="signin-msg" class="err"></span>
</div>
<div id="app" style="display:none">
  <span class="muted">signed in as <b id="who"></b> (<span id="role"></span>)</span>
  <button onclick="signout()">Sign out</button>

  <h2>Models</h2>
  <table id="models"><thead><tr>
    <th>name</th><th>type</th><th>version</th><th>scheduler</th><th>state</th><th>evaluation</th><th></th>
  </tr></thead><tbody></tbody></table>

  <h2>Schedulers</h2>
  <table id="schedulers"><thead><tr>
    <th>id</th><th>cluster</th><th>address</th><th>state</th>
  </tr></thead><tbody></tbody></table>

  <h2>Scheduler clusters <span class="muted">(live scheduling config)</span></h2>
  <table id="clusters"><thead><tr>
    <th>id</th><th>name</th><th>default</th><th>scheduler config</th><th>client config</th><th></th>
  </tr></thead><tbody></tbody></table>

  <h2>Applications</h2>
  <input id="app-name" placeholder="name"><input id="app-url" placeholder="url">
  <input id="app-prio" placeholder="priority" size="4">
  <button onclick="createApp()">Create</button>
  <table id="applications"><thead><tr>
    <th>name</th><th>url</th><th>priority</th><th>bio</th><th></th>
  </tr></thead><tbody></tbody></table>

  <h2>Jobs <span class="muted">(async group fan-out: preheat / sync_peers)</span></h2>
  <select id="job-type"><option>preheat</option><option>sync_peers</option></select>
  <input id="job-queues" placeholder="scheduler ids (see table above; blank = all active)">
  <input id="job-url" placeholder="url (preheat)">
  <button onclick="createJob()">Create</button>
  <table id="jobs"><thead><tr>
    <th>group</th><th>state</th><th>jobs</th><th>errors</th>
  </tr></thead><tbody></tbody></table>

  <h2>Users <span class="muted">(admin)</span></h2>
  <table id="users"><thead><tr>
    <th>name</th><th>email</th><th>role</th><th>state</th>
  </tr></thead><tbody></tbody></table>

  <h2>Personal access tokens</h2>
  <input id="pat-name" placeholder="token name">
  <select id="pat-role">
    <option>readonly</option><option>peer</option><option>operator</option><option>admin</option>
  </select>
  <button onclick="createPat()">Create</button>
  <div id="pat-new" class="ok"></div>
  <table id="pats"><thead><tr>
    <th>name</th><th>role</th><th>expires</th><th>revoked</th><th></th>
  </tr></thead><tbody></tbody></table>
</div>
<script>
const tok = () => localStorage.getItem("df_token") || "";
async function api(path, opts) {
  opts = opts || {};
  opts.headers = Object.assign(
    tok() ? {"Authorization": "Bearer " + tok()} : {},
    opts.body ? {"Content-Type": "application/json"} : {}, opts.headers || {});
  const r = await fetch("/api/v1" + path, opts);
  if (r.status === 401 && !opts._retried && localStorage.getItem("df_refresh_id")
      && path !== "/oauth:refresh") {
    // Expired session with a refresh grant in hand: renew and retry once.
    if (await oauthRefresh()) return api(path, Object.assign({}, opts, {_retried: true}));
  }
  if (!r.ok) {
    const err = new Error((await r.json()).error || r.status);
    err.status = r.status;
    throw err;
  }
  return r.json();
}
async function signin() {
  try {
    const out = await api("/users:signin", {method: "POST", body: JSON.stringify(
      {name: document.getElementById("u").value, password: document.getElementById("p").value})});
    localStorage.setItem("df_token", out.token);
    localStorage.setItem("df_role", out.role);
    localStorage.setItem("df_user", document.getElementById("u").value);
    boot();
  } catch (e) { document.getElementById("signin-msg").textContent = e.message; }
}
function signout() { localStorage.clear(); location.reload(); }
// -- OAuth sign-in (providers -> authorize redirect -> callback code ->
//    :signin; sessions renew via /oauth:refresh, falling back to the
//    authorize flow when the provider revoked the refresh token). --
async function oauthButtons() {
  try {
    const providers = await api("/oauth:providers");
    document.getElementById("oauth-buttons").innerHTML = providers.map(p =>
      `<button onclick="oauthStart('${esc(p)}')">Sign in with ${esc(p)}</button>`
    ).join("");
  } catch (e) { /* no oauth configured */ }
}
async function oauthStart(name) {
  const cb = location.origin + location.pathname + "?oauth=" + encodeURIComponent(name);
  const out = await api(`/oauth/${name}:authorize-url?redirect_uri=` + encodeURIComponent(cb));
  location.href = out.url;
}
async function oauthCallback() {
  const q = new URLSearchParams(location.search);
  if (!q.get("oauth") || !q.get("code")) return false;
  const name = q.get("oauth");
  const cb = location.origin + location.pathname + "?oauth=" + encodeURIComponent(name);
  const out = await api(`/oauth/${name}:signin`, {method: "POST", body: JSON.stringify(
    {code: q.get("code"), state: q.get("state"), redirect_uri: cb})});
  localStorage.setItem("df_token", out.token);
  localStorage.setItem("df_role", out.role);
  localStorage.setItem("df_user", out.user || name);
  if (out.refresh_id) localStorage.setItem("df_refresh_id", out.refresh_id);
  history.replaceState(null, "", location.pathname);
  return true;
}
async function oauthRefresh() {
  const rid = localStorage.getItem("df_refresh_id");
  if (!rid) return false;
  try {
    const out = await api("/oauth:refresh", {method: "POST",
      body: JSON.stringify({refresh_id: rid})});
    localStorage.setItem("df_token", out.token);
    localStorage.setItem("df_role", out.role);
    localStorage.setItem("df_refresh_id", out.refresh_id);
    return true;
  } catch (e) {
    if (e.status === 403) {
      // Provider revoked the grant: degrade to re-authentication.
      localStorage.removeItem("df_refresh_id");
      localStorage.removeItem("df_token");
    }
    // Network/5xx: keep the handle — the grant is intact server-side.
    return false;
  }
}
function fill(id, rows) {
  document.querySelector("#" + id + " tbody").innerHTML = rows.join("");
}
const esc = s => String(s).replace(/[&<>"]/g, c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
async function refresh() {
  const models = await api("/models");
  fill("models", models.map(m => `<tr><td>${esc(m.name)}</td><td>${esc(m.type)}</td>
    <td>v${m.version}</td><td><code>${esc(m.scheduler_id)}</code></td>
    <td><span class="pill ${m.state}">${m.state}</span></td>
    <td><code>${esc(JSON.stringify(m.evaluation))}</code></td>
    <td><button onclick="act('${m.id}','activate')">activate</button>
        <button onclick="act('${m.id}','deactivate')">deactivate</button></td></tr>`));
  const scheds = await api("/schedulers");
  fill("schedulers", scheds.map(s => `<tr><td><code>${esc(s.id)}</code></td>
    <td>${esc(s.cluster_id)}</td><td>${esc(s.ip)}:${s.port}</td><td>${esc(s.state)}</td></tr>`));
  const clusters = await api("/clusters");
  // ids ride in data attributes, never inline JS strings — even though
  // the store rejects quote-bearing ids, the console must not rely on it.
  fill("clusters", clusters.map(c => `<tr><td><code>${esc(c.id)}</code></td>
    <td>${esc(c.name)}</td><td>${c.is_default}</td>
    <td><code>${esc(JSON.stringify(c.scheduler_cluster_config))}</code></td>
    <td><code>${esc(JSON.stringify(c.client_config))}</code></td>
    <td><button data-id="${esc(c.id)}" onclick="editCluster(this.dataset.id)">edit config</button></td></tr>`));
  const apps = await api("/applications");
  fill("applications", apps.map(a => `<tr><td>${esc(a.name)}</td>
    <td><code>${esc(a.url)}</code></td><td>${a.priority}</td><td>${esc(a.bio)}</td>
    <td><button data-id="${esc(a.id)}" onclick="delApp(this.dataset.id)">delete</button></td></tr>`));
  const jobs = await api("/jobs");
  fill("jobs", jobs.map(g => `<tr><td><code>${esc(g.group_id)}</code></td>
    <td><span class="pill ${g.state === "SUCCESS" ? "active" : "inactive"}">${esc(g.state)}</span></td>
    <td>${g.jobs.map(j => `${esc(j.queue)}:${esc(j.state)}`).join(" ")}</td>
    <td class="err">${g.jobs.map(j => esc(j.error || "")).filter(Boolean).join("; ")}</td></tr>`));
  try {
    const users = await api("/users");
    fill("users", users.map(u => `<tr><td>${esc(u.name)}</td><td>${esc(u.email)}</td>
      <td>${esc(u.role)}</td><td>${esc(u.state)}</td></tr>`));
  } catch (e) { fill("users", [`<tr><td colspan=4 class="muted">${esc(e.message)}</td></tr>`]); }
  try {
    const pats = await api("/pats");
    fill("pats", pats.map(p => `<tr><td>${esc(p.name)}</td><td>${esc(p.role)}</td>
      <td>${new Date(p.expires_at * 1000).toISOString().slice(0,10)}</td>
      <td>${p.revoked}</td>
      <td><button onclick="revoke('${p.id}')">revoke</button></td></tr>`));
  } catch (e) { fill("pats", []); }
}
async function act(id, action) {
  try { await api(`/models/${id}:${action}`, {method: "POST", body: "{}"}); refresh(); }
  catch (e) { alert(e.message); }
}
async function editCluster(id) {
  const cur = (await api("/clusters")).find(c => c.id === id);
  const next = prompt("scheduler_cluster_config JSON (applied live by schedulers):",
                      JSON.stringify(cur.scheduler_cluster_config));
  if (next === null) return;
  try {
    await api(`/clusters/${id}:update`, {method: "POST", body: JSON.stringify(
      {scheduler_cluster_config: JSON.parse(next)})});
    refresh();
  } catch (e) { alert(e.message); }
}
async function createApp() {
  try {
    await api("/applications", {method: "POST", body: JSON.stringify(
      {name: document.getElementById("app-name").value,
       url: document.getElementById("app-url").value,
       priority: parseInt(document.getElementById("app-prio").value || "0")})});
    refresh();
  } catch (e) { alert(e.message); }
}
async function delApp(id) {
  try { await api(`/applications/${id}:delete`, {method: "POST", body: "{}"}); refresh(); }
  catch (e) { alert(e.message); }
}
async function createJob() {
  try {
    // Workers poll "scheduler:<id>" (cli/scheduler wiring) — accept bare
    // scheduler ids and prefix them; blank = every ACTIVE scheduler.
    let ids = document.getElementById("job-queues").value
      .split(",").map(s => s.trim()).filter(Boolean);
    if (!ids.length) {
      ids = (await api("/schedulers"))
        .filter(s => s.state === "active").map(s => s.id);
      if (!ids.length) { alert("no active schedulers"); return; }
    }
    const queues = ids.map(q => q.includes(":") ? q : "scheduler:" + q);
    const type = document.getElementById("job-type").value;
    // The preheat handler's contract (jobs/preheat.py): urls LIST +
    // piece_size; sync_peers takes no args.
    const args = {};
    const url = document.getElementById("job-url").value;
    if (type === "preheat") {
      if (!url) { alert("preheat needs a url"); return; }
      args.urls = [url];
      args.piece_size = 4 * 1024 * 1024;
    }
    await api("/jobs", {method: "POST", body: JSON.stringify(
      {type: type, queues: queues, args: args})});
    refresh();
  } catch (e) { alert(e.message); }
}
async function createPat() {
  try {
    const out = await api("/pats", {method: "POST", body: JSON.stringify(
      {name: document.getElementById("pat-name").value,
       role: document.getElementById("pat-role").value})});
    document.getElementById("pat-new").textContent =
      "token (shown once): " + out.token;
    refresh();
  } catch (e) { alert(e.message); }
}
async function revoke(id) {
  try { await api(`/pats/${id}:revoke`, {method: "POST", body: "{}"}); refresh(); }
  catch (e) { alert(e.message); }
}
async function boot() {
  try {
    if (await oauthCallback()) { /* token stored from the callback */ }
  } catch (e) {
    // Expired state / replayed callback: clean the URL, surface the
    // error, and fall through to the sign-in options.
    history.replaceState(null, "", location.pathname);
    document.getElementById("signin-msg").textContent = e.message;
  }
  if (!tok() && !(await oauthRefresh())) { oauthButtons(); return; }
  if (!tok()) { oauthButtons(); return; }
  document.getElementById("signin").style.display = "none";
  document.getElementById("app").style.display = "block";
  document.getElementById("who").textContent = localStorage.getItem("df_user") || "?";
  document.getElementById("role").textContent = localStorage.getItem("df_role") || "?";
  refresh();
  setInterval(refresh, 10000);
}
boot();
</script>
</body>
</html>
"""
