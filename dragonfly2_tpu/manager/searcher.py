"""Scheduler-cluster searcher (reference: manager/searcher/searcher.go).

A joining daemon reports (ip, hostname, idc, location); the searcher ranks
scheduler clusters by weighted affinity and returns them best-first.

Weights (searcher.go:49-62): CIDR 0.3, hostname-regex 0.3, IDC 0.25,
location 0.14, cluster-type (default flag) 0.01.  Location affinity
matches '|'-separated prefix segments capped at 5 (like the evaluator's).
Clusters with no live schedulers are filtered out (searcher.go:146-152).
"""

from __future__ import annotations

import ipaddress
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

CIDR_WEIGHT = 0.3
HOSTNAME_WEIGHT = 0.3
IDC_WEIGHT = 0.25
LOCATION_WEIGHT = 0.14
CLUSTER_TYPE_WEIGHT = 0.01

MAX_LOCATION_ELEMENTS = 5


@dataclass
class ClusterScopes:
    """Affinity scopes configured per cluster (searcher.go Scopes)."""

    idc: str = ""                      # '|' separated accepted IDCs
    location: str = ""                 # '|' separated path
    cidrs: Sequence[str] = field(default_factory=tuple)
    hostnames: Sequence[str] = field(default_factory=tuple)  # regexes


@dataclass
class SchedulerCluster:
    id: str
    name: str = ""
    scopes: ClusterScopes = field(default_factory=ClusterScopes)
    is_default: bool = False
    scheduler_ids: List[str] = field(default_factory=list)  # live schedulers


def _cidr_score(ip: str, cidrs: Sequence[str]) -> float:
    if not ip or not cidrs:
        return 0.0
    try:
        addr = ipaddress.ip_address(ip)
    except ValueError:
        return 0.0
    for cidr in cidrs:
        try:
            if addr in ipaddress.ip_network(cidr, strict=False):
                return 1.0
        except ValueError:
            continue
    return 0.0


def _hostname_score(hostname: str, patterns: Sequence[str]) -> float:
    if not hostname or not patterns:
        return 0.0
    for pat in patterns:
        try:
            if re.search(pat, hostname):
                return 1.0
        except re.error:
            continue
    return 0.0


def _idc_score(idc: str, scope_idc: str) -> float:
    if not idc or not scope_idc:
        return 0.0
    accepted = {s.strip().lower() for s in scope_idc.split("|")}
    return 1.0 if idc.lower() in accepted else 0.0


def _location_score(location: str, scope_location: str) -> float:
    if not location or not scope_location:
        return 0.0
    if location.lower() == scope_location.lower():
        return 1.0
    a, b = location.split("|"), scope_location.split("|")
    n = min(len(a), len(b), MAX_LOCATION_ELEMENTS)
    score = 0
    for i in range(n):
        if a[i].lower() != b[i].lower():
            break
        score += 1
    return score / MAX_LOCATION_ELEMENTS


class Searcher:
    """FindSchedulerClusters (searcher.go:106-139)."""

    def evaluate(
        self,
        cluster: SchedulerCluster,
        *,
        ip: str = "",
        hostname: str = "",
        idc: str = "",
        location: str = "",
    ) -> float:
        s = cluster.scopes
        return (
            CIDR_WEIGHT * _cidr_score(ip, s.cidrs)
            + HOSTNAME_WEIGHT * _hostname_score(hostname, s.hostnames)
            + IDC_WEIGHT * _idc_score(idc, s.idc)
            + LOCATION_WEIGHT * _location_score(location, s.location)
            + CLUSTER_TYPE_WEIGHT * (1.0 if cluster.is_default else 0.0)
        )

    def find_scheduler_clusters(
        self,
        clusters: Sequence[SchedulerCluster],
        *,
        ip: str = "",
        hostname: str = "",
        conditions: Optional[Dict[str, str]] = None,
    ) -> List[SchedulerCluster]:
        conditions = conditions or {}
        live = [c for c in clusters if c.scheduler_ids]
        if not live:
            raise LookupError("no scheduler clusters with live schedulers")
        return sorted(
            live,
            key=lambda c: self.evaluate(
                c,
                ip=ip,
                hostname=hostname,
                idc=conditions.get("idc", ""),
                location=conditions.get("location", ""),
            ),
            reverse=True,
        )
