"""Dynamic configuration: manager-sourced, observer-notified, disk-cached.

Reference semantics (internal/dynconfig/dynconfig.go:45-136,
scheduler/config/dynconfig.go:58-137, client/config/dynconfig_manager.go):
- clients poll the manager every ``refresh_interval`` for cluster-scoped
  config (scheduler lists, cluster overrides like candidate/filter parent
  limits, active model versions);
- observers register and are notified on change;
- every successful fetch is cached to disk; when the manager is
  unreachable the cached copy keeps the service running.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class DynconfigServer:
    """Manager-side: per-scope config versions (the source of truth)."""

    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._data: Dict[str, Dict[str, Any]] = {}
        self._version: Dict[str, int] = {}

    def set(self, scope: str, config: Dict[str, Any]) -> int:
        with self._mu:
            self._data[scope] = dict(config)
            self._version[scope] = self._version.get(scope, 0) + 1
            return self._version[scope]

    def update(self, scope: str, **fields: Any) -> int:
        with self._mu:
            merged = dict(self._data.get(scope, {}))
            merged.update(fields)
            return self.set(scope, merged)

    def get(self, scope: str) -> tuple:
        """Returns (config, version); raises KeyError for unknown scope."""
        with self._mu:
            return dict(self._data[scope]), self._version[scope]


class Dynconfig:
    """Client-side cached fetcher with observers and disk fallback."""

    def __init__(
        self,
        fetch: Callable[[], Dict[str, Any]],
        *,
        refresh_interval: float = 300.0,
        cache_path: Optional[str] = None,
        backoff_rng=None,
    ) -> None:
        from ..rpc.retry import DecorrelatedJitterBackoff

        self._fetch = fetch
        self._interval = refresh_interval
        self._cache_path = cache_path
        self._mu = threading.RLock()
        self._data: Optional[Dict[str, Any]] = None
        self._fetched_at = 0.0
        self._notified = False  # observers have seen SOME config
        self._observers: List[Callable[[Dict[str, Any]], None]] = []
        # Refresh FAILURES back off with capped decorrelated jitter so a
        # restarting manager is not met by the whole fleet's polls in one
        # synchronized wave; a success resets to the normal cadence.
        # Seeded rng => reproducible per-instance schedule.
        self._backoff = DecorrelatedJitterBackoff(
            base=min(2.0, refresh_interval),
            cap=max(refresh_interval, 2.0),
            rng=backoff_rng,
        )
        self.last_refresh_ok = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- observers (dynconfig.go:361-412 observer pattern) -------------------

    def register(self, observer: Callable[[Dict[str, Any]], None]) -> None:
        with self._mu:
            self._observers.append(observer)
            data = self._data
        if data is not None:
            observer(dict(data))

    def deregister(self, observer: Callable[[Dict[str, Any]], None]) -> None:
        with self._mu:
            if observer in self._observers:
                self._observers.remove(observer)

    # -- fetch / cache -------------------------------------------------------

    def _load_disk_cache(self) -> Optional[Dict[str, Any]]:
        if not self._cache_path or not os.path.exists(self._cache_path):
            return None
        try:
            with open(self._cache_path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _store_disk_cache(self, data: Dict[str, Any]) -> None:
        if not self._cache_path:
            return
        tmp = self._cache_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self._cache_path)
        except OSError:
            pass

    def refresh(self) -> bool:
        """One fetch; on failure fall back to memory then disk cache.
        Returns True if new data was obtained and observers notified.

        Observers are guaranteed to see config at least once even when the
        first data comes from the disk cache during a manager outage, and
        even when post-recovery data equals the cached copy.
        """
        try:
            data = self._fetch()
        except Exception:  # noqa: BLE001 — manager outage must not kill clients
            self.last_refresh_ok = False
            observers: List[Callable[[Dict[str, Any]], None]] = []
            with self._mu:
                if self._data is None:
                    disk = self._load_disk_cache()
                    if disk is not None:
                        self._data = disk
                        if not self._notified:
                            observers = list(self._observers)
                            self._notified = bool(observers)
                fallback = self._data
            for obs in observers:
                obs(dict(fallback))
            return False
        self.last_refresh_ok = True
        self._backoff.reset()
        with self._mu:
            changed = data != self._data or not self._notified
            self._data = data
            self._fetched_at = time.time()
            observers = list(self._observers) if changed else []
            if observers:
                self._notified = True
        self._store_disk_cache(data)
        for obs in observers:
            try:
                obs(dict(data))
            except Exception:  # noqa: BLE001 — one bad observer must not
                # starve the others or kill the refresh thread.
                import logging

                logging.getLogger(__name__).exception("dynconfig observer failed")
        return changed

    def get(self) -> Dict[str, Any]:
        with self._mu:
            if self._data is not None and (
                time.time() - self._fetched_at < self._interval
            ):
                return dict(self._data)
        self.refresh()
        with self._mu:
            if self._data is None:
                raise RuntimeError("dynconfig: no data and manager unreachable")
            return dict(self._data)

    # -- background serve ----------------------------------------------------

    def serve(self) -> None:
        if self._thread is not None:
            return
        self.refresh()

        def loop() -> None:
            wait = self._interval if self.last_refresh_ok else self._backoff.next()
            while not self._stop.wait(wait):
                try:
                    self.refresh()
                except Exception:  # noqa: BLE001 — the refresh thread is forever
                    import logging

                    logging.getLogger(__name__).exception("dynconfig refresh failed")
                # Failure cadence: decorrelated-jitter backoff until the
                # manager answers again (anti-thundering-herd).
                wait = (
                    self._interval if self.last_refresh_ok
                    else self._backoff.next()
                )

        self._thread = threading.Thread(target=loop, name="dynconfig", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
