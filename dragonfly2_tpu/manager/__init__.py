"""Manager control plane (reference: manager/).

The pieces of the reference manager the learned-scheduling loop depends
on, rebuilt as an embeddable runtime:

- ``registry``  — the model registry: versioned immutable scorer
  artifacts with transactional single-active activation per scheduler
  (reference: manager/rpcserver/manager_server_v1.go:802-901 CreateModel,
  manager/service/model.go:103-190 activation, manager/models/model.go
  schema).  Artifacts are the trainer's local-scorer blobs rather than
  Triton ``model.graphdef`` dirs.
- ``searcher``  — scheduler-cluster selection for joining daemons by
  weighted affinity (manager/searcher/searcher.go:106-287).
- ``dynconfig`` — manager-sourced dynamic config with observer
  notification and disk-cache fallback (internal/dynconfig/dynconfig.go,
  scheduler/config/dynconfig.go:58-137).
- ``cluster``   — scheduler/seed-peer cluster records + keepalive state
  (manager/models, keepalive at manager_server_v2.go:749).
- ``users``     — user accounts, pbkdf2 passwords, personal access
  tokens (manager/models/user.go, personal_access_token.go, handlers).
- ``oauth``     — OAuth2 authorization-code sign-in seam
  (manager/models/oauth.go).
"""

from .registry import ArtifactDigestError, Model, ModelRegistry, ModelState  # noqa: F401
from .searcher import ClusterScopes, SchedulerCluster, Searcher  # noqa: F401
from .dynconfig import Dynconfig, DynconfigServer  # noqa: F401
from .cluster import ClusterManager, SchedulerInstance, SeedPeerInstance  # noqa: F401
from .users import PersonalAccessToken, User, UserStore  # noqa: F401
from .oauth import OAuthProvider, OAuthSignin  # noqa: F401
