"""OpenAPI description of the manager REST surface (reference:
api/manager/swagger.json — the gin-swagger export the console and API
clients consume).  Served at GET /swagger.json and /api/v1/openapi by
manager/rest.py; hand-maintained next to the routes it describes."""

from __future__ import annotations


def _op(summary, *, body=None, params=None, roles=None):
    op = {"summary": summary, "responses": {"200": {"description": "OK"}}}
    if body:
        op["requestBody"] = {
            "content": {"application/json": {"schema": {
                "type": "object", "properties": body,
            }}}
        }
    if params:
        op["parameters"] = [
            {"name": n, "in": "query", "schema": {"type": "string"}}
            for n in params
        ]
    if roles:
        op["description"] = f"Requires role ≥ {roles} when RBAC is enabled."
    return op


STR = {"type": "string"}
INT = {"type": "integer"}
OBJ = {"type": "object"}


def spec() -> dict:
    """The OpenAPI 3 document for every route manager/rest.py serves."""
    paths = {
        "/api/v1/healthy": {"get": _op("Liveness probe")},
        "/api/v1/models": {
            "get": _op("List models", params=["scheduler_id", "name"]),
            "post": _op("Create a model version (trainer flow)",
                        body={"name": STR, "type": STR, "scheduler_id": STR,
                              "artifact_b64": STR, "evaluation": OBJ},
                        roles="PEER"),
        },
        "/api/v1/models:active": {
            "get": _op("The single active model", params=["scheduler_id", "name"]),
        },
        "/api/v1/models:get": {"get": _op("Model by id", params=["id"])},
        "/api/v1/models:artifact": {
            "get": _op("Model artifact (base64)", params=["id"]),
        },
        "/api/v1/models/{id}:activate": {
            "post": _op("Activate (single-active per name)", roles="OPERATOR"),
        },
        "/api/v1/models/{id}:deactivate": {
            "post": _op("Deactivate", roles="OPERATOR"),
        },
        "/api/v1/schedulers": {
            "get": _op("Active scheduler instances"),
            "post": _op("Register a scheduler instance",
                        body={"id": STR, "cluster_id": STR, "hostname": STR,
                              "ip": STR, "port": INT},
                        roles="PEER"),
        },
        "/api/v1/schedulers/{id}:keepalive": {
            "post": _op("Liveness tick → {known}", roles="PEER"),
        },
        "/api/v1/clusters": {
            "get": _op("List scheduler-cluster records"),
            "post": _op("Create a scheduler cluster",
                        body={"id": STR, "name": STR,
                              "scheduler_cluster_config": OBJ,
                              "client_config": OBJ, "scopes": OBJ},
                        roles="OPERATOR"),
        },
        "/api/v1/clusters/{id}:update": {
            "post": _op("Partial update (limits apply LIVE via dynconfig)",
                        roles="OPERATOR"),
        },
        "/api/v1/clusters/{id}:delete": {"post": _op("Delete", roles="OPERATOR")},
        "/api/v1/clusters/{id}:config": {
            "get": _op("The dynconfig payload schedulers poll"),
        },
        "/api/v1/clusters:search": {
            "get": _op("Rank clusters for a client",
                       params=["ip", "hostname", "idc", "location"]),
        },
        "/api/v1/applications": {
            "get": _op("List applications"),
            "post": _op("Create an application",
                        body={"name": STR, "url": STR, "bio": STR,
                              "priority": INT},
                        roles="OPERATOR"),
        },
        "/api/v1/applications/{id}:update": {
            "post": _op("Partial update", roles="OPERATOR"),
        },
        "/api/v1/applications/{id}:delete": {
            "post": _op("Delete", roles="OPERATOR"),
        },
        "/api/v1/configs": {
            "get": _op("List named config rows"),
            "post": _op("Create a config (name unique)",
                        body={"name": STR, "value": STR, "bio": STR},
                        roles="OPERATOR"),
        },
        "/api/v1/configs/{id}:update": {
            "post": _op("Partial update", roles="OPERATOR"),
        },
        "/api/v1/configs/{id}:delete": {"post": _op("Delete", roles="OPERATOR")},
        "/api/v1/buckets": {
            "get": _op("List buckets (configured backend)"),
            "post": _op("Create a bucket", body={"name": STR},
                        roles="OPERATOR"),
        },
        "/api/v1/buckets/{name}:delete": {
            "post": _op("Destroy a bucket", roles="OPERATOR"),
        },
        "/api/v1/topology": {
            "get": _op("Cross-replica probe-edge pull", params=["exclude"]),
            "post": _op("Scheduler probe-edge push",
                        body={"scheduler_id": STR, "edges":
                              {"type": "array", "items": OBJ}},
                        roles="PEER"),
        },
        "/api/v1/jobs": {
            "get": _op("Recent group jobs (console view)"),
            "post": _op("Create a group job (preheat, sync_peers)",
                        body={"type": STR, "args": OBJ, "queues":
                              {"type": "array", "items": STR}},
                        roles="OPERATOR"),
        },
        "/api/v1/certs:issue": {
            "post": _op("Issue a cluster-CA-signed certificate from a CSR "
                        "(certify flow; TTL server-capped)",
                        body={"csr_pem": STR, "ttl_hours": INT},
                        roles="PEER"),
        },
        "/api/v1/certs:ca": {
            "get": _op("Cluster trust root (PEM)"),
        },
        "/api/v1/jobs/{group_id}": {"get": _op("Group job state")},
        "/api/v1/jobs:poll": {
            "post": _op("Worker long-poll",
                        body={"queue": STR, "timeout_s": INT}, roles="PEER"),
        },
        "/api/v1/jobs/{id}:result": {
            "post": _op("Worker result report",
                        body={"state": STR, "result": OBJ, "error": STR},
                        roles="PEER"),
        },
        "/api/v1/users:signup": {
            "post": _op("Open signup (READONLY role)",
                        body={"name": STR, "password": STR, "email": STR}),
        },
        "/api/v1/users:signin": {
            "post": _op("Password signin → session token",
                        body={"name": STR, "password": STR}),
        },
        "/api/v1/users": {"get": _op("List users", roles="ADMIN")},
        "/api/v1/users/{id}:role": {"post": _op("Set role", roles="ADMIN")},
        "/api/v1/users/{id}:state": {
            "post": _op("Enable/disable", roles="ADMIN"),
        },
        "/api/v1/users/{id}:reset-password": {
            "post": _op("Reset password (self w/ session, or ADMIN)"),
        },
        "/api/v1/pats": {
            "get": _op("Own personal access tokens", params=["user_id"]),
            "post": _op("Create a PAT (raw shown once)",
                        body={"name": STR, "role": STR, "ttl_s": INT}),
        },
        "/api/v1/pats/{id}:revoke": {"post": _op("Revoke a PAT")},
        "/api/v1/oauth:providers": {"get": _op("OAuth providers")},
        "/api/v1/oauth/{name}:authorize-url": {
            "get": _op("Provider authorize URL", params=["redirect_uri"]),
        },
        "/api/v1/oauth/{name}:signin": {
            "post": _op("OAuth code exchange → session token",
                        body={"code": STR, "state": STR,
                              "redirect_uri": STR}),
        },
    }
    from .. import __version__

    return {
        "openapi": "3.0.3",
        "info": {
            "title": "dragonfly2-tpu manager API",
            "version": __version__,
            "description": (
                "Control-plane REST surface (reference parity: "
                "api/manager/swagger.json).  Mutations authenticate with "
                "`Authorization: Bearer <session token | PAT>` when RBAC "
                "is enabled; reads stay open."
            ),
        },
        "paths": paths,
    }
