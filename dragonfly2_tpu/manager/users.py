"""Users and personal access tokens (manager RBAC completion).

Reference: manager's user accounts (manager/models/user.go, signup/signin
handlers in manager/handlers/user.go), casbin role bindings
(manager/permission/rbac), and personal access tokens
(manager/models/personal_access_token.go) guarding the REST surface.

TPU-build shape: pbkdf2-hashed passwords and sha256-hashed PATs in the
same embedded-sqlite idiom as the model registry; session auth is the
HMAC bearer token from security/tokens.py, so one verifier chain covers
console sessions AND machine PATs.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, List, Optional

from ..security.tokens import Role

if TYPE_CHECKING:  # state seam type (no runtime import needed)
    from .state import StateBackend

PBKDF2_ITERATIONS = 100_000
PAT_PREFIX = "dfp_"  # raw token shape: dfp_<hex>; only the hash is stored


@dataclass
class User:
    id: str
    name: str
    email: str = ""
    role: Role = Role.READONLY
    state: str = "enabled"  # enabled | disabled
    created_at: float = field(default_factory=time.time)


@dataclass
class PersonalAccessToken:
    id: str
    user_id: str
    name: str
    role: Role
    token_hash: str
    expires_at: float
    revoked: bool = False
    created_at: float = field(default_factory=time.time)

    @property
    def expired(self) -> bool:
        return time.time() > self.expires_at


def _hash_password(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac(
        "sha256", password.encode(), salt, PBKDF2_ITERATIONS
    )


def _hash_pat(raw: str) -> str:
    return hashlib.sha256(raw.encode()).hexdigest()


class _BackendUserStore:
    """users/pats as JSON docs behind the manager's state seam
    (manager/state.StateBackend); binary hash/salt fields ride base64."""

    def __init__(self, backend: "StateBackend") -> None:
        self._users = backend.table("users")
        self._pats = backend.table("pats")

    def upsert_user(self, u: "User", password_hash: bytes, salt: bytes) -> None:
        import base64

        self._users.put(u.id, {
            "id": u.id, "name": u.name, "email": u.email,
            "role": int(u.role), "state": u.state, "created_at": u.created_at,
            "password_hash": base64.b64encode(password_hash).decode(),
            "salt": base64.b64encode(salt).decode(),
        })

    def upsert_pat(self, p: "PersonalAccessToken") -> None:
        self._pats.put(p.id, {
            "id": p.id, "user_id": p.user_id, "name": p.name,
            "role": int(p.role), "token_hash": p.token_hash,
            "expires_at": p.expires_at, "revoked": p.revoked,
            "created_at": p.created_at,
        })

    def load_all(self):
        import base64

        users, creds, pats = {}, {}, {}
        for d in self._users.load_all().values():
            u = User(id=d["id"], name=d["name"], email=d["email"],
                     role=Role(d["role"]), state=d["state"],
                     created_at=d["created_at"])
            users[u.id] = u
            creds[u.id] = (
                base64.b64decode(d["password_hash"]),
                base64.b64decode(d["salt"]),
            )
        for d in self._pats.load_all().values():
            pats[d["id"]] = PersonalAccessToken(
                id=d["id"], user_id=d["user_id"], name=d["name"],
                role=Role(d["role"]), token_hash=d["token_hash"],
                expires_at=d["expires_at"], revoked=bool(d["revoked"]),
                created_at=d["created_at"],
            )
        return users, creds, pats


class UserStore:
    """In-memory source of truth with write-through persistence via
    the manager state seam (sqlite embedded; external SQL/KV for HA)."""

    def __init__(
        self, db_path: Optional[str] = None, *,
        backend: "Optional[StateBackend]" = None,
    ) -> None:
        self._mu = threading.RLock()
        self._users: Dict[str, User] = {}
        self._creds: Dict[str, tuple] = {}  # user_id → (hash, salt)
        self._pats: Dict[str, PersonalAccessToken] = {}
        self._db: Optional[_BackendUserStore] = None
        if backend is None and db_path:
            from .state import SQLiteBackend

            backend = SQLiteBackend(db_path)
        if backend is not None:
            self._db = _BackendUserStore(backend)
            self._users, self._creds, self._pats = self._db.load_all()

    # -- users (handlers/user.go signup/signin) -----------------------------

    def create_user(
        self,
        name: str,
        password: str,
        *,
        email: str = "",
        role: Role = Role.READONLY,
    ) -> User:
        if len(password) < 8:
            raise ValueError("password must be >= 8 characters")
        with self._mu:
            if any(u.name == name for u in self._users.values()):
                raise ValueError(f"user {name!r} already exists")
            salt = secrets.token_bytes(16)
            user = User(
                id=f"user-{secrets.token_hex(8)}", name=name,
                email=email, role=role,
            )
            pw_hash = _hash_password(password, salt)
            self._users[user.id] = user
            self._creds[user.id] = (pw_hash, salt)
            if self._db:
                self._db.upsert_user(user, pw_hash, salt)
            return user

    def ensure_root(self, password: str) -> User:
        """First-boot bootstrap: an admin 'root' user (the reference seeds
        one through DB migration)."""
        with self._mu:
            existing = self.by_name("root")
            if existing is not None:
                return existing
        return self.create_user("root", password, role=Role.ADMIN)

    def by_name(self, name: str) -> Optional[User]:
        with self._mu:
            for u in self._users.values():
                if u.name == name:
                    return u
        return None

    def get(self, user_id: str) -> Optional[User]:
        with self._mu:
            return self._users.get(user_id)

    def list_users(self) -> List[User]:
        with self._mu:
            return sorted(self._users.values(), key=lambda u: u.created_at)

    def verify_password(self, name: str, password: str) -> Optional[User]:
        """The signin check; None on unknown user / bad password /
        disabled account.  Constant-time hash comparison."""
        user = self.by_name(name)
        if user is None or user.state != "enabled":
            return None
        with self._mu:
            pw_hash, salt = self._creds[user.id]
        if hmac.compare_digest(_hash_password(password, salt), pw_hash):
            return user
        return None

    def reset_password(self, user_id: str, new_password: str) -> None:
        if len(new_password) < 8:
            raise ValueError("password must be >= 8 characters")
        with self._mu:
            user = self._users[user_id]
            salt = secrets.token_bytes(16)
            pw_hash = _hash_password(new_password, salt)
            self._creds[user_id] = (pw_hash, salt)
            if self._db:
                self._db.upsert_user(user, pw_hash, salt)

    def set_role(self, user_id: str, role: Role) -> User:
        with self._mu:
            user = self._users[user_id]
            user.role = role
            if self._db:
                pw_hash, salt = self._creds[user_id]
                self._db.upsert_user(user, pw_hash, salt)
            return user

    def set_state(self, user_id: str, state: str) -> User:
        if state not in ("enabled", "disabled"):
            raise ValueError(f"bad state {state!r}")
        with self._mu:
            user = self._users[user_id]
            user.state = state
            if self._db:
                pw_hash, salt = self._creds[user_id]
                self._db.upsert_user(user, pw_hash, salt)
            return user

    # -- personal access tokens ---------------------------------------------

    def create_pat(
        self,
        user_id: str,
        name: str,
        *,
        role: Optional[Role] = None,
        ttl_s: float = 90 * 24 * 3600.0,
    ) -> tuple:
        """→ (PersonalAccessToken, raw_token).  The raw token is shown
        exactly once; only its sha256 is stored.  A PAT's role is capped
        at its owner's role — tokens can't escalate."""
        with self._mu:
            user = self._users[user_id]
            granted = user.role if role is None else min(role, user.role)
            raw = PAT_PREFIX + secrets.token_hex(20)
            pat = PersonalAccessToken(
                id=f"pat-{secrets.token_hex(8)}", user_id=user_id, name=name,
                role=Role(granted), token_hash=_hash_pat(raw),
                expires_at=time.time() + ttl_s,
            )
            self._pats[pat.id] = pat
            if self._db:
                self._db.upsert_pat(pat)
            return pat, raw

    def list_pats(self, user_id: Optional[str] = None) -> List[PersonalAccessToken]:
        with self._mu:
            pats = list(self._pats.values())
        if user_id is not None:
            pats = [p for p in pats if p.user_id == user_id]
        return sorted(pats, key=lambda p: p.created_at)

    def revoke_pat(self, pat_id: str) -> None:
        with self._mu:
            pat = self._pats[pat_id]
            pat.revoked = True
            if self._db:
                self._db.upsert_pat(pat)

    def authenticate_pat(self, raw: str) -> Optional[User]:
        """→ owning user (with role capped to the PAT's grant) when the
        raw token is live; None otherwise."""
        if not raw.startswith(PAT_PREFIX):
            return None
        h = _hash_pat(raw)
        with self._mu:
            for pat in self._pats.values():
                if hmac.compare_digest(pat.token_hash, h):
                    if pat.revoked or pat.expired:
                        return None
                    user = self._users.get(pat.user_id)
                    if user is None or user.state != "enabled":
                        return None
                    # The caller sees the PAT's effective role.
                    return User(
                        id=user.id, name=user.name, email=user.email,
                        role=min(pat.role, user.role), state=user.state,
                        created_at=user.created_at,
                    )
        return None
