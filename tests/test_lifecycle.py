"""Self-driving model lifecycle plane (ISSUE 19, DESIGN.md §29).

Covers the subsystem end to end:

- arbiter decision kernel: epoch cadence (plan_epoch) and the
  global-vs-regional CANARY admission gate (arbitrate_candidates),
  including input-order determinism — both are DF018 replay roots;
- LifecycleStore durability: row defaults, resume-from-backend, the
  bounded promotion-history tail;
- LifecycleDaemon units: epoch deferral without a full batch, the
  crash-between-register-and-begin re-entry, regional arbitration
  retiring a specialization that buys nothing;
- the zero-human acceptance drill (sim/lifecycle.py): unattended
  train→export→register→SHADOW→CANARY→ACTIVE, injected-regression
  auto-rollback, bounce-resume to exactly one ACTIVE;
- ModelSubscriber regional keys: a scheduler serves ITS region's
  promoted specialization and every other region keeps the global arm
  (no cross-region bleed), with per-key version bookkeeping;
- tools/bench_lifecycle.py --smoke JSON schema gate (tier-1).

The HA leader-kill-mid-promotion chaos drill lives in
tests/test_lifecycle_failover.py.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]

from dragonfly2_tpu.lifecycle import (
    GLOBAL_KEY,
    LifecycleConfig,
    LifecycleDaemon,
    LifecycleStore,
    arbitrate_candidates,
    plan_epoch,
    regional_model_name,
)
from dragonfly2_tpu.lifecycle.state import HISTORY_KEEP
from dragonfly2_tpu.manager import ModelRegistry, ModelState
from dragonfly2_tpu.manager.state import MemoryBackend
from dragonfly2_tpu.records.features import DOWNLOAD_FEATURE_DIM
from dragonfly2_tpu.rollout import (
    LocalRolloutClient,
    RolloutController,
    RolloutGuardrails,
)
from dragonfly2_tpu.scheduler import MLEvaluator, ModelSubscriber
from dragonfly2_tpu.sim.lifecycle import (
    LifecycleDrillConfig,
    _World,
    run_lifecycle_drill,
)
from dragonfly2_tpu.trainer.export import MLPScorer, scorer_to_bytes
from dragonfly2_tpu.trainer.streaming import StreamingConfig, StreamingTrainer

MODEL_NAME = "parent-bandwidth-mlp"


def _mk_scorer(seed):
    rng = np.random.default_rng(seed)
    dims = (DOWNLOAD_FEATURE_DIM, 16, 1)
    weights = [
        (
            rng.standard_normal((dims[i], dims[i + 1])).astype(np.float32) * 0.3,
            rng.standard_normal(dims[i + 1]).astype(np.float32) * 0.05,
        )
        for i in range(len(dims) - 1)
    ]
    return MLPScorer(weights=weights)


def _shadow_report(joined=500, regret=0.05):
    return {
        "joined_edges": joined,
        "announces": joined // 4,
        "regret_at_k": {"k": 4, "candidate": regret, "active": 0.3},
        "inversion_rate": {"pairs": joined, "candidate": 0.1, "active": 0.3},
        "psi_max": 0.01,
    }


# ---------------------------------------------------------------------------
# Arbiter: the pure decision kernel (DF018 replay roots)
# ---------------------------------------------------------------------------


class TestPlanEpoch:
    def test_holds_below_cadence(self):
        plan = plan_epoch(records_seen=100, watermark=0, epoch_records=256,
                          candidate_in_flight=False)
        assert plan["train"] is False
        assert plan["watermark"] == 0
        assert "100/256" in plan["reason"]

    def test_cuts_when_cadence_reached_and_advances_watermark(self):
        plan = plan_epoch(records_seen=300, watermark=0, epoch_records=256,
                          candidate_in_flight=False)
        assert plan["train"] is True
        assert plan["watermark"] == 300  # next epoch measures from HERE

    def test_one_candidate_in_flight_blocks_the_next_epoch(self):
        plan = plan_epoch(records_seen=10_000, watermark=0, epoch_records=256,
                          candidate_in_flight=True)
        assert plan["train"] is False
        assert plan["reason"] == "candidate still in flight"

    def test_disabled_cadence_never_trains(self):
        plan = plan_epoch(records_seen=10_000, watermark=0, epoch_records=0,
                          candidate_in_flight=False)
        assert plan["train"] is False


class TestArbitrateCandidates:
    def test_thin_evidence_holds(self):
        verdict = arbitrate_candidates(
            {GLOBAL_KEY: _shadow_report(joined=10)}, min_joined=50,
        )
        assert verdict["advance"] == []
        assert verdict["hold"] == {GLOBAL_KEY: "10/50 joined samples"}
        assert verdict["retire"] == {}

    def test_regional_must_beat_global_by_margin_ties_go_to_global(self):
        verdict = arbitrate_candidates(
            {
                GLOBAL_KEY: _shadow_report(regret=0.30),
                "idc-a": _shadow_report(regret=0.21),   # beats by > 0.02
                "idc-b": _shadow_report(regret=0.29),   # within the margin
            },
            min_joined=50, margin=0.02,
        )
        assert verdict["advance"] == [GLOBAL_KEY, "idc-a"]
        assert "idc-b" in verdict["retire"]
        assert "does not beat global" in verdict["retire"]["idc-b"]

    def test_global_retired_only_when_beaten_everywhere(self):
        verdict = arbitrate_candidates(
            {
                GLOBAL_KEY: _shadow_report(regret=0.50),
                "idc-a": _shadow_report(regret=0.10),
                "idc-b": _shadow_report(regret=0.20),
            },
            min_joined=50, margin=0.02,
        )
        assert verdict["advance"] == ["idc-a", "idc-b"]
        assert GLOBAL_KEY in verdict["retire"]

    def test_regional_without_global_candidate_advances(self):
        verdict = arbitrate_candidates(
            {"idc-a": _shadow_report(regret=0.4)}, min_joined=50,
        )
        assert verdict["advance"] == ["idc-a"]
        assert verdict["retire"] == {}

    def test_held_global_holds_eligible_regionals(self):
        """A global candidate below the evidence floor is NOT absent:
        regionals must beat it, not outrace its sample accumulation —
        everyone holds until the global arm can be judged."""
        verdict = arbitrate_candidates(
            {
                GLOBAL_KEY: _shadow_report(joined=10),
                "idc-a": _shadow_report(regret=0.01),  # eligible + excellent
            },
            min_joined=50, margin=0.02,
        )
        assert verdict["advance"] == []
        assert verdict["retire"] == {}
        assert "global candidate below evidence floor" in verdict["hold"]["idc-a"]
        assert verdict["hold"][GLOBAL_KEY] == "10/50 joined samples"

    def test_verdict_ignores_input_insertion_order(self):
        """The replay root must be a pure function of the report VALUES:
        two daemons assembling the same reports in different dict orders
        (hash-seed skew) must emit byte-identical verdicts (DF019)."""
        reports = {
            GLOBAL_KEY: _shadow_report(regret=0.30),
            "idc-a": _shadow_report(regret=0.21),
            "idc-b": _shadow_report(regret=0.35),
            "idc-c": _shadow_report(joined=10),
        }
        forward = arbitrate_candidates(dict(reports))
        reversed_order = arbitrate_candidates(
            {k: reports[k] for k in reversed(list(reports))}
        )
        assert json.dumps(forward, sort_keys=True) == json.dumps(
            reversed_order, sort_keys=True
        )


class TestRegionalModelName:
    def test_global_key_is_the_bare_name(self):
        assert regional_model_name(MODEL_NAME, None) == MODEL_NAME
        assert regional_model_name(MODEL_NAME, GLOBAL_KEY) == MODEL_NAME

    def test_regions_compose_the_registry_key(self):
        assert regional_model_name(MODEL_NAME, "idc-a") == f"{MODEL_NAME}@idc-a"


# ---------------------------------------------------------------------------
# LifecycleStore: the DF014 `lifecycle` namespace
# ---------------------------------------------------------------------------


class TestLifecycleStore:
    def test_unknown_key_returns_a_default_row(self):
        store = LifecycleStore(MemoryBackend())
        row = store.row("global")
        assert row == {"epoch": 0, "watermark": 0, "candidate_id": "",
                       "candidate_version": 0, "history": []}

    def test_rows_survive_a_reload_from_the_backend(self):
        backend = MemoryBackend()
        store = LifecycleStore(backend)
        store.update("global", epoch=3, watermark=4096,
                     candidate_id="m-7", candidate_version=7)
        store.append_history("global", {"epoch": 3, "event": "registered"})
        resumed = LifecycleStore(backend)  # the manager bounce
        row = resumed.row("global")
        assert row["epoch"] == 3 and row["watermark"] == 4096
        assert resumed.candidate("global") == "m-7"
        assert row["history"] == [{"epoch": 3, "event": "registered"}]

    def test_history_tail_is_bounded(self):
        store = LifecycleStore(MemoryBackend())
        for i in range(HISTORY_KEEP + 20):
            store.append_history("global", {"epoch": i, "event": "registered"})
        history = store.row("global")["history"]
        assert len(history) == HISTORY_KEEP
        assert history[-1]["epoch"] == HISTORY_KEEP + 19  # newest kept

    def test_cleared_candidate_reads_as_none(self):
        store = LifecycleStore(MemoryBackend())
        store.update("global", candidate_id="m-1")
        store.update("global", candidate_id="")
        assert store.candidate("global") is None


# ---------------------------------------------------------------------------
# LifecycleDaemon units
# ---------------------------------------------------------------------------


def _drill_cfg(**kw):
    kw.setdefault("epoch_records", 128)
    kw.setdefault("batch_size", 32)
    kw.setdefault("announces", 24)
    kw.setdefault("parents", 4)
    kw.setdefault("min_shadow_samples", 40)
    kw.setdefault("min_canary_samples", 40)
    return LifecycleDrillConfig(**kw)


def _small_trainer(_key):
    return StreamingTrainer(
        StreamingConfig(batch_size=32, warmup_steps=4, learning_rate=3e-3,
                        snapshot_rows=512, seed=11)
    )


def _replay_source_for(registry, world, cfg, sid):
    """The sim drill's honest read side, re-pointed at ``registry``:
    scores REAL exported blobs and accumulates per candidate version so
    the controller sees joined counts grow across pumps."""
    from dragonfly2_tpu.trainer.export import load_scorer

    acc = {}

    def source(key):
        name = regional_model_name(cfg.model_name, key)
        cand = registry.candidate_model(sid, name)
        if cand is None:
            return None
        active = registry.active_model(sid, name)
        shadow, dl, _ = world.shadow_batch(
            load_scorer(registry.load_artifact(cand)), cand.version,
            load_scorer(registry.load_artifact(active)) if active else None,
            active.version if active else 0,
        )
        slot = acc.get(key)
        if slot is None or slot["version"] != cand.version:
            slot = {"version": cand.version, "shadow": [], "dl": []}
            acc[key] = slot
        slot["shadow"].append(shadow)
        slot["dl"].append(dl)
        return (np.concatenate(slot["shadow"]), np.concatenate(slot["dl"]))

    return source


class TestLifecycleDaemon:
    def test_epoch_defers_until_a_full_batch_lands(self):
        """Cadence fires on record count but the trainer needs one full
        batch: a thin feed leaves the watermark so the epoch re-fires
        once the rest arrives, instead of exporting an untrained net."""
        backend = MemoryBackend()
        registry = ModelRegistry(backend=backend)
        controller = RolloutController(registry, backend=backend)
        world = _World(_drill_cfg())
        daemon = LifecycleDaemon(
            registry, LocalRolloutClient(controller),
            config=LifecycleConfig(scheduler_id="s1", epoch_records=16),
            backend=backend, trainer_factory=_small_trainer,
        )
        daemon.feed(world.record_rows(20))  # past cadence, below batch 32
        assert daemon.step()["epochs"] == []
        assert daemon.store.row(GLOBAL_KEY)["epoch"] == 0
        daemon.feed(world.record_rows(44))
        assert daemon.step()["epochs"], "deferred epoch never re-fired"
        assert daemon.store.row(GLOBAL_KEY)["epoch"] == 1
        assert registry.candidate_model("s1", daemon.config.model_name)

    def test_storeless_daemon_keeps_watermark_in_memory(self):
        """The production CLI wiring (cli/trainer.py) passes no backend:
        the cadence contract — an epoch per ``epoch_records`` NEW
        records — must still hold, with watermarks in the in-memory
        store instead of reading 0 every cycle and cutting an epoch the
        moment each candidate resolves."""
        registry = ModelRegistry()
        controller = RolloutController(registry)
        world = _World(_drill_cfg())
        daemon = LifecycleDaemon(
            registry, LocalRolloutClient(controller),
            config=LifecycleConfig(scheduler_id="s1", epoch_records=16),
            trainer_factory=_small_trainer,
        )
        daemon.feed(world.record_rows(64))
        assert daemon.step()["epochs"], "first epoch never cut"
        row = daemon.store.row(GLOBAL_KEY)
        assert row["epoch"] == 1 and row["watermark"] == 64
        # Resolve the candidate; with NO new records the loop must idle
        # instead of endlessly re-registering candidates.
        cand = registry.candidate_model("s1", daemon.config.model_name)
        registry.deactivate(cand.id)
        assert daemon.step()["epochs"] == []
        assert daemon.store.row(GLOBAL_KEY)["epoch"] == 1
        assert registry.candidate_model("s1", daemon.config.model_name) is None

    def test_starved_second_epoch_defers_not_reexports(self):
        """trainer.step is cumulative: an epoch-2 cycle whose queue has
        no full batch must defer on THIS call's step count, not export
        unchanged weights because epoch 1 trained."""
        backend = MemoryBackend()
        registry = ModelRegistry(backend=backend)
        controller = RolloutController(registry, backend=backend)
        world = _World(_drill_cfg())
        daemon = LifecycleDaemon(
            registry, LocalRolloutClient(controller),
            config=LifecycleConfig(scheduler_id="s1", epoch_records=16),
            backend=backend, trainer_factory=_small_trainer,
        )
        daemon.feed(world.record_rows(64))
        assert daemon.step()["epochs"]
        assert daemon._trainers[GLOBAL_KEY].step > 0  # cumulative from now on
        cand = registry.candidate_model("s1", daemon.config.model_name)
        registry.deactivate(cand.id)  # epoch 1's candidate resolves
        daemon.feed(world.record_rows(20))  # past cadence, below batch 32
        assert daemon.step()["epochs"] == [], "starved epoch must defer"
        assert daemon.store.row(GLOBAL_KEY)["epoch"] == 1
        assert registry.candidate_model("s1", daemon.config.model_name) is None
        daemon.feed(world.record_rows(44))  # the rest of the batch lands
        assert daemon.step()["epochs"], "deferred epoch never re-fired"
        assert daemon.store.row(GLOBAL_KEY)["epoch"] == 2

    def test_full_trainer_queue_does_not_advance_cadence(self):
        """Rows the trainer queue rejected never train anything: they
        must not count toward the epoch cadence either."""
        registry = ModelRegistry()
        controller = RolloutController(registry)
        world = _World(_drill_cfg())

        def tiny_queue_trainer(_key):
            return StreamingTrainer(
                StreamingConfig(batch_size=32, queue_capacity=1,
                                snapshot_rows=512, seed=11)
            )

        daemon = LifecycleDaemon(
            registry, LocalRolloutClient(controller),
            config=LifecycleConfig(scheduler_id="s1", epoch_records=16),
            trainer_factory=tiny_queue_trainer,
        )
        daemon.feed(world.record_rows(8))   # enqueued
        daemon.feed(world.record_rows(8))   # queue full → dropped
        assert daemon.records_seen(GLOBAL_KEY) == 8
        assert daemon.records_dropped(GLOBAL_KEY) == 8

    def test_orphan_shadow_candidate_is_reentered(self):
        """A candidate that reached SHADOW without a rollout row (crash
        between create_model and begin on a remote manager): the report
        KeyErrors and the daemon re-begins the rollout."""
        registry = ModelRegistry()
        m1 = registry.create_model(name=MODEL_NAME, type="mlp",
                                   scheduler_id="s1",
                                   artifact=scorer_to_bytes(_mk_scorer(1)))
        registry.activate(m1.id)
        controller = RolloutController(registry)
        m2 = registry.create_model(name=MODEL_NAME, type="mlp",
                                   scheduler_id="s1",
                                   artifact=scorer_to_bytes(_mk_scorer(2)))
        # The tear: SHADOW in the registry, no rollout row anywhere.
        registry.set_state(m2.id, ModelState.SHADOW)
        assert controller.get("s1", MODEL_NAME) is None
        world = _World(_drill_cfg())
        cfg = _drill_cfg()
        daemon = LifecycleDaemon(
            registry, LocalRolloutClient(controller),
            config=LifecycleConfig(scheduler_id="s1", min_joined=10),
            backend=MemoryBackend(), trainer_factory=_small_trainer,
            replay_source=_replay_source_for(world=world, registry=registry,
                                             cfg=cfg, sid="s1"),
        )
        daemon.pump_rollouts()
        repaired = controller.get("s1", MODEL_NAME)
        assert repaired is not None and repaired.model_id == m2.id
        assert repaired.phase == "shadow"

    def test_arbitration_retires_a_specialization_that_buys_nothing(self):
        """Regional arm trained on the SAME records as the global arm:
        identical quality cannot beat global by the margin, so the
        arbiter retires it before CANARY and the global candidate walks
        to ACTIVE alone."""
        cfg = _drill_cfg()
        world = _World(cfg)
        backend = MemoryBackend()
        registry = ModelRegistry(backend=backend)
        controller = RolloutController(
            registry, backend=backend,
            guardrails=RolloutGuardrails(min_shadow_samples=40,
                                         min_canary_samples=40),
        )
        daemon = LifecycleDaemon(
            registry, LocalRolloutClient(controller),
            config=LifecycleConfig(
                scheduler_id="s1", regions=("idc-a",), epoch_records=128,
                max_steps_per_epoch=20, min_joined=10,
                arbitration_margin=0.25,
            ),
            backend=backend, trainer_factory=_small_trainer,
            replay_source=_replay_source_for(world=world, registry=registry,
                                             cfg=cfg, sid="s1"),
        )
        regional_name = f"{daemon.config.model_name}@idc-a"
        daemon.feed(world.record_rows(160), region="idc-a")
        for _ in range(6):
            daemon.step()
            if registry.active_model("s1", daemon.config.model_name):
                break
        assert registry.active_model("s1", daemon.config.model_name), (
            "global candidate never promoted"
        )
        # The specialization was retired, not promoted and not left
        # dangling: no ACTIVE, no candidate under the regional key.
        assert registry.active_model("s1", regional_name) is None
        assert registry.candidate_model("s1", regional_name) is None
        events = [h["event"] for h in daemon.store.row("idc-a")["history"]]
        assert "arbitration_retired" in events
        assert daemon.store.candidate("idc-a") is None


# ---------------------------------------------------------------------------
# The zero-human acceptance drill (sim/lifecycle.py)
# ---------------------------------------------------------------------------


class TestLifecycleDrill:
    def test_full_loop_regression_and_bounce_resume(self):
        out = run_lifecycle_drill(_drill_cfg())
        assert out["ok"], out
        s1, s2, s3 = out["stage1"], out["stage2"], out["stage3"]
        # Stage 1: train→export→register→SHADOW→CANARY→ACTIVE, no hands.
        assert s1["active_version"] == 1 and s1["candidate_clear"]
        # Stage 2: the inverted head was caught by the REAL guardrails
        # and stage 1's model stayed ACTIVE (last-good).
        assert s2["rolled_back"] and s2["active_version"] == 1
        assert "regression" in s2["rollback_reason"]
        # Stage 3: the bounce resumed — same epoch counter (no retrain),
        # the in-flight candidate promoted, exactly one ACTIVE row with
        # a digest-verified artifact.
        assert s3["had_in_flight"] and s3["promoted_resumed_candidate"]
        assert s3["resumed_epoch"] == s3["pre_bounce_epoch"]
        assert s3["active_count"] == 1 and s3["artifact_ok"]
        # Promotion lineage landed in the durable history.
        assert out["events"][:3] == ["registered", "advance", "promote"]
        assert "rollback" in out["events"]
        assert out["events"][-1] == "promote"


# ---------------------------------------------------------------------------
# ModelSubscriber regional keys: no cross-region bleed (satellite)
# ---------------------------------------------------------------------------


class TestModelSubscriberRegionalKeys:
    def _registry_two_arms(self):
        reg = ModelRegistry()
        mg = reg.create_model(name=MODEL_NAME, type="mlp", scheduler_id="s1",
                              artifact=scorer_to_bytes(_mk_scorer(1)))
        reg.activate(mg.id)
        ma = reg.create_model(name=f"{MODEL_NAME}@idc-a", type="mlp",
                              scheduler_id="s1",
                              artifact=scorer_to_bytes(_mk_scorer(2)))
        reg.activate(ma.id)
        return reg, mg, ma

    def test_region_serves_its_promoted_specialization(self):
        reg, _mg, ma = self._registry_two_arms()
        sub = ModelSubscriber(reg, MLEvaluator(None), scheduler_id="s1",
                              idc="idc-a")
        assert sub.refresh() is True
        assert sub._loaded_key == f"{MODEL_NAME}@idc-a"
        assert sub._loaded_version == ma.version

    def test_no_cross_region_bleed(self):
        """idc-a's specialization must never reach idc-b (or an
        idc-less scheduler): they only ever ask for their own two
        names and fall back to the global arm."""
        reg, mg, _ma = self._registry_two_arms()
        for idc in ("idc-b", None):
            sub = ModelSubscriber(reg, MLEvaluator(None), scheduler_id="s1",
                                  idc=idc)
            assert sub.refresh() is True
            assert sub._loaded_key == MODEL_NAME
            assert sub._loaded_version == mg.version

    def test_versions_never_compare_across_keys(self):
        """A NEWER global version must not displace a region's loaded
        specialization: versions are per-(scheduler_id, name) counters,
        so the scoped poll wins regardless of version arithmetic."""
        reg, _mg, ma = self._registry_two_arms()
        sub = ModelSubscriber(reg, MLEvaluator(None), scheduler_id="s1",
                              idc="idc-a")
        sub.refresh()
        mg2 = reg.create_model(name=MODEL_NAME, type="mlp", scheduler_id="s1",
                               artifact=scorer_to_bytes(_mk_scorer(3)))
        reg.activate(mg2.id)
        assert mg2.version > ma.version
        assert sub.refresh() is False  # no swap: the scoped arm still wins
        assert sub._loaded_key == f"{MODEL_NAME}@idc-a"
        assert sub._loaded_version == ma.version

    def test_retired_specialization_falls_back_to_global(self):
        reg, mg, ma = self._registry_two_arms()
        sub = ModelSubscriber(reg, MLEvaluator(None), scheduler_id="s1",
                              idc="idc-a")
        sub.refresh()
        reg.deactivate(ma.id)
        assert sub.refresh() is True
        assert sub._loaded_key == MODEL_NAME
        assert sub._loaded_version == mg.version

    def test_regional_candidate_scopes_shadow_and_reports(self):
        """A regional candidate in flight shadow-scores in ITS region
        only, and candidate_name hands the reporter the scoped key so
        the controller judges the right rollout row."""
        reg, _mg, _ma = self._registry_two_arms()
        controller = RolloutController(reg)
        client = LocalRolloutClient(controller)
        m3 = reg.create_model(name=f"{MODEL_NAME}@idc-a", type="mlp",
                              scheduler_id="s1",
                              artifact=scorer_to_bytes(_mk_scorer(4)))
        controller.begin(m3.id)
        ml_a, ml_b = MLEvaluator(None), MLEvaluator(None)
        sub_a = ModelSubscriber(reg, ml_a, scheduler_id="s1", idc="idc-a",
                                rollout_client=client)
        sub_b = ModelSubscriber(reg, ml_b, scheduler_id="s1", idc="idc-b",
                                rollout_client=client)
        sub_a.refresh()
        sub_b.refresh()
        assert ml_a.shadow is not None
        assert sub_a.candidate_name == f"{MODEL_NAME}@idc-a"
        assert ml_b.shadow is None, "idc-a's candidate bled into idc-b"
        assert sub_b.candidate_name == MODEL_NAME
        sub_a.stop()
        sub_b.stop()


# ---------------------------------------------------------------------------
# bench_lifecycle smoke: the tier-1 JSON schema gate
# ---------------------------------------------------------------------------


class TestBenchLifecycleSmoke:
    def test_smoke_emits_schema_json(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "bench_lifecycle.py"),
             "--smoke"],
            capture_output=True, text=True, timeout=300, cwd=str(REPO),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = proc.stdout.strip().splitlines()[-1]
        out = json.loads(line)
        sys.path.insert(0, str(REPO / "tools"))
        try:
            from bench_lifecycle import SCHEMA_KEYS
        finally:
            sys.path.pop(0)
        assert all(k in out for k in SCHEMA_KEYS), out
        assert out["ok"] is True and out["drill_ok"] is True
        assert out["records_per_sec"] > 0
