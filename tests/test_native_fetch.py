"""In-engine client fetch loop tests (DESIGN.md §28): the fallback
matrix proved byte-identical (native-both vs native-server vs
pure-Python — every piece, every Range shape, and the corrupt-body
refusal), the dispatch gates (TLS, attached tee consumer, piece-plane
fault scenarios, the dispatch seam itself), the mid-native-fetch
SIGKILL drill, and the bench smoke schema gate for the native-both
arm.  The byte-identity sweep runs twice — with the native library and
with it force-absent — because the Python arm is the reference the
native plane must never drift from."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu import native  # noqa: E402
from dragonfly2_tpu.daemon import DaemonStorage, UploadManager  # noqa: E402
from dragonfly2_tpu.daemon.conductor import Conductor  # noqa: E402
from dragonfly2_tpu.records.storage import Storage  # noqa: E402
from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler  # noqa: E402
from dragonfly2_tpu.rpc.piece_transport import PieceHTTPServer  # noqa: E402
from dragonfly2_tpu.rpc.scheduler_server import SchedulerHTTPServer  # noqa: E402
from dragonfly2_tpu.scheduler import (  # noqa: E402
    Evaluator,
    NetworkTopology,
    Resource,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
)
from dragonfly2_tpu.scheduler.resource import Host  # noqa: E402
from dragonfly2_tpu.utils import faultinject  # noqa: E402
from dragonfly2_tpu.utils.faultinject import (  # noqa: E402
    FaultInjector,
    FaultSpec,
    installed,
)

PIECE = 64 * 1024
N_PIECES = 6


def _origin_pieces(seed: int, n: int = N_PIECES, piece: int = PIECE):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, 256, piece, dtype=np.uint8).tobytes()
        for _ in range(n)
    ]


class _Origin:
    def __init__(self, pieces):
        self.pieces = pieces

    def fetch(self, url, number, piece_size):
        return self.pieces[number]


@pytest.fixture(scope="module")
def plane(tmp_path_factory):
    """One scheduler + one warm plain-HTTP wire parent holding every
    piece of the sweep task — the swarm every arm downloads from."""
    tmp = tmp_path_factory.mktemp("native-fetch-plane")
    pieces = _origin_pieces(11)
    url = "https://origin/native-fetch-sweep"
    content_length = N_PIECES * PIECE

    resource = Resource()
    service = SchedulerService(
        resource,
        Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
        Storage(str(tmp / "records"), buffer_size=8),
        NetworkTopology(resource.host_manager),
    )
    server = SchedulerHTTPServer(service)
    server.serve()

    pstore = DaemonStorage(str(tmp / "parent"), prefer_native=False)
    piece_server = PieceHTTPServer(UploadManager(pstore))
    piece_server.serve()
    phost = Host(
        id="nf-parent", hostname="nf-parent", ip="127.0.0.1",
        port=8002, download_port=piece_server.port,
    )
    phost.stats.network.idc = "idc-a"
    pclient = RemoteScheduler(server.url, timeout=5.0)
    parent = Conductor(
        phost, pstore, pclient,
        piece_fetcher=HTTPPieceFetcher(pclient.resolve_host),
        source_fetcher=_Origin(pieces),
    )
    warm = parent.download(
        url, piece_size=PIECE, content_length=content_length
    )
    assert warm.ok and warm.pieces == N_PIECES
    cleanup = []
    yield {
        "tmp": tmp,
        "scheduler": server,
        "url": url,
        "pieces": pieces,
        "content_length": content_length,
        "pclient": pclient,
        "service": service,
        "cleanup": cleanup,
    }
    for child_server, child_storage in cleanup:
        child_server.stop()
        child_storage.close()
    piece_server.stop()
    server.stop()
    assert native.leaked_servers() == (0, 0)


def _child_download(
    plane, store_dir, name, *, native_fetch, prefer_native=True,
    tenant="", piece_parallelism=4,
):
    """One wire child over the plane's swarm.  The child serves its own
    store for real (completed peers become parent candidates for later
    children — a dead advertised port would poison the pool); the plane
    fixture owns server/storage shutdown."""
    storage = DaemonStorage(str(store_dir), prefer_native=prefer_native)
    child_server = PieceHTTPServer(UploadManager(storage))
    child_server.serve()
    plane["cleanup"].append((child_server, storage))
    client = RemoteScheduler(plane["scheduler"].url, timeout=5.0)
    host = Host(
        id=name, hostname=name, ip="127.0.0.1", port=8002,
        download_port=child_server.port,
    )
    host.stats.network.idc = "idc-a"
    conductor = Conductor(
        host, storage, client,
        piece_fetcher=HTTPPieceFetcher(client.resolve_host, tenant=tenant),
        source_fetcher=None,
        native_fetch=native_fetch,
        piece_parallelism=piece_parallelism,
    )
    r = conductor.download(
        plane["url"], piece_size=PIECE,
        content_length=plane["content_length"],
    )
    return storage, r


RANGE_SHAPES = [
    "bytes=0-{last}",            # whole object
    "bytes=0-99",                # head
    "bytes={cross_lo}-{cross_hi}",  # straddles a piece boundary
    "bytes={tail}-",             # open end
    "bytes=-100",                # suffix
    "bytes={mid}-{mid}",         # single byte
]


def _range_cases(total):
    return [
        s.format(
            last=total - 1,
            cross_lo=PIECE - 50,
            cross_hi=PIECE + 49,
            tail=total - 100,
            mid=2 * PIECE + 7,
        )
        for s in RANGE_SHAPES
    ]


def _range_get(port, task, rng_header):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/tasks/{task}",
        headers={"Range": rng_header},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.read()


class TestFallbackMatrixByteIdentity:
    """native-both vs native-server-only vs pure-Python over the SAME
    seeded swarm: identical task bytes, identical pieces, identical
    Range bodies — with the native library present AND force-absent."""

    @pytest.mark.parametrize("lib_present", [True, False])
    def test_arms_byte_identical(self, plane, tmp_path, monkeypatch,
                                 lib_present):
        if not lib_present:
            monkeypatch.setattr(native, "available", lambda: False)
        elif not native.available():
            pytest.skip("native engine unavailable")
        blob = b"".join(plane["pieces"])
        arms = {
            "nativeboth": dict(native_fetch=True, prefer_native=True),
            "nativeserver": dict(native_fetch=False, prefer_native=True),
            "python": dict(native_fetch=False, prefer_native=False),
        }
        stores = {}
        try:
            for arm, kw in arms.items():
                storage, r = _child_download(
                    plane, tmp_path / f"{arm}-{lib_present}",
                    f"nf-{arm}-{int(lib_present)}", **kw,
                )
                assert r.ok and r.pieces == N_PIECES, (arm, r)
                stores[arm] = (storage, r.task_id)
                # Whole task AND every piece, against the origin bytes.
                assert storage.read_task_bytes(r.task_id) == blob, arm
                for n, want in enumerate(plane["pieces"]):
                    assert storage.read_piece(r.task_id, n) == want, (arm, n)

            # Every Range shape, served straight off each arm's store
            # through the piece transport, must agree byte-for-byte.
            servers = {
                arm: PieceHTTPServer(UploadManager(st))
                for arm, (st, _) in stores.items()
            }
            try:
                for srv in servers.values():
                    srv.serve()
                for case in _range_cases(len(blob)):
                    bodies = {}
                    for arm, srv in servers.items():
                        code, body = _range_get(
                            srv.port, stores[arm][1], case
                        )
                        assert code == 206, (arm, case)
                        bodies[arm] = body
                    assert len(set(bodies.values())) == 1, (case, bodies)
            finally:
                for srv in servers.values():
                    srv.stop()
        finally:
            pass  # plane cleanup owns the child stores/servers


class _CorruptHandler(BaseHTTPRequestHandler):
    """A parent that advertises every piece but serves WRONG-LENGTH
    bodies — valid HTTP framing, corrupt payload."""

    protocol_version = "HTTP/1.1"
    n_pieces = N_PIECES

    def do_GET(self):
        if "/pieces/" in self.path:
            body = b"\x5a" * (PIECE // 2)  # half-length garbage
        elif self.path.rstrip("/").endswith("/pieces"):
            body = b"\x01" * self.n_pieces  # "I hold everything"
        else:
            body = b""
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: D102 — keep pytest output clean
        pass


class TestCorruptBodyRefusal:
    """A body that fails the expected-length check is refused by BOTH
    arms: nothing commits, the download does not complete corrupt."""

    @pytest.mark.parametrize("native_fetch", [True, False])
    def test_same_refusal_both_arms(self, plane, tmp_path, native_fetch):
        if native_fetch and not native.available():
            pytest.skip("native engine unavailable")
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _CorruptHandler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        url = "https://origin/native-fetch-corrupt"
        pieces = _origin_pieces(13)
        tmp = plane["tmp"]
        try:
            # An honest conductor seeds the task on the scheduler, but
            # its ANNOUNCED download port is the corrupt server: every
            # child fetch lands there.
            chost = Host(
                id=f"nf-corrupt-parent-{int(native_fetch)}",
                hostname=f"nf-corrupt-parent-{int(native_fetch)}",
                ip="127.0.0.1", port=8002,
                download_port=httpd.server_address[1],
            )
            chost.stats.network.idc = "idc-a"
            cclient = RemoteScheduler(plane["scheduler"].url, timeout=5.0)
            seeder_store = DaemonStorage(
                str(tmp / f"corrupt-seed-{int(native_fetch)}"),
                prefer_native=False,
            )
            seeder = Conductor(
                chost, seeder_store, cclient,
                piece_fetcher=HTTPPieceFetcher(cclient.resolve_host),
                source_fetcher=_Origin(pieces),
            )
            warm = seeder.download(
                url, piece_size=PIECE, content_length=N_PIECES * PIECE
            )
            assert warm.ok

            storage = DaemonStorage(
                str(tmp_path / "victim"), prefer_native=native_fetch
            )
            client = RemoteScheduler(plane["scheduler"].url, timeout=5.0)
            host = Host(
                id=f"nf-corrupt-child-{int(native_fetch)}",
                hostname=f"nf-corrupt-child-{int(native_fetch)}",
                ip="127.0.0.1", port=8002, download_port=1,
            )
            host.stats.network.idc = "idc-a"
            conductor = Conductor(
                host, storage, client,
                piece_fetcher=HTTPPieceFetcher(client.resolve_host),
                source_fetcher=None,
                native_fetch=native_fetch,
                piece_wait_timeout_s=2.0,
            )
            r = conductor.download(
                url, piece_size=PIECE, content_length=N_PIECES * PIECE
            )
            # Identical refusal: no corrupt byte ever commits.
            assert not r.ok
            assert storage.held_pieces(r.task_id) == 0
            storage.close()
        finally:
            httpd.shutdown()
            httpd.server_close()


class _FetcherSpy:
    """Wraps native.NativePieceFetcher, counting constructions — the
    witness that a gate routed the download to the Python arm."""

    def __init__(self):
        self.constructed = 0
        self._real = native.NativePieceFetcher

    def __call__(self, *a, **kw):
        self.constructed += 1
        return self._real(*a, **kw)


@pytest.fixture()
def fetcher_spy(monkeypatch):
    if not native.available():
        pytest.skip("native engine unavailable")
    spy = _FetcherSpy()
    monkeypatch.setattr(native, "NativePieceFetcher", spy)
    return spy


class TestDispatchGates:
    def test_native_path_used_when_ungated(self, plane, tmp_path,
                                           fetcher_spy):
        storage, r = _child_download(
            plane, tmp_path / "s", "nf-gate-on", native_fetch=True
        )
        assert r.ok and fetcher_spy.constructed == 1

    def test_knob_off_routes_python(self, plane, tmp_path, fetcher_spy):
        storage, r = _child_download(
            plane, tmp_path / "s", "nf-gate-knob", native_fetch=False
        )
        assert r.ok and fetcher_spy.constructed == 0

    def test_python_store_routes_python(self, plane, tmp_path, fetcher_spy):
        storage, r = _child_download(
            plane, tmp_path / "s", "nf-gate-pystore",
            native_fetch=True, prefer_native=False,
        )
        assert r.ok and fetcher_spy.constructed == 0

    def test_tls_endpoint_is_not_native_dialable(self):
        import ssl

        ctx = ssl.create_default_context()
        fetcher = HTTPPieceFetcher(
            lambda hid: ("127.0.0.1", 1), ssl_context=ctx
        )
        assert fetcher.native_endpoint("h") is None
        plain = HTTPPieceFetcher(lambda hid: ("127.0.0.1", 7))
        assert plain.native_endpoint("h") == ("127.0.0.1", 7)

    def test_piece_fault_scenario_routes_python_and_bites(
        self, plane, tmp_path, fetcher_spy
    ):
        inj = FaultInjector(
            [FaultSpec(site="piece.fetch", kind="delay", every=3,
                       delay_s=0.01)]
        )
        with installed(inj):
            storage, r = _child_download(
                plane, tmp_path / "s", "nf-gate-fault", native_fetch=True
            )
        assert r.ok and fetcher_spy.constructed == 0
        # The scenario actually bit on the Python arm — the gate did not
        # just bypass the native path, it preserved fault semantics.
        assert any(i.site == "piece.fetch" for i in inj.history)

    def test_dispatch_seam_raise_routes_python(self, plane, tmp_path,
                                               fetcher_spy):
        inj = FaultInjector(
            [FaultSpec(site="daemon.piece.native_fetch", kind="dferror",
                       every=1)]
        )
        with installed(inj):
            storage, r = _child_download(
                plane, tmp_path / "s", "nf-gate-seam", native_fetch=True
            )
        assert r.ok and fetcher_spy.constructed == 0
        assert any(
            i.site == "daemon.piece.native_fetch" for i in inj.history
        )
        assert storage.read_task_bytes(r.task_id) == b"".join(
            plane["pieces"]
        )

    def test_tee_consumer_routes_python(self, plane, tmp_path, fetcher_spy):
        storage = DaemonStorage(str(tmp_path / "s"), prefer_native=True)
        client = RemoteScheduler(plane["scheduler"].url, timeout=5.0)
        host = Host(
            id="nf-gate-tee", hostname="nf-gate-tee", ip="127.0.0.1",
            port=8002, download_port=1,
        )
        host.stats.network.idc = "idc-a"
        conductor = Conductor(
            host, storage, client,
            piece_fetcher=HTTPPieceFetcher(client.resolve_host),
            source_fetcher=None,
            native_fetch=True,
        )
        handle = conductor.open_stream(
            plane["url"], piece_size=PIECE,
            content_length=plane["content_length"],
        )
        got = b"".join(handle.chunks())
        assert got == b"".join(plane["pieces"])
        assert fetcher_spy.constructed == 0
        storage.close()


@pytest.mark.skipif(
    not native.available(), reason="native engine unavailable"
)
class TestSigkillMidNativeFetch:
    def test_kill_between_commit_and_bookkeeping_resumes(self, tmp_path):
        """The crash seam lands a SIGKILL on the first drained native
        completion — after its C++ commit, before any Python
        bookkeeping, with engine workers still in flight.  The durable
        plane must come back partial-but-clean: a fresh conductor over
        the same store completes and digest-checks."""
        n_pieces = 12
        content_length = n_pieces * PIECE
        url = "https://origin/native-kill-blob"
        pieces = _origin_pieces(5, n=n_pieces)

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            Storage(str(tmp_path / "records"), buffer_size=8),
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerHTTPServer(service)
        server.serve()
        pstore = DaemonStorage(str(tmp_path / "parent"), prefer_native=False)
        piece_server = PieceHTTPServer(UploadManager(pstore))
        piece_server.serve()
        phost = Host(
            id="nk-parent", hostname="nk-parent", ip="127.0.0.1",
            port=8002, download_port=piece_server.port,
        )
        phost.stats.network.idc = "idc-a"
        pclient = RemoteScheduler(server.url, timeout=5.0)
        parent = Conductor(
            phost, pstore, pclient,
            piece_fetcher=HTTPPieceFetcher(pclient.resolve_host),
            source_fetcher=_Origin(pieces),
        )
        warm = parent.download(
            url, piece_size=PIECE, content_length=content_length
        )
        assert warm.ok and warm.pieces == n_pieces

        child_store = str(tmp_path / "childstore")
        scenario = {
            "seed": 0,
            "faults": [
                # Site index 0 is the dispatch fire; index 1 is the
                # FIRST drained completion record.
                FaultSpec(
                    site="daemon.piece.native_fetch", kind="crash", at=(1,)
                ).to_dict(),
            ],
        }
        try:
            proc = subprocess.Popen(
                [
                    sys.executable,
                    str(REPO / "tests" / "_native_kill_child.py"),
                    server.url, child_store, url,
                    str(content_length), str(PIECE),
                ],
                env={
                    **os.environ,
                    "DF_FAULTINJECT": json.dumps(scenario),
                    "JAX_PLATFORMS": "cpu",
                    "DF_LOCK_WITNESS": "0",
                },
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                cwd=str(REPO),
            )
            try:
                out, err = proc.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, err = proc.communicate()
                pytest.fail(f"child hung: {out!r} {err!r}")
            assert proc.returncode == -signal.SIGKILL, (
                proc.returncode, out, err,
            )
            assert b'"ok"' not in out, "child finished before the kill"

            storage2 = DaemonStorage(child_store, prefer_native=True)
            loaded = storage2.reload_persistent_tasks(
                storage2.scan_disk_tasks()
            )
            assert loaded, "no partial task survived the kill"
            held_before = storage2.held_pieces(loaded[0])
            assert 0 < held_before < n_pieces, (
                f"kill landed outside the native window "
                f"({held_before} pieces)"
            )
            client2 = RemoteScheduler(server.url, timeout=5.0)
            chost = Host(
                id="nk-child-2", hostname="nk-child-2",
                ip="127.0.0.1", port=8002, download_port=1,
            )
            chost.stats.network.idc = "idc-a"
            resumer = Conductor(
                chost, storage2, client2,
                piece_fetcher=HTTPPieceFetcher(
                    client2.resolve_host, timeout=5.0
                ),
                source_fetcher=None,
            )
            r = resumer.download(
                url, piece_size=PIECE, content_length=content_length
            )
            assert r.ok
            assert storage2.read_task_bytes(r.task_id) == b"".join(pieces)
            storage2.close()
        finally:
            piece_server.stop()
            server.stop()
            assert native.leaked_servers() == (0, 0)


class TestBenchNativeSmoke:
    def test_smoke_schema_gates_native_both(self, capsys):
        if not native.available():
            pytest.skip("native engine unavailable")
        from tools import bench_download

        rc = bench_download.main(["--smoke", "--engine", "native-both"])
        line = capsys.readouterr().out.strip().splitlines()[-1]
        out = json.loads(line)
        assert rc == 0 and out["ok"], out
        for arm in ("nativeboth_single", "nativeboth_saturate",
                    "pipelined_saturate"):
            assert arm in out["arms"], arm
            for k in bench_download.ARM_KEYS:
                assert k in out["arms"][arm], (arm, k)
        nat = out["native"]
        assert nat["enabled"] is True
        assert nat["leaked_servers"] == [0, 0]
        assert nat["speedup_native_single"] is not None
        assert out["serve"]["batched_pieces"] > 0
        # Per-core headline present on every arm.
        assert out["arms"]["pipelined_single"]["MBps_per_core"] > 0
