"""Scheduler core tests: resource FSMs, DAG edges, evaluators, scheduling.

Models the reference's in-process swarm tests
(scheduler/scheduling/scheduling_test.go builds multi-peer DAGs and
asserts parent ranking).
"""

import pytest

from dragonfly2_tpu.scheduler import (
    Evaluator,
    MLEvaluator,
    NetworkTopology,
    Probe,
    ProbeAgent,
    Resource,
    ScheduleResultKind,
    Scheduling,
    SchedulingConfig,
    new_evaluator,
)
from dragonfly2_tpu.scheduler.evaluator import (
    NetworkTopologyEvaluator,
    host_type_score,
    idc_affinity_score,
    location_affinity_score,
)
from dragonfly2_tpu.scheduler.resource import (
    PEER_BACK_TO_SOURCE,
    PEER_RUNNING,
    PEER_SUCCEEDED,
    Host,
    Peer,
    Task,
)
from dragonfly2_tpu.utils.fsm import InvalidEventError
from dragonfly2_tpu.utils.types import HostType, SizeScope


def make_host(i, type=HostType.NORMAL, idc="idc-a", location="r1|z1|rk1", upload_limit=50):
    h = Host(
        id=f"host-{i}",
        hostname=f"host-{i}",
        ip=f"10.0.0.{i}",
        type=type,
        concurrent_upload_limit=upload_limit,
    )
    h.stats.network.idc = idc
    h.stats.network.location = location
    return h


def make_task(tid="task-0", pieces=10, length=40 << 20):
    t = Task(tid, "https://example.com/blob")
    t.content_length = length
    t.total_piece_count = pieces
    return t


def make_peer(i, task, host):
    p = Peer(f"peer-{i}", task, host)
    task.store_peer(p)
    host.store_peer(p)
    return p


def running_parent(i, task, host, finished=5):
    """A peer in Running state that has back-to-source (can serve pieces)."""
    p = make_peer(i, task, host)
    p.fsm.event("RegisterNormal")
    p.fsm.event("DownloadBackToSource")
    for n in range(finished):
        p.finish_piece(n, 10_000_000)
    return p


class TestPeerFSM:
    def test_normal_lifecycle(self):
        t, h = make_task(), make_host(1)
        p = make_peer(1, t, h)
        p.fsm.event("RegisterNormal")
        p.fsm.event("Download")
        assert p.fsm.current == PEER_RUNNING
        p.fsm.event("DownloadSucceeded")
        assert p.fsm.current == PEER_SUCCEEDED

    def test_illegal_transition_raises(self):
        t, h = make_task(), make_host(1)
        p = make_peer(1, t, h)
        with pytest.raises(InvalidEventError):
            p.fsm.event("Download")  # must register first

    def test_back_to_source_from_running(self):
        t, h = make_task(), make_host(1)
        p = make_peer(1, t, h)
        p.fsm.event("RegisterNormal")
        p.fsm.event("Download")
        p.fsm.event("DownloadBackToSource")
        assert p.fsm.current == PEER_BACK_TO_SOURCE

    def test_task_redownload_from_terminal(self):
        t = make_task()
        t.fsm.event("Download")
        t.fsm.event("DownloadSucceeded")
        t.fsm.event("Download")  # re-download allowed (task.go:199)
        assert t.fsm.current == "Running"


class TestSizeScope:
    def test_scopes(self):
        t = make_task(pieces=10, length=40 << 20)
        assert t.size_scope() is SizeScope.NORMAL
        t = make_task(pieces=1, length=1 << 20)
        assert t.size_scope() is SizeScope.SMALL
        t = make_task(pieces=1, length=100)
        assert t.size_scope() is SizeScope.TINY
        t = make_task(pieces=0, length=0)
        assert t.size_scope() is SizeScope.EMPTY
        t = Task("t", "u")
        assert t.size_scope() is SizeScope.UNKNOWN


class TestTaskDAG:
    def test_add_edge_consumes_upload_slot(self):
        t = make_task()
        h1, h2 = make_host(1, upload_limit=1), make_host(2)
        p1, p2 = make_peer(1, t, h1), make_peer(2, t, h2)
        assert t.add_peer_edge(p1, p2)
        assert h1.free_upload_count() == 0
        assert t.peer_in_degree(p2.id) == 1

    def test_edge_rejected_when_no_upload_slot(self):
        t = make_task()
        h1 = make_host(1, upload_limit=1)
        h2, h3 = make_host(2), make_host(3)
        p1, p2, p3 = make_peer(1, t, h1), make_peer(2, t, h2), make_peer(3, t, h3)
        assert t.add_peer_edge(p1, p2)
        assert not t.add_peer_edge(p1, p3)  # slot exhausted
        assert t.peer_in_degree(p3.id) == 0

    def test_cycle_rejected(self):
        t = make_task()
        h1, h2 = make_host(1), make_host(2)
        p1, p2 = make_peer(1, t, h1), make_peer(2, t, h2)
        assert t.add_peer_edge(p1, p2)
        assert not t.can_add_peer_edge(p2.id, p1.id)

    def test_delete_in_edges_releases_slots(self):
        t = make_task()
        h1, h2 = make_host(1, upload_limit=2), make_host(2)
        p1, p2 = make_peer(1, t, h1), make_peer(2, t, h2)
        t.add_peer_edge(p1, p2)
        assert h1.free_upload_count() == 1
        t.delete_peer_in_edges(p2.id)
        assert h1.free_upload_count() == 2
        assert h1.upload_count == 1


class TestEvaluator:
    def test_affinity_scores(self):
        assert idc_affinity_score("idc-a", "idc-a") == 1.0
        assert idc_affinity_score("idc-a", "idc-b") == 0.0
        assert idc_affinity_score("", "idc-b") == 0.0
        assert location_affinity_score("a|b|c", "a|b|c") == 1.0
        assert location_affinity_score("a|b|c", "a|b|x") == 2 / 5
        assert location_affinity_score("a|b", "x|b") == 0.0

    def test_seed_peer_preferred_while_fetching(self):
        t = make_task()
        seed = make_peer(1, t, make_host(1, type=HostType.SUPER_SEED))
        seed.fsm.event("RegisterNormal")
        seed.fsm.event("Download")
        assert host_type_score(seed) == 1.0
        seed2 = make_peer(2, t, make_host(2, type=HostType.SUPER_SEED))
        seed2.fsm.event("RegisterNormal")
        seed2.fsm.event("Download")
        seed2.fsm.event("DownloadSucceeded")
        assert host_type_score(seed2) == 0.0  # finished seed scores min
        normal = make_peer(3, t, make_host(3))
        assert host_type_score(normal) == 0.5

    def test_ranking_prefers_same_idc(self):
        t = make_task()
        child = make_peer(0, t, make_host(0, idc="idc-a", location="r1|z1|rk1"))
        same = running_parent(1, t, make_host(1, idc="idc-a", location="r1|z1|rk1"))
        far = running_parent(2, t, make_host(2, idc="idc-b", location="r2|z9|rk9"))
        ev = Evaluator()
        ranked = ev.evaluate_parents([far, same], child, t.total_piece_count)
        assert ranked[0] is same

    def test_bad_node_by_state_and_cost(self):
        t = make_task()
        p = make_peer(1, t, make_host(1))
        ev = Evaluator()
        assert ev.is_bad_node(p)  # Pending
        p.fsm.event("RegisterNormal")
        p.fsm.event("Download")
        assert not ev.is_bad_node(p)  # no cost samples yet
        p.append_piece_cost(100)
        p.append_piece_cost(100)
        assert not ev.is_bad_node(p)
        p.append_piece_cost(100 * 25)  # > 20x mean
        assert ev.is_bad_node(p)

    def test_bad_node_three_sigma(self):
        t = make_task()
        p = make_peer(1, t, make_host(1))
        p.fsm.event("RegisterNormal")
        p.fsm.event("Download")
        for _ in range(35):
            p.append_piece_cost(100)
        assert not ev_is_bad(p)
        p.append_piece_cost(101)  # zero stdev → anything above mean is bad
        assert ev_is_bad(p)


def ev_is_bad(p):
    return Evaluator().is_bad_node(p)


class TestNetworkTopologyEvaluator:
    def test_rtt_shifts_ranking(self):
        nt = NetworkTopology()
        t = make_task()
        child = make_peer(0, t, make_host(0, idc="idc-x"))
        a = running_parent(1, t, make_host(1, idc="idc-x"))
        b = running_parent(2, t, make_host(2, idc="idc-x"))
        # a has terrible RTT to child, b has great RTT.
        nt.enqueue_probe(a.host.id, child.host.id, Probe(child.host.id, 900_000_000))
        nt.enqueue_probe(b.host.id, child.host.id, Probe(child.host.id, 1_000_000))
        ev = new_evaluator("nt", networktopology=nt)
        assert isinstance(ev, NetworkTopologyEvaluator)
        ranked = ev.evaluate_parents([a, b], child, t.total_piece_count)
        assert ranked[0] is b


class TestScheduling:
    def _swarm(self, n_parents=6, upload_limit=50):
        t = make_task()
        child_host = make_host(0, idc="idc-a")
        child = make_peer(0, t, child_host)
        child.fsm.event("RegisterNormal")
        parents = [
            running_parent(i + 1, t, make_host(i + 1, upload_limit=upload_limit))
            for i in range(n_parents)
        ]
        return t, child, parents

    def test_schedule_attaches_parents(self):
        t, child, parents = self._swarm()
        s = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        res = s.schedule_candidate_parents(child)
        assert res.kind is ScheduleResultKind.PARENTS
        assert 1 <= len(res.parents) <= 4
        assert t.peer_in_degree(child.id) == len(res.parents)

    def test_same_host_filtered(self):
        t = make_task()
        shared = make_host(9)
        child = make_peer(0, t, shared)
        child.fsm.event("RegisterNormal")
        running_parent(1, t, shared)
        s = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0, retry_back_to_source_limit=1))
        res = s.schedule_candidate_parents(child)
        assert res.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE

    def test_back_to_source_when_no_parents(self):
        t = make_task()
        child = make_peer(0, t, make_host(0))
        child.fsm.event("RegisterNormal")
        s = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        res = s.schedule_candidate_parents(child)
        assert res.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE
        assert res.retries == 4

    def test_hard_fail_when_no_back_to_source_budget(self):
        t = make_task()
        t.back_to_source_limit = 0
        t.back_to_source_peers.add("someone")  # budget consumed
        child = make_peer(0, t, make_host(0))
        child.fsm.event("RegisterNormal")
        s = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        res = s.schedule_candidate_parents(child)
        assert res.kind is ScheduleResultKind.FAILED
        assert res.retries == 5

    def test_need_back_to_source_flag_short_circuits(self):
        t, child, _ = self._swarm()
        child.need_back_to_source = True
        s = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        res = s.schedule_candidate_parents(child)
        assert res.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE

    def test_blocklist_respected(self):
        t, child, parents = self._swarm(n_parents=2)
        s = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        res = s.schedule_candidate_parents(child, blocklist={p.id for p in parents})
        assert res.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE

    def test_find_success_parent(self):
        t, child, parents = self._swarm(n_parents=3)
        parents[1].fsm.event("DownloadSucceeded")
        s = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        got = s.find_success_parent(child)
        assert got is parents[1]

    def test_schedule_once_keeps_assignment_when_attach_races_lost(self):
        """ADVICE r2: losing every upload-slot race must leave the child's
        REAL edges intact (detach-first left it edgeless and invisible to
        reschedule_stalled)."""
        t, child, parents = self._swarm(n_parents=6)
        s = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        first = s.schedule_once(child)
        assert first.kind is ScheduleResultKind.PARENTS
        before = {p.id for p in t.load_parents(child.id)}
        assert before
        real = t.add_peer_edge
        t.add_peer_edge = lambda parent, peer: False  # every race lost
        try:
            res = s.schedule_once(child)
        finally:
            t.add_peer_edge = real
        assert res.kind is ScheduleResultKind.FAILED
        assert {p.id for p in t.load_parents(child.id)} == before

    def test_schedule_once_swaps_edges_attach_first(self):
        """A successful single-shot reschedule replaces the edge set: new
        parents attach, old ones detach and get their upload slots back."""
        t, child, parents = self._swarm(n_parents=6, upload_limit=2)
        s = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        first = s.schedule_once(child)
        old = {p.id for p in t.load_parents(child.id)}
        res = s.schedule_once(child)
        assert res.kind is ScheduleResultKind.PARENTS
        now = {p.id for p in t.load_parents(child.id)}
        assert now == {p.id for p in res.parents}
        assert now.isdisjoint(old)
        for p in parents:
            if p.id in old:  # released slot: back to the full limit
                assert p.host.free_upload_count() == 2


class TestMLEvaluatorFallback:
    def test_no_model_falls_back_to_rules(self):
        ev = MLEvaluator()
        assert not ev.has_model
        t = make_task()
        child = make_peer(0, t, make_host(0, idc="idc-a"))
        same = running_parent(1, t, make_host(1, idc="idc-a"))
        far = running_parent(2, t, make_host(2, idc="idc-b", location="r9|z9|rk9"))
        ranked = ev.evaluate_parents([far, same], child, t.total_piece_count)
        assert ranked[0] is same

    def test_scorer_overrides_rules(self):
        class Inverse:
            def score(self, feats, **buckets):
                import numpy as np

                # Score by parent cpu feature ascending → deterministic control.
                return -feats[:, 12]

        t = make_task()
        child = make_peer(0, t, make_host(0))
        a = running_parent(1, t, make_host(1))
        b = running_parent(2, t, make_host(2))
        a.host.stats.cpu.percent = 90.0
        b.host.stats.cpu.percent = 10.0
        ev = MLEvaluator(Inverse())
        ranked = ev.evaluate_parents([a, b], child, t.total_piece_count)
        assert ranked[0] is b


class TestResourceGC:
    def test_peer_gc_reaps_left_peers(self):
        r = Resource()
        t = make_task()
        h = make_host(1)
        r.store_task(t)
        r.store_host(h)
        p = make_peer(1, t, h)
        r.store_peer(p)
        p.fsm.event("Leave")
        reaped = r.peer_manager.run_gc()
        assert reaped == 1
        assert t.peer_count() == 0
        assert h.peer_count() == 0


class TestNetworkTopologyStore:
    def test_ema_and_queue_cap(self):
        nt = NetworkTopology()
        for i in range(8):  # queue caps at 5
            nt.enqueue_probe("s", "d", Probe("d", 100 + i))
        assert len(nt.probes("s", "d")) == 5
        # EMA folds left-to-right with 0.1 on the accumulator.
        rtts = [103, 104, 105, 106, 107]
        avg = float(rtts[0])
        for r in rtts[1:]:
            avg = avg * 0.1 + r * 0.9
        assert nt.average_rtt("s", "d") == int(avg)
        assert nt.probed_count("d") == 8

    def test_find_probed_hosts_least_probed(self):
        from dragonfly2_tpu.scheduler.resource import HostManager

        hm = HostManager()
        hosts = [make_host(i) for i in range(10)]
        for h in hosts:
            hm.store(h.id, h)
        nt = NetworkTopology(hm)
        # Load up probe counts on hosts 0..4 so 5..9 are least-probed.
        for i in range(5):
            nt.enqueue_probe("x", f"host-{i}", Probe(f"host-{i}", 100))
        got = nt.find_probed_hosts("host-0")
        assert len(got) == 5
        got_ids = {h.id for h in got}
        assert got_ids == {f"host-{i}" for i in range(5, 10)}

    def test_probe_agent_and_snapshot(self):
        from dragonfly2_tpu.scheduler.resource import HostManager

        hm = HostManager()
        hosts = [make_host(i) for i in range(6)]
        for h in hosts:
            hm.store(h.id, h)
        nt = NetworkTopology(hm)
        agent = ProbeAgent(hosts[0], nt, ping=lambda h: 5_000_000)
        assert agent.sync_probes() == 5
        records = nt.snapshot()
        assert len(records) == 1
        assert records[0].host.id == hosts[0].id
        assert len(records[0].dest_hosts) == 5
        assert all(d.probes.average_rtt == 5_000_000 for d in records[0].dest_hosts)

    def test_edge_arrays_export(self):
        nt = NetworkTopology()
        nt.enqueue_probe("a", "b", Probe("b", 10))
        nt.enqueue_probe("b", "c", Probe("c", 20))
        ids, src, dst, rtt = nt.to_edge_arrays()
        assert len(ids) == 3
        assert src.shape == dst.shape == rtt.shape == (2,)

    def test_delete_host(self):
        nt = NetworkTopology()
        nt.enqueue_probe("a", "b", Probe("b", 10))
        nt.enqueue_probe("c", "a", Probe("a", 10))
        nt.enqueue_probe("c", "d", Probe("d", 10))
        nt.delete_host("a")
        assert nt.edge_count() == 1


class TestDownloadRecordParents:
    """Regression for d5940d0: report_peer_finished released the parent
    edges BEFORE building the Download record, so every record had zero
    parents and the training loop starved (VERDICT round 1, weak #1)."""

    def _service(self, tmp_path):
        from dragonfly2_tpu.records.storage import Storage
        from dragonfly2_tpu.scheduler.service import SchedulerService

        resource = Resource()
        return SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            Storage(str(tmp_path / "records"), buffer_size=1),
        )

    def test_record_keeps_parents_after_slot_release(self, tmp_path):
        service = self._service(tmp_path)
        url = "https://origin/blob"
        reg0 = service.register_peer(host=make_host(0), url=url)
        service.set_task_info(
            reg0.peer, content_length=40 << 20, total_piece_count=10, piece_size=4 << 20
        )
        for n in range(10):
            service.report_piece_finished(
                reg0.peer, n, length=4 << 20, cost_ns=10_000_000
            )
        service.report_peer_finished(reg0.peer)

        reg1 = service.register_peer(host=make_host(1), url=url)
        assert reg1.schedule.kind is ScheduleResultKind.PARENTS
        assert reg0.peer.id in [p.id for p in reg1.schedule.parents]
        for n in range(10):
            service.report_piece_finished(
                reg1.peer, n, parent_id=reg0.peer.id, length=4 << 20, cost_ns=5_000_000
            )
        service.report_peer_finished(reg1.peer)

        # Slot released: the DAG edge is gone...
        assert not reg1.peer.task.load_parents(reg1.peer.id)
        # ...but the record still carries parent attribution.
        rec = next(
            d for d in service.storage.list_download() if d.id == reg1.peer.id
        )
        assert reg0.peer.id in [p.id for p in rec.parents]

    def test_failed_record_keeps_parents(self, tmp_path):
        service = self._service(tmp_path)
        url = "https://origin/blob2"
        reg0 = service.register_peer(host=make_host(0), url=url)
        service.set_task_info(
            reg0.peer, content_length=40 << 20, total_piece_count=10, piece_size=4 << 20
        )
        for n in range(10):
            service.report_piece_finished(
                reg0.peer, n, length=4 << 20, cost_ns=10_000_000
            )
        service.report_peer_finished(reg0.peer)

        reg1 = service.register_peer(host=make_host(1), url=url)
        assert reg1.schedule.kind is ScheduleResultKind.PARENTS
        service.report_peer_failed(reg1.peer)
        rec = next(
            d for d in service.storage.list_download() if d.id == reg1.peer.id
        )
        assert rec.state == "Failed"
        assert reg0.peer.id in [p.id for p in rec.parents]


class TestServerPush:
    """Push hub + service triggers (scheduler/push.py): parent death and
    stalls push fresh schedules to subscribed children."""

    def _service(self, tmp_path=None, cooldown=0.0):
        from dragonfly2_tpu.scheduler.push import PeerStreamHub
        from dragonfly2_tpu.scheduler.service import SchedulerService

        hub = PeerStreamHub(push_cooldown_s=cooldown)
        # One parent per child: the OTHER seed stays a fresh candidate, so
        # single-shot push rescheduling has somewhere to move the child.
        service = SchedulerService(
            Resource(),
            Scheduling(
                Evaluator(),
                SchedulingConfig(retry_interval=0, candidate_parent_limit=1),
            ),
            hub=hub,
        )
        return service, hub

    def _seed_and_child(self, service):
        url = "https://origin/push-blob"
        regs = []
        for i in range(2):
            reg = service.register_peer(host=make_host(i), url=url)
            service.set_task_info(reg.peer, content_length=40 << 20,
                                  total_piece_count=10, piece_size=4 << 20)
            for n in range(10):
                service.report_piece_finished(reg.peer, n, length=4 << 20,
                                              cost_ns=10_000_000)
            service.report_peer_finished(reg.peer)
            regs.append(reg)
        child = service.register_peer(host=make_host(5), url=url)
        assert child.schedule.kind is ScheduleResultKind.PARENTS
        return regs, child

    def test_parent_failure_pushes_children(self):
        service, hub = self._service()
        regs, child = self._seed_and_child(service)
        got = []
        hub.register(child.peer.id, got.append)
        parent = child.schedule.parents[0]
        service.report_peer_failed(parent)
        assert got, "no push on parent failure"
        res = got[0]
        assert res.kind is ScheduleResultKind.PARENTS
        assert parent.id not in [p.id for p in res.parents]

    def test_leave_peer_pushes_children(self):
        service, hub = self._service()
        regs, child = self._seed_and_child(service)
        got = []
        hub.register(child.peer.id, got.append)
        service.leave_peer(child.schedule.parents[0])
        assert got and got[0].kind is ScheduleResultKind.PARENTS

    def test_stall_sweep_pushes_idle_peers(self):
        service, hub = self._service()
        regs, child = self._seed_and_child(service)
        got = []
        hub.register(child.peer.id, got.append)
        child.peer.updated_at -= 60  # pretend nothing happened for a minute
        pushed = service.reschedule_stalled(max_idle_s=5)
        assert pushed == 1 and got
        # fresh parents exclude the stalled assignment
        old = {p.id for p in child.schedule.parents}
        assert not old & {p.id for p in got[0].parents}
        # a repeated sweep immediately after pushes nothing (clock reset)
        assert service.reschedule_stalled(max_idle_s=5) == 0

    def test_cooldown_damps_push_storm(self):
        service, hub = self._service(cooldown=60.0)
        regs, child = self._seed_and_child(service)
        got = []
        hub.register(child.peer.id, got.append)
        child.peer.updated_at -= 120
        assert service.reschedule_stalled(max_idle_s=5) == 1
        child.peer.updated_at -= 120
        assert service.reschedule_stalled(max_idle_s=5) == 0  # cooldown holds
        assert len(got) == 1

    def test_unsubscribed_children_untouched(self):
        service, hub = self._service()
        regs, child = self._seed_and_child(service)
        before = child.peer.task.load_parents(child.peer.id)
        service.report_peer_failed(child.schedule.parents[0])
        # no hub subscription → assignment not churned by the push path
        after = child.peer.task.load_parents(child.peer.id)
        assert [p.id for p in before] == [p.id for p in after]


class TestTopologyDurabilityAndSharing:
    """VERDICT r2 next-#5: the probe graph survives restarts (disk state)
    and replicates across scheduler replicas via the manager (the Redis
    analog)."""

    def test_save_load_restores_rtt_scores(self, tmp_path):
        nt = NetworkTopology()
        # Edges run PARENT → child (the nt evaluator queries that way).
        for i in range(4):
            nt.enqueue_probe(f"d{i}", "s", Probe("s", 1_000_000 * 30 ** i))
            nt.enqueue_probe(f"d{i}", "s", Probe("s", 1_100_000 * 30 ** i))
        path = str(tmp_path / "topo.json")
        nt.save(path)

        # "Restart": a FRESH store reloads the state byte-for-byte.
        nt2 = NetworkTopology()
        assert nt2.load(path) == 4
        for i in range(4):
            assert nt2.average_rtt(f"d{i}", "s") == nt.average_rtt(f"d{i}", "s")
            assert len(nt2.probes(f"d{i}", "s")) == 2
        assert nt2.probed_count("s") == nt.probed_count("s")
        # The nt evaluator ranks with the reloaded knowledge.
        t = make_task()
        child = make_peer(0, t, make_host(0))
        child.host.id = "s"
        near = running_parent(1, t, make_host(1))
        near.host.id = "d0"  # ~1ms avg
        far = running_parent(2, t, make_host(2))
        far.host.id = "d3"   # ~29s avg (way past the ping budget)
        ev = NetworkTopologyEvaluator(nt2)
        ranked = ev.evaluate_parents([far, near], child, t.total_piece_count)
        assert ranked[0] is near
        # Corrupt/missing state degrades to empty, not a crash.
        assert NetworkTopology().load(str(tmp_path / "ghost.json")) == 0
        (tmp_path / "bad.json").write_text("{not json")
        assert NetworkTopology().load(str(tmp_path / "bad.json")) == 0

    def test_probe_on_replica_a_informs_ranking_on_b(self, tmp_path):
        """Two schedulers, one manager: A's probe shifts B's nt ranking
        after one sync round each."""
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer
        from dragonfly2_tpu.scheduler.topology_sync import TopologySync

        server = ManagerRESTServer(ModelRegistry(), ClusterManager())
        server.serve()
        try:
            nt_a, nt_b = NetworkTopology(), NetworkTopology()
            sync_a = TopologySync(nt_a, server.url, "sched-a",
                                  state_path=str(tmp_path / "a.json"))
            sync_b = TopologySync(nt_b, server.url, "sched-b")
            # Probe lands on A only.
            nt_a.enqueue_probe("parent-near", "child-host", Probe("child-host", 500_000))
            nt_a.enqueue_probe("parent-far", "child-host", Probe("child-host", 900_000_000))
            sync_a.sync_once()          # push A
            adopted = sync_b.sync_once()  # pull into B
            assert adopted == 2
            assert nt_b.average_rtt("parent-near", "child-host") == 500_000

            t = make_task()
            child = make_peer(0, t, make_host(0))
            child.host.id = "child-host"
            near = running_parent(1, t, make_host(1))
            near.host.id = "parent-near"
            far = running_parent(2, t, make_host(2))
            far.host.id = "parent-far"
            ev = NetworkTopologyEvaluator(nt_b)
            ranked = ev.evaluate_parents([far, near], child, t.total_piece_count)
            assert ranked[0] is near, "A's probe did not inform B's ranking"

            # Newest-wins: B later probes the same edge itself; A's stale
            # copy must not clobber it on the next pull.
            nt_b.enqueue_probe("parent-near", "child-host", Probe("child-host", 700_000))
            local = nt_b.average_rtt("parent-near", "child-host")
            sync_b.sync_once()
            assert nt_b.average_rtt("parent-near", "child-host") == local
            # A's disk checkpoint was written by its sync.
            assert NetworkTopology().load(str(tmp_path / "a.json")) == 2
        finally:
            server.stop()
