"""Subprocess body for the mid-native-fetch SIGKILL drill
(tests/test_native_fetch.py).

A wire daemon whose download takes the in-engine fetch path (native
store + plain-HTTP parent, DESIGN.md §28).  The parent test installs a
``crash`` FaultSpec on the ``daemon.piece.native_fetch`` seam
(DF_FAULTINJECT) positioned on a drained completion record, so the
process SIGKILLs itself BETWEEN a C++ piece commit and its Python
bookkeeping — mid-window, with the engine's workers still in flight.
The parent then proves the durable plane is untouched: a fresh
conductor over the same store resumes the download, completes, and the
reassembled bytes digest-check against the origin.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from dragonfly2_tpu.utils import faultinject  # noqa: E402


def main():
    scheduler_url, store_dir, url = sys.argv[1:4]
    content_length, piece_size = int(sys.argv[4]), int(sys.argv[5])
    faultinject.install_from_env()

    from dragonfly2_tpu import native
    from dragonfly2_tpu.daemon import DaemonStorage
    from dragonfly2_tpu.daemon.conductor import Conductor
    from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler
    from dragonfly2_tpu.scheduler.resource import Host

    if not native.available():
        # The drill is native-only; the parent test skips in this case,
        # so reaching here is a harness bug — make it loud.
        print(json.dumps({"ok": False, "error": "native unavailable"}),
              flush=True)
        return 2

    host = Host(
        id="native-kill-child", hostname="native-kill-child", ip="127.0.0.1",
        port=8002, download_port=1,
    )
    host.stats.network.idc = "idc-a"
    client = RemoteScheduler(scheduler_url, timeout=5.0)
    storage = DaemonStorage(store_dir, prefer_native=True)
    assert storage.is_native
    conductor = Conductor(
        host, storage, client,
        piece_fetcher=HTTPPieceFetcher(client.resolve_host, timeout=5.0),
        source_fetcher=None,
        piece_parallelism=1,  # one engine worker: the kill lands early
    )
    print("native-kill-child: ready", flush=True)
    r = conductor.download(
        url, piece_size=piece_size, content_length=content_length
    )
    # Reaching here means the crash fault never fired (drill failure —
    # the parent asserts this line is absent).
    print(json.dumps({"ok": bool(r.ok), "pieces": r.pieces}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
