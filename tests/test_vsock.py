"""AF_VSOCK transport (pkg/rpc/vsock.go parity): address parsing always;
the live listener/dial path when the host supports vsock loopback."""

import pytest

from dragonfly2_tpu.rpc.vsock import (
    VMADDR_CID_ANY,
    VMADDR_CID_LOCAL,
    VsockHTTPConnection,
    VsockService,
    parse_vsock_addr,
    vsock_available,
)


class TestAddressing:
    def test_parse(self):
        assert parse_vsock_addr("vsock://2:65010") == (2, 65010)
        assert parse_vsock_addr("vsock://4294967295:0") == (4294967295, 0)
        assert parse_vsock_addr("vsock://2:100000") == (2, 100000)  # u32 ports
        for bad in ("tcp://1:2", "vsock://", "vsock://x:1", "http://h"):
            with pytest.raises(ValueError):
                parse_vsock_addr(bad)


def _loopback_works() -> bool:
    if not vsock_available():
        return False
    import socket

    try:
        s = socket.socket(socket.AF_VSOCK, socket.SOCK_STREAM)
        s.bind((VMADDR_CID_LOCAL, 0))
        s.close()
        return True
    except OSError:
        return False


class TestLiveVsock:
    def test_http_over_vsock_loopback(self):
        if not _loopback_works():
            pytest.skip("no vsock loopback on this host")
        from http.server import BaseHTTPRequestHandler

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = b'{"ok": true}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        svc = VsockService(H, 0, cid=VMADDR_CID_LOCAL)
        svc.serve()
        try:
            status, body = VsockHTTPConnection(
                VMADDR_CID_LOCAL, svc.port
            ).call("GET", "/healthy")
            assert status == 200 and b'"ok": true' in body
        finally:
            svc.stop()

    def test_bind_any_when_available(self):
        # Guard with a TRIAL BIND: socket() succeeding does not guarantee
        # bind() does (module loaded, no transport registered).
        import socket

        if not vsock_available():
            pytest.skip("AF_VSOCK unavailable")
        try:
            probe = socket.socket(socket.AF_VSOCK, socket.SOCK_STREAM)
            probe.bind((VMADDR_CID_ANY, 0xFFFFFFFF))
            probe.close()
        except OSError:
            pytest.skip("AF_VSOCK bind unsupported on this host")
        from http.server import BaseHTTPRequestHandler

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

        svc = VsockService(H, 0, cid=VMADDR_CID_ANY)
        svc.serve()
        assert svc.port > 0
        svc.stop()
