"""Piece data-plane tests (PR 11, DESIGN.md §22): keep-alive connection
pool lifecycle, sendfile/buffered serve byte-identity (pieces AND byte
ranges), sub-piece Range reads, the commit pipeline, batched piece
reports across transports, hedged straggler fetch, and the
bench_download --smoke schema gate."""

from __future__ import annotations

import json
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
from dragonfly2_tpu.daemon.piece_pipeline import (
    CommitPipeline,
    PieceLatencyTracker,
    PieceReportBatcher,
    hedged_fetch,
)
from dragonfly2_tpu.rpc.piece_transport import (
    HTTPPieceFetcher,
    PieceConnectionPool,
    PieceHTTPServer,
)

PIECE = 64 * 1024


def _make_store(tmp_path, name: str, pieces, piece_size=PIECE, task="t"):
    st = DaemonStorage(str(tmp_path / name), prefer_native=False)
    st.register_task(
        task, piece_size=piece_size,
        content_length=sum(len(p) for p in pieces),
    )
    for i, p in enumerate(pieces):
        st.write_piece(task, i, p)
    return st


def _blocks(n, size=PIECE, seed=7):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, size, dtype=np.uint8).tobytes() for _ in range(n)]


class TestConnectionPool:
    def test_reuse_across_pieces_server_side_evidence(self, tmp_path):
        blocks = _blocks(4)
        st = _make_store(tmp_path, "s", blocks)
        server = PieceHTTPServer(UploadManager(st))
        server.serve()
        try:
            fetcher = HTTPPieceFetcher(lambda hid: ("127.0.0.1", server.port))
            for rep in range(3):
                for i in range(4):
                    assert fetcher.fetch("p", "t", i) == blocks[i]
            # 12 pieces over (at most a couple of) keep-alive connections:
            # the server saw far fewer connections than requests, and the
            # pool recorded the reuses.
            assert server.connections_accepted <= 2
            assert fetcher.pool.reuses >= 10
            assert fetcher.pool.dials == server.connections_accepted
        finally:
            fetcher.close()
            server.stop()

    def test_legacy_unpooled_dials_per_piece(self, tmp_path):
        blocks = _blocks(3)
        st = _make_store(tmp_path, "s", blocks)
        server = PieceHTTPServer(UploadManager(st))
        server.serve()
        try:
            fetcher = HTTPPieceFetcher(
                lambda hid: ("127.0.0.1", server.port), pooled=False
            )
            for i in range(3):
                assert fetcher.fetch("p", "t", i) == blocks[i]
            assert server.connections_accepted == 3  # one per piece
        finally:
            server.stop()

    def test_parent_restart_stale_socket_redials(self, tmp_path):
        blocks = _blocks(2)
        st = _make_store(tmp_path, "s", blocks)
        upload = UploadManager(st)
        server = PieceHTTPServer(upload)
        server.serve()
        port = server.port
        fetcher = HTTPPieceFetcher(lambda hid: ("127.0.0.1", port))
        try:
            assert fetcher.fetch("p", "t", 0) == blocks[0]
            assert fetcher.pool.idle_count("p") == 1
            server.stop()
            # A stopped ThreadingHTTPServer closes its LISTENER but its
            # per-connection threads drain gracefully — kill the pooled
            # socket to model the restart actually severing connections.
            fetcher.pool._idle["p"][0].sock.close()
            # Same port, new server process-analog: the pooled socket is
            # dead; the retry must detect it and re-dial transparently.
            server = PieceHTTPServer(upload, port=port)
            server.serve()
            assert fetcher.fetch("p", "t", 1) == blocks[1]
            assert fetcher.pool.dials >= 2
        finally:
            fetcher.close()
            server.stop()

    def test_parent_reresolve_invalidates_pool(self, tmp_path):
        blocks = _blocks(2)
        st = _make_store(tmp_path, "s", blocks)
        upload = UploadManager(st)
        server_a = PieceHTTPServer(upload)
        server_a.serve()
        server_b = PieceHTTPServer(upload)
        server_b.serve()
        addr = {"port": server_a.port}
        fetcher = HTTPPieceFetcher(lambda hid: ("127.0.0.1", addr["port"]))
        try:
            assert fetcher.fetch("p", "t", 0) == blocks[0]
            assert fetcher.pool.idle_count("p") == 1
            # Parent restarted on a NEW announced port: the resolver now
            # answers differently → the stale-address pool entry drops.
            addr["port"] = server_b.port
            assert fetcher.fetch("p", "t", 1) == blocks[1]
            assert server_b.connections_accepted == 1
            # Only the fresh-address connection is pooled.
            assert fetcher.pool.idle_count("p") == 1
            assert fetcher.pool.dials == 2
        finally:
            fetcher.close()
            server_a.stop()
            server_b.stop()

    def test_breaker_open_drains_pool(self, tmp_path):
        blocks = _blocks(1)
        st = _make_store(tmp_path, "s", blocks)
        server = PieceHTTPServer(UploadManager(st))
        server.serve()
        fetcher = HTTPPieceFetcher(
            lambda hid: ("127.0.0.1", server.port),
            breaker_threshold=2, timeout=1.0,
        )
        try:
            assert fetcher.fetch("p", "t", 0) == blocks[0]
            assert fetcher.pool.idle_count("p") == 1
            server.stop()
            # Sever the surviving keep-alive socket too (stop() only
            # closes the listener): attempt 1 hits the dead socket,
            # attempt 2's dial is refused → threshold-2 breaker opens.
            fetcher.pool._idle["p"][0].sock.close()
            with pytest.raises(Exception):
                fetcher.fetch("p", "t", 0)
            assert fetcher._breaker("p").state == "open"
            # Breaker-open invalidated the parent's pooled sockets.
            assert fetcher.pool.idle_count("p") == 0
        finally:
            fetcher.close()

    def test_pool_bounds_idle_connections(self):
        pool = PieceConnectionPool(max_idle_per_parent=1)

        class _Conn:
            host, port = "127.0.0.1", 1
            closed = 0

            def close(self):
                self.closed += 1

        pool._addr["p"] = ("127.0.0.1", 1)
        c1, c2 = _Conn(), _Conn()
        pool.release("p", c1, reusable=True)
        pool.release("p", c2, reusable=True)  # over the idle bound
        assert pool.idle_count("p") == 1 and c2.closed == 1
        pool.invalidate("p")
        assert pool.idle_count("p") == 0 and c1.closed == 1


from dragonfly2_tpu.security import CertificateAuthority  # noqa: E402

requires_crypto = pytest.mark.skipif(
    CertificateAuthority is None, reason="`cryptography` not installed"
)


class TestMTLSPoolParity:
    @requires_crypto
    def test_pooled_fetch_over_mtls_reuses_connections(self, tmp_path):
        from dragonfly2_tpu.security import (
            CertificateAuthority,
            PeerIdentity,
            client_context,
            server_context,
        )

        ca = CertificateAuthority()
        server_id = PeerIdentity.issue(
            ca, common_name="parent", hostnames=["localhost"],
            ips=["127.0.0.1"],
        )
        client_id = PeerIdentity.issue(ca, common_name="child")
        blocks = _blocks(3)
        st = _make_store(tmp_path, "s", blocks)
        server = PieceHTTPServer(
            UploadManager(st), ssl_context=server_context(server_id)
        )
        server.serve()
        ctx = client_context(client_id)
        ctx.check_hostname = False  # IP connect in test
        fetcher = HTTPPieceFetcher(
            lambda hid: ("127.0.0.1", server.port), ssl_context=ctx
        )
        try:
            for rep in range(2):
                for i in range(3):
                    assert fetcher.fetch("p", "t", i) == blocks[i]
            # TLS handshakes amortize exactly like plain TCP dials.
            assert fetcher.pool.reuses >= 4
            assert server.connections_accepted <= 2
            # The TLS serve path is the buffered one (sendfile would
            # bypass encryption).
            assert server.sendfile_serves == 0
        finally:
            fetcher.close()
            server.stop()


class TestSendfileByteIdentity:
    def _servers(self, tmp_path, blocks, piece_size=PIECE):
        st = _make_store(tmp_path, "s", blocks, piece_size=piece_size)
        upload = UploadManager(st)
        fast = PieceHTTPServer(upload, use_sendfile=True)
        slow = PieceHTTPServer(upload, use_sendfile=False)
        fast.serve()
        slow.serve()
        return st, upload, fast, slow

    def test_piece_bodies_identical(self, tmp_path):
        blocks = _blocks(4)
        st, upload, fast, slow = self._servers(tmp_path, blocks)
        try:
            ff = HTTPPieceFetcher(lambda hid: ("127.0.0.1", fast.port))
            fs = HTTPPieceFetcher(lambda hid: ("127.0.0.1", slow.port))
            for i in range(4):
                a = ff.fetch("p", "t", i)
                b = fs.fetch("p", "t", i)
                assert a == b == blocks[i]
            assert fast.sendfile_serves == 4
            assert slow.sendfile_serves == 0
            # Both paths went through the shared accounting gate.
            assert upload.upload_count == 8
            assert upload.bytes_served == 8 * PIECE
        finally:
            ff.close()
            fs.close()
            fast.stop()
            slow.stop()

    def _range_get(self, port, task, rng_header):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/tasks/{task}",
            headers={"Range": rng_header},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read()

    def test_range_requests_identical_and_correct(self, tmp_path):
        blocks = _blocks(4)
        blob = b"".join(blocks)
        st, upload, fast, slow = self._servers(tmp_path, blocks)
        try:
            total = len(blob)
            cases = [
                f"bytes=0-{total - 1}",            # whole object
                "bytes=0-99",                       # head
                f"bytes={PIECE - 50}-{PIECE + 49}",  # straddles a boundary
                f"bytes={total - 100}-",            # open end
                "bytes=-100",                       # suffix
                f"bytes={2 * PIECE + 7}-{2 * PIECE + 7}",  # single byte
            ]
            for case in cases:
                code_f, body_f = self._range_get(fast.port, "t", case)
                code_s, body_s = self._range_get(slow.port, "t", case)
                assert code_f == code_s == 206
                assert body_f == body_s, case
                # Correctness against the whole object's bytes.
                spec = case[len("bytes="):]
                s, e = spec.split("-", 1)
                if s == "":
                    want = blob[-int(e):]
                elif e == "":
                    want = blob[int(s):]
                else:
                    want = blob[int(s): int(e) + 1]
                assert body_f == want, case
            assert fast.sendfile_serves >= len(cases)
        finally:
            fast.stop()
            slow.stop()

    def test_small_range_reads_only_the_span(self, tmp_path):
        """The serve_range small-read fix: a 100-byte Range request must
        not materialize whole overlapping pieces (feeds the roadmap's
        OCI/ranged-reads item)."""
        blocks = _blocks(2)
        st = _make_store(tmp_path, "s", blocks)
        upload = UploadManager(st)

        calls = {"full": 0, "at": []}
        engine = st.engine
        orig_read, orig_at = engine.read_piece, engine.read_piece_at

        def counting_read(task_id, number, **kw):
            calls["full"] += 1
            return orig_read(task_id, number, **kw)

        def counting_at(task_id, number, offset, max_len):
            calls["at"].append((number, offset, max_len))
            return orig_at(task_id, number, offset, max_len)

        engine.read_piece = counting_read
        engine.read_piece_at = counting_at
        data = upload.serve_range("t", PIECE - 50, 100, PIECE)
        assert data == b"".join(blocks)[PIECE - 50: PIECE + 50]
        assert calls["full"] == 0, "whole-piece read on a 100-byte range"
        assert len(calls["at"]) == 2  # one sub-read per overlapped piece
        assert all(ml <= 100 for _, _, ml in calls["at"])

    def test_partial_task_range_falls_back_and_errors_on_hole(self, tmp_path):
        """range_file_span refuses a span over uncommitted pieces; the
        buffered fallback raises KeyError at the hole (pre-PR parity:
        the HTTP server maps it to 404)."""
        blocks = _blocks(3)
        st = DaemonStorage(str(tmp_path / "p"), prefer_native=False)
        st.register_task("t", piece_size=PIECE, content_length=3 * PIECE)
        st.write_piece("t", 0, blocks[0])
        st.write_piece("t", 2, blocks[2])  # hole at piece 1
        assert st.range_file_span("t", 0, 3 * PIECE) is None
        span = st.range_file_span("t", 10, 100)  # inside committed piece 0
        assert span is not None and span[1] == 10 and span[2] == 100
        assert st.range_file_span("t", PIECE + 10, 100) is None  # the hole
        upload = UploadManager(st)
        assert upload.serve_range("t", 0, PIECE, PIECE) == blocks[0]
        with pytest.raises(KeyError):
            upload.serve_range("t", 0, 3 * PIECE, PIECE)


class TestCommitPipeline:
    def test_commits_in_order_and_flushes_on_close(self):
        committed = []
        p = CommitPipeline(
            lambda n, d, pid, c: committed.append((n, d, pid, c)), depth=2
        )
        for i in range(6):
            assert p.submit(i, bytes([i]), "par", i * 10)
        assert p.close() is None
        assert committed == [
            (i, bytes([i]), "par", i * 10) for i in range(6)
        ]

    def test_error_latches_and_submit_refuses(self):
        def boom(n, d, pid, c):
            raise IOError("disk full")

        p = CommitPipeline(boom, depth=2)
        p.submit(0, b"x", "par", 1)
        deadline = time.monotonic() + 5
        while p.error is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert isinstance(p.error, IOError)
        assert p.submit(1, b"y", "par", 1) is False
        assert isinstance(p.close(), IOError)

    def test_backpressure_bounds_queue(self):
        release = threading.Event()
        inflight = []

        def slow_commit(n, d, pid, c):
            inflight.append(n)
            release.wait(5)

        p = CommitPipeline(slow_commit, depth=1)
        assert p.submit(0, b"a", "p", 1)
        assert p.submit(1, b"b", "p", 1)  # fills the depth-1 queue
        blocked = {"done": False}

        def submit_third():
            p.submit(2, b"c", "p", 1)
            blocked["done"] = True

        t = threading.Thread(target=submit_third, daemon=True)
        t.start()
        time.sleep(0.15)
        assert not blocked["done"], "depth-1 queue did not backpressure"
        release.set()
        t.join(5)
        assert blocked["done"]
        p.close()


class _FakePeer:
    id = "peer-1"


class TestReportBatcher:
    def test_coalesces_into_batches(self):
        calls = []

        class Sched:
            def report_pieces_finished(self, peer, pieces):
                calls.append(list(pieces))

        b = PieceReportBatcher(Sched(), _FakePeer(), linger_s=0.05)
        for i in range(8):
            assert b.submit(i, "par", 100, 5)
        assert b.close() is None
        reported = [p["number"] for batch in calls for p in batch]
        assert sorted(reported) == list(range(8))
        # Coalescing happened: strictly fewer wire calls than reports.
        assert len(calls) < 8
        assert b.reported == 8 and b.flushes == len(calls)

    def test_falls_back_per_piece_without_batch_method(self):
        singles = []

        class Sched:
            def report_piece_finished(self, peer, number, *, parent_id="",
                                      length=0, cost_ns=0):
                singles.append((number, parent_id, length, cost_ns))

        b = PieceReportBatcher(Sched(), _FakePeer(), linger_s=0.0)
        for i in range(3):
            b.submit(i, "par", 7, 9)
        assert b.close() is None
        assert sorted(singles) == [(i, "par", 7, 9) for i in range(3)]

    def test_not_found_batch_degrades_to_singles(self):
        """N-1 wire skew: a pre-batch scheduler answers typed NOT_FOUND
        for the unknown method — the batcher degrades to per-piece
        reports for the rest of the download (DESIGN.md §10d)."""
        from dragonfly2_tpu.rpc.scheduler_client import RPCError
        from dragonfly2_tpu.utils.dferrors import Code

        singles = []
        batch_calls = []

        class OldSched:
            def report_pieces_finished(self, peer, pieces):
                batch_calls.append(len(pieces))
                raise RPCError(
                    "report_pieces_finished: HTTP 404: unknown method",
                    code=int(Code.NOT_FOUND),
                )

            def report_piece_finished(self, peer, number, *, parent_id="",
                                      length=0, cost_ns=0):
                singles.append(number)

        b = PieceReportBatcher(OldSched(), _FakePeer(), linger_s=0.0)
        for i in range(4):
            b.submit(i, "par", 3, 5)
        assert b.close() is None
        assert sorted(singles) == [0, 1, 2, 3]
        # The batch RPC was tried once, then remembered as unsupported.
        assert len(batch_calls) == 1

    def test_flush_error_latches(self):
        class Sched:
            def report_pieces_finished(self, peer, pieces):
                raise ConnectionError("scheduler down")

        b = PieceReportBatcher(Sched(), _FakePeer(), linger_s=0.0)
        b.submit(0, "par", 1, 1)
        deadline = time.monotonic() + 5
        while b.error is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert isinstance(b.error, ConnectionError)
        assert b.submit(1, "par", 1, 1) is False
        assert isinstance(b.close(), ConnectionError)


class TestHedgedFetch:
    def test_no_threshold_means_plain_fetch(self):
        data, winner, hedged = hedged_fetch(
            lambda pid: b"x", lambda d: True, "a", "b", threshold_s=None
        )
        assert (data, winner, hedged) == (b"x", "a", False)

    def test_straggler_loses_to_hedge(self):
        stall = threading.Event()

        def fetch(pid):
            if pid == "slow":
                stall.wait(5)
                return b"late"
            return b"fast"

        data, winner, hedged = hedged_fetch(
            fetch, lambda d: True, "slow", "alt", threshold_s=0.05
        )
        stall.set()
        assert (data, winner, hedged) == (b"fast", "alt", True)

    def test_fast_primary_failure_propagates_not_hedges(self):
        def fetch(pid):
            raise ConnectionError("refused")

        with pytest.raises(ConnectionError):
            hedged_fetch(fetch, lambda d: True, "a", "b", threshold_s=5.0)

    def test_invalid_hedge_body_loses_to_valid_primary(self):
        def fetch(pid):
            if pid == "slow":
                time.sleep(0.15)
                return b"good"
            return b"bad"  # invalid — fails validate

        data, winner, hedged = hedged_fetch(
            fetch, lambda d: d == b"good", "slow", "alt", threshold_s=0.05
        )
        assert (data, winner, hedged) == (b"good", "slow", True)

    def test_tracker_threshold_derivation(self):
        t = PieceLatencyTracker(min_samples=4, floor_s=0.01, multiplier=2.0)
        assert t.threshold_s() is None
        for v in (0.01, 0.01, 0.01, 0.1):
            t.observe(v)
        th = t.threshold_s()
        assert th == pytest.approx(0.2)  # p99 (=0.1) × 2


class TestBatchReportRPC:
    def test_http_wire_batch_advances_scheduler_state(self, tmp_path):
        from dragonfly2_tpu.records.storage import Storage
        from dragonfly2_tpu.rpc import RemoteScheduler
        from dragonfly2_tpu.rpc.scheduler_server import SchedulerHTTPServer
        from dragonfly2_tpu.scheduler import (
            Evaluator,
            NetworkTopology,
            Resource,
            SchedulerService,
            Scheduling,
            SchedulingConfig,
        )
        from dragonfly2_tpu.scheduler.resource import Host

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            Storage(str(tmp_path / "records"), buffer_size=1),
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerHTTPServer(service)
        server.serve()
        try:
            client = RemoteScheduler(server.url)
            host = Host(id="n0", hostname="n0", ip="127.0.0.1",
                        download_port=1)
            host.stats.network.idc = "idc-a"
            reg = client.register_peer(host=host, url="https://o/batch-rpc")
            client.set_task_info(reg.peer, 4 * PIECE, 4, PIECE)
            client.report_pieces_finished(
                reg.peer,
                [
                    {"number": i, "parent_id": "", "length": PIECE,
                     "cost_ns": 1000 + i}
                    for i in range(4)
                ],
            )
            # Client mirror advanced per piece...
            assert len(reg.peer.finished_pieces) == 4
            # ...and the SERVER's peer saw all four from one RPC.
            srv_peer = service.resource.peer_manager.load(reg.peer.id)
            assert srv_peer is not None and len(srv_peer.finished_pieces) == 4
        finally:
            server.stop()


class TestBenchDownloadSmoke:
    def test_smoke_schema_gate(self, capsys):
        from tools import bench_download

        rc = bench_download.main(["--smoke"])
        line = capsys.readouterr().out.strip().splitlines()[-1]
        out = json.loads(line)
        assert rc == 0 and out["ok"], out
        for key in bench_download.SCHEMA_KEYS:
            assert key in out, key
        for arm in ("legacy_single", "pipelined_single",
                    "legacy_swarm", "pipelined_swarm"):
            assert arm in out["arms"]
            for k in bench_download.ARM_KEYS:
                assert k in out["arms"][arm], (arm, k)
        # The fast arm really exercised the new plane, even at smoke size.
        assert out["serve"]["sendfile_serves"] > 0
        assert out["pool"]["reuses"] > 0
        assert out["serve"]["legacy_sendfile_serves"] == 0
