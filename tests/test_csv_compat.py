"""Reference-CSV codec: column-count parity, roundtrips, and (when the
reference tree is mounted) parsing its actual test fixtures."""

import os

import pytest

from dragonfly2_tpu.records.csv_compat import (
    DOWNLOAD_COLUMNS_TOTAL,
    NETWORK_TOPOLOGY_COLUMNS_TOTAL,
    download_from_row,
    download_to_row,
    read_download_csv,
    read_topology_csv,
    topology_from_row,
    topology_to_row,
    write_download_csv,
    write_topology_csv,
)
from dragonfly2_tpu.records.schema import (
    Download,
    DownloadError,
    HostRecord,
    NetworkTopologyRecord,
    Parent,
    Piece,
    ProbeStats,
    TaskRecord,
    TopoHost,
)

REFERENCE = "/root/reference/trainer/storage/testdata"


def make_download() -> Download:
    host = HostRecord(id="child-1", hostname="c1", ip="10.0.0.1", port=8002,
                      download_port=8001, concurrent_upload_limit=50)
    host.cpu.logical_count = 8
    host.cpu.percent = 37.5
    host.cpu.times.user = 120.25
    host.memory.total = 64 << 30
    host.memory.used_percent = 41.0
    host.network.idc = "idc-a"
    host.network.location = "eu-west"
    host.disk.total = 1 << 40
    host.build.git_version = "v2.1.0"
    parent_host = HostRecord(id="parent-1", hostname="p1", ip="10.0.0.2")
    parent = Parent(
        id="peer-parent-1", state="Succeeded", cost=1_500_000_000,
        upload_piece_count=3, finished_piece_count=3, host=parent_host,
        pieces=[Piece(length=4 << 20, cost=250_000_000, created_at=111)] * 3,
        created_at=100, updated_at=200,
    )
    return Download(
        id="peer-child-1", tag="t", application="app", state="Succeeded",
        error=DownloadError(code="", message=""),
        cost=2_000_000_000, finished_piece_count=7,
        task=TaskRecord(id="task-1", url="https://o/blob", type="normal",
                        content_length=28 << 20, total_piece_count=7,
                        state="Succeeded", created_at=50, updated_at=60),
        host=host, parents=[parent], created_at=300, updated_at=400,
    )


def make_topology() -> NetworkTopologyRecord:
    src = TopoHost(id="h-src", type="normal", hostname="s", ip="10.1.0.1",
                   port=8002)
    src.network.idc = "idc-b"
    dests = []
    for i in range(3):
        d = TopoHost(id=f"h-d{i}", type="normal", hostname=f"d{i}",
                     ip=f"10.1.0.{i+2}", port=8002,
                     probes=ProbeStats(average_rtt=5_000_000 + i,
                                       created_at=10, updated_at=20))
        dests.append(d)
    return NetworkTopologyRecord(id="nt-1", host=src, dest_hosts=dests,
                                 created_at=999)


class TestLayout:
    def test_column_counts_match_reference(self):
        # Verified against the reference fixtures: 1934 / 71.
        assert DOWNLOAD_COLUMNS_TOTAL == 1934
        assert NETWORK_TOPOLOGY_COLUMNS_TOTAL == 71
        assert len(download_to_row(make_download())) == 1934
        assert len(topology_to_row(make_topology())) == 71

    def test_zero_record_renders_go_zero_values(self):
        row = download_to_row(Download())
        # Strings empty, numerics "0" (gocsv zero rendering) — except the
        # two places OUR defaults are deliberately non-zero: task
        # content_length (-1 = unknown) and host type ("normal").
        assert set(row) <= {"", "0", "-1", "normal"}
        assert row[0] == ""   # id (string)
        assert row[6] == "0"  # cost (int64)


class TestRoundtrip:
    def test_download_roundtrip_exact(self, tmp_path):
        records = [make_download(), Download(id="empty")]
        path = str(tmp_path / "download.csv")
        assert write_download_csv(records, path) == 2
        back = read_download_csv(path)
        assert back == records  # dataclass equality, full depth

    def test_topology_roundtrip_exact(self, tmp_path):
        records = [make_topology(), NetworkTopologyRecord(id="bare")]
        path = str(tmp_path / "nt.csv")
        assert write_topology_csv(records, path) == 2
        assert read_topology_csv(path) == records

    def test_row_stability(self):
        """write → read → write produces the identical row (no drift)."""
        row = download_to_row(make_download())
        again = download_to_row(download_from_row(row))
        assert again == row
        trow = topology_to_row(make_topology())
        assert topology_to_row(topology_from_row(trow)) == trow

    def test_go_float_formatting(self):
        """strconv.FormatFloat(v, 'g', -1, 64) behavior: e-form when the
        decimal exponent is < -4 or >= 6, shortest digits either way."""
        from dragonfly2_tpu.records.csv_compat import _go_float

        assert _go_float(0.0) == "0"
        assert _go_float(1.5) == "1.5"
        assert _go_float(123456.78) == "123456.78"
        assert _go_float(100000.0) == "100000"
        assert _go_float(1000000.0) == "1e+06"
        assert _go_float(8589934592.0) == "8.589934592e+09"
        assert _go_float(0.0001) == "0.0001"
        assert _go_float(0.00001) == "1e-05"
        assert _go_float(-2500000.5) == "-2.5000005e+06"
        # Every form round-trips through the reader.
        for v in (1e6, 8589934592.0, 1e-5, -2500000.5, 123456.78):
            assert float(_go_float(v)) == v

    def test_precision_survives_roundtrip(self):
        """%g-style truncation and the int(float()) detour both corrupt
        real values — full precision must survive."""
        d = make_download()
        d.created_at = 1_700_000_000_000_000_001      # int64 > 2^53
        d.host.cpu.times.user = 123456.78             # >6 sig digits
        d.host.memory.used_percent = 41.333333
        back = download_from_row(download_to_row(d))
        assert back.created_at == 1_700_000_000_000_000_001
        assert back.host.cpu.times.user == 123456.78
        assert back.host.memory.used_percent == 41.333333

    def test_wrong_width_rejected(self):
        with pytest.raises(ValueError):
            download_from_row(["x"] * 10)
        with pytest.raises(ValueError):
            topology_from_row(["x"] * 70)


class TestMigrationToColumnar:
    def test_csv_dataset_feeds_tpu_ingest(self, tmp_path):
        """The migration path: reference-CSV records → columnar shard →
        readable by the trainer's ingest reader."""
        from dragonfly2_tpu.records.columnar import ColumnarReader
        from dragonfly2_tpu.records.csv_compat import (
            convert_download_csv_to_columnar,
        )
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS

        csv_path = str(tmp_path / "legacy.csv")
        write_download_csv([make_download() for _ in range(4)], csv_path)
        out = str(tmp_path / "legacy.dfc")
        n = convert_download_csv_to_columnar(csv_path, out)
        assert n > 0
        r = ColumnarReader(out)
        assert tuple(r.columns) == tuple(DOWNLOAD_COLUMNS)
        assert r.num_rows == n


class TestTrainerAcceptsReferenceCSV:
    def test_csv_upload_trains_end_to_end(self, tmp_path):
        """A reference scheduler streaming its CSV dataset (announcer.go
        upload shape) into our trainer: ingested, converted, trained,
        model registered — no client-side changes."""
        import numpy as np

        from dragonfly2_tpu.manager import ModelRegistry
        from dragonfly2_tpu.records.synthetic import SyntheticCluster
        from dragonfly2_tpu.trainer.service import MLP_MODEL_NAME, TrainerService
        from dragonfly2_tpu.trainer.train import TrainConfig

        # Build a CSV dataset with real signal from the synthetic cluster.
        cluster = SyntheticCluster(num_hosts=24, seed=3)
        records = []
        rng = np.random.default_rng(0)
        for i in range(300):
            d = make_download()
            d.id = f"peer-{i}"
            src, dst = rng.integers(0, 24, 2)
            d.host.id = cluster.hosts[dst].id
            d.parents[0].host.id = cluster.hosts[src].id
            bw = cluster._bandwidth_vec(
                np.array([src]), np.array([dst])
            )[0]
            piece_cost_ns = int((4 << 20) / max(bw, 1.0) * 1e9)
            for p in d.parents[0].pieces:
                p.cost = piece_cost_ns
            records.append(d)
        csv_path = str(tmp_path / "download_legacy.csv")
        write_download_csv(records, csv_path)

        registry = ModelRegistry()
        svc = TrainerService(
            registry, data_dir=str(tmp_path / "staged"),
            train_config=TrainConfig(epochs=2, warmup_steps=2),
        )
        session = svc.open_train_stream(
            ip="10.0.0.1", hostname="legacy-sched", scheduler_id="legacy"
        )
        with open(csv_path, "rb") as f:
            svc.receive_shard_bytes(
                session, "download", "download_legacy.csv", f.read()
            )
        key = session.close_and_train()
        run = svc.runs[key]
        assert run.error is None, run.error
        assert run.download_rows > 0
        assert registry.list(scheduler_id="legacy", name=MLP_MODEL_NAME)


@pytest.mark.skipif(
    not os.path.isdir(REFERENCE), reason="reference tree not mounted"
)
class TestReferenceFixtures:
    """The actual files the reference's trainer tests ship."""

    def test_parses_reference_download_fixture(self):
        records = read_download_csv(os.path.join(REFERENCE, "download.csv"))
        assert records  # all-zero row parses to a default Download
        assert records[0].id == "" and records[0].parents == []

    def test_parses_reference_topology_fixture(self):
        records = read_topology_csv(
            os.path.join(REFERENCE, "networktopology.csv")
        )
        assert records
        first = records[0]
        assert first.id == "6"
        assert first.host.id == "3" and first.host.type == "super"
        assert first.host.network.location == "china"
        assert first.host.network.idc == "e1"
        assert first.dest_hosts and first.dest_hosts[0].probes.average_rtt == 10
