"""Trainer numerics tests (golden-run style, CPU-backend JAX — SURVEY §4
implication: the trainer needs loss-curve/numerics tests the reference
never had). All runs use the 8-device virtual CPU mesh from conftest."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dragonfly2_tpu.models import (
    GATRanker,
    GNNConfig,
    GraphSAGE,
    MLPConfig,
    MLPRegressor,
    build_neighbor_table,
)
from dragonfly2_tpu.parallel import MeshSpec, create_mesh
from dragonfly2_tpu.records.features import DOWNLOAD_FEATURE_DIM
from dragonfly2_tpu.records.synthetic import SyntheticCluster
from dragonfly2_tpu.trainer import (
    EdgeBatches,
    TrainConfig,
    export_mlp_scorer,
    load_scorer,
    train_gat_ranker,
    train_graphsage,
    train_mlp,
)
from dragonfly2_tpu.trainer.export import scorer_to_bytes
from dragonfly2_tpu.trainer.ingest import split_columns


@pytest.fixture(scope="module")
def cluster():
    return SyntheticCluster(num_hosts=48, seed=42)


@pytest.fixture(scope="module")
def rows(cluster):
    return cluster.generate_feature_rows(6000, seed=1)


class TestMesh:
    def test_create_mesh_8_devices(self):
        mesh = create_mesh()
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("data", "model")

    def test_mesh_spec_validation(self):
        with pytest.raises(ValueError):
            MeshSpec(data=3, model=2).resolve(8)
        assert MeshSpec().resolve(8) == (8, 1)
        assert MeshSpec(data=4, model=2).resolve(8) == (4, 2)


class TestMLP:
    def test_forward_shape(self):
        model = MLPRegressor(MLPConfig(hidden=(32, 16)))
        x = np.zeros((4, DOWNLOAD_FEATURE_DIM), np.float32)
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        y = model.apply({"params": params}, x)
        assert y.shape == (4,)

    def test_training_reduces_loss_and_learns(self, rows):
        feats, target, _, _ = split_columns(rows)
        train = EdgeBatches(rows[:5000], batch_size=512, seed=0)
        val = EdgeBatches(rows[5000:], batch_size=1000, shuffle=False, drop_remainder=False)
        state, metrics, history = train_mlp(
            train,
            val,
            model_config=MLPConfig(hidden=(64, 64)),
            config=TrainConfig(epochs=30, learning_rate=3e-3, warmup_steps=20, log_every=10),
        )
        assert history[0]["loss"] > history[-1]["loss"]
        # Predicting the mean gives log-space MAE ~1.0 on this data; the
        # model must do meaningfully better.
        assert metrics.mae < 0.65, metrics
        assert metrics.f1 > 0.75, metrics
        # Exported scorer (normalizer baked in) matches the eval path.
        from dragonfly2_tpu.trainer import export_from_state

        # Rows here are raw (unmasked) — the exported artifact must record that.
        scorer = export_from_state(state, post_hoc_masked=False)
        feats, target, _, _ = next(iter(val.epoch(0)))
        pred = scorer.score(feats)
        assert float(np.mean(np.abs(pred - target))) < 0.7

    def test_export_matches_flax_forward(self, rows):
        feats, *_ = split_columns(rows[:64])
        model = MLPRegressor(MLPConfig(hidden=(32, 16), dropout=0.0))
        params = model.init(jax.random.PRNGKey(1), feats)["params"]
        flax_out = np.asarray(model.apply({"params": params}, feats))
        scorer = export_mlp_scorer(params, post_hoc_masked=False)
        np_out = scorer.score(feats)
        np.testing.assert_allclose(np_out, flax_out, rtol=2e-2, atol=2e-2)

    def test_scorer_serialization_roundtrip(self, rows, tmp_path):
        feats, *_ = split_columns(rows[:16])
        model = MLPRegressor(MLPConfig(hidden=(32,), dropout=0.0))
        params = model.init(jax.random.PRNGKey(2), feats)["params"]
        scorer = export_mlp_scorer(params)
        blob = scorer_to_bytes(scorer)
        restored = load_scorer(blob)
        np.testing.assert_allclose(restored.score(feats), scorer.score(feats))
        path = tmp_path / "scorer.npz"
        from dragonfly2_tpu.trainer.export import save_scorer

        save_scorer(scorer, str(path))
        restored2 = load_scorer(str(path))
        np.testing.assert_allclose(restored2.score(feats), scorer.score(feats))


class TestNeighborTable:
    def test_padding_and_sampling(self):
        src = np.array([0, 1, 2, 3, 4, 5, 6, 7], dtype=np.int32)
        dst = np.array([9, 9, 9, 9, 0, 0, 1, 2], dtype=np.int32)
        rtt = np.arange(8, dtype=np.float32)
        table = build_neighbor_table(10, src, dst, rtt, max_neighbors=3)
        assert table.indices.shape == (10, 3)
        assert float(table.mask[9].sum()) == 3.0  # degree 4 sampled to 3
        assert float(table.mask[0].sum()) == 2.0
        assert float(table.mask[5].sum()) == 0.0  # isolated
        # node 1's single in-neighbor is src 6
        assert int(table.indices[1, 0]) == 6
        assert float(table.edge_feats[1, 0, 0]) == 6.0

    def test_gnn_forward_shapes(self, cluster):
        src, dst, rtt = cluster.probe_edges(density=0.2, seed=0)
        table = build_neighbor_table(cluster.num_hosts, src, dst, rtt / 1e9)
        nf = cluster._host_feature_matrix()
        sage = GraphSAGE(GNNConfig(hidden=32, out_dim=16, num_layers=2))
        params = sage.init(jax.random.PRNGKey(0), nf, table)["params"]
        emb = sage.apply({"params": params}, nf, table)
        assert emb.shape == (cluster.num_hosts, 16)
        assert np.isfinite(np.asarray(emb)).all()

        gat = GATRanker(GNNConfig(hidden=32, out_dim=16, num_layers=1, num_heads=2))
        q_src = np.arange(8, dtype=np.int32)
        q_dst = (np.arange(8, dtype=np.int32) + 1) % cluster.num_hosts
        params = gat.init(jax.random.PRNGKey(0), nf, table, q_src, q_dst)["params"]
        scores = gat.apply({"params": params}, nf, table, q_src, q_dst)
        assert scores.shape == (8,)
        assert np.isfinite(np.asarray(scores)).all()


class TestGraphTraining:
    def test_graphsage_learns_rtt(self, cluster):
        src, dst, rtt = cluster.probe_edges(density=0.3, seed=1)
        table = build_neighbor_table(cluster.num_hosts, src, dst, rtt / 1e9)
        nf = cluster._host_feature_matrix()
        target = np.log1p(rtt / 1e6).astype(np.float32)  # log-ms
        state, metrics, history = train_graphsage(
            nf, table, src, dst, target,
            model_config=GNNConfig(hidden=32, out_dim=16, num_layers=2, dropout=0.0),
            config=TrainConfig(epochs=300, learning_rate=1e-2, warmup_steps=20, log_every=100),
            batch_size=128,
        )
        assert history[0]["loss"] > history[-1]["loss"]
        baseline_mae = float(np.mean(np.abs(target - target.mean())))
        assert metrics.mae < baseline_mae * 0.5, (metrics.mae, baseline_mae)

    def test_gat_ranker_learns_bandwidth(self, cluster):
        # Probe graph provides structure; download edges provide bw targets.
        psrc, pdst, prtt = cluster.probe_edges(density=0.3, seed=2)
        table = build_neighbor_table(cluster.num_hosts, psrc, pdst, prtt / 1e9)
        nf = cluster._host_feature_matrix()
        rng = np.random.default_rng(3)
        n = 4000
        e_src = rng.integers(0, cluster.num_hosts, n)
        e_dst = (e_src + rng.integers(1, cluster.num_hosts, n)) % cluster.num_hosts
        bw = cluster._bandwidth_vec(e_src, e_dst)
        target = np.log1p(bw).astype(np.float32)
        state, metrics, history = train_gat_ranker(
            nf, table, e_src.astype(np.int32), e_dst.astype(np.int32), target,
            model_config=GNNConfig(hidden=32, out_dim=16, num_layers=1, num_heads=2, dropout=0.0),
            config=TrainConfig(epochs=60, learning_rate=3e-3, warmup_steps=20, log_every=100),
            batch_size=512,
        )
        baseline_mae = float(np.mean(np.abs(target - target.mean())))
        assert metrics.mae < baseline_mae * 0.7, (metrics.mae, baseline_mae)


class TestHopModelParallel:
    def test_node_sharded_training_matches_replicated(self):
        """node_sharding="model" (tensor-parallel node tables) trains to
        the same result as replicated mode on a (4 data × 2 model) mesh —
        the config[4] scale path as a PRODUCT option, not dryrun-only."""
        import numpy as np

        from dragonfly2_tpu.models import build_neighbor_table
        from dragonfly2_tpu.models.hop import HopConfig
        from dragonfly2_tpu.records.synthetic import SyntheticCluster
        from dragonfly2_tpu.trainer.train import TrainConfig, train_hop_ranker

        n_nodes, n_edges = 512, 16_384
        cluster = SyntheticCluster(num_hosts=n_nodes, seed=0)
        src, dst, rtt = cluster.probe_edges(density=0.05, seed=0)
        table = build_neighbor_table(n_nodes, src, dst, rtt / 1e9, max_neighbors=8)
        nf = cluster._host_feature_matrix()
        rng = np.random.default_rng(0)
        es = rng.integers(0, n_nodes, n_edges).astype(np.int32)
        ed = (es + rng.integers(1, n_nodes, n_edges).astype(np.int32)) % n_nodes
        y = np.log1p(cluster._bandwidth_vec(es, ed, rng=np.random.default_rng(7))).astype(np.float32)

        mesh = create_mesh(MeshSpec(data=4, model=2))
        cfg = TrainConfig(epochs=2, warmup_steps=2)
        mcfg = HopConfig(hidden=32, out_dim=16, node_embed_dim=8)
        _, m_repl, _ = train_hop_ranker(
            nf, table, es, ed, y, model_config=mcfg, config=cfg,
            mesh=mesh, batch_size=2048, node_sharding="replicated",
        )
        _, m_mp, _ = train_hop_ranker(
            nf, table, es, ed, y, model_config=mcfg, config=cfg,
            mesh=mesh, batch_size=2048, node_sharding="model",
        )
        # Same data, same seeds: metrics agree to float tolerance (the
        # sharded program's reduction order differs slightly).
        assert abs(m_repl.mae - m_mp.mae) < 5e-3, (m_repl.mae, m_mp.mae)
        with __import__("pytest").raises(ValueError):
            train_hop_ranker(
                nf, table, es, ed, y, model_config=mcfg, config=cfg,
                mesh=mesh, batch_size=2048, node_sharding="bogus",
            )
