"""Dynamic replay-determinism cross-check (DF018/DF019, enforced).

``tests/conftest.py`` installs ``dragonfly2_tpu.utils.dfdet`` before any
test import: the ambient nondeterminism sources (time.time/monotonic/
perf_counter + _ns, os.urandom, uuid.uuid1/uuid4, ambient random draws)
are patched with call-site recorders that are ARMED only while a
declared replay root (``records/determinism_contracts.py``) is on the
stack.  This module (named ``zz`` so it collects last and sees the whole
session) drives the replay surfaces, then asserts:

- every ambient read observed under an armed root maps into DF018's
  static taint knowledge (``tools/dflint/detrules.py``) — a resolver
  blind spot is a tier-1 failure, not silent rot;
- stale contracts fail in both directions (an undeclared root name in an
  observation is a gap; every declared root resolves statically);
- the acceptance mutations fail BOTH halves: ``time.time()`` inserted
  into ``SLOEngine.evaluate`` fails static DF018 by name AND surfaces as
  a witness gap when the mutant runs armed; dropping ``sort_keys`` from
  the journal writer fails static DF019 by name AND makes the dual-run
  drill diverge across PYTHONHASHSEED values;
- the dual-run harness holds: every declared replay root re-executed in
  two subprocesses (``tests/_det_child.py``) over identical journal
  bytes with different PYTHONHASHSEED produces byte-identical decision
  JSON.

A gap here means the static resolver (or the contract registry) has a
blind spot — fix ``tools/dflint/detrules.py`` /
``records/determinism_contracts.py``, never this test.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import types
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils import dfdet  # noqa: E402

SLO_RELPATH = "dragonfly2_tpu/utils/slo.py"
MJ_RELPATH = "dragonfly2_tpu/utils/metric_journal.py"
# Acceptance mutation 1: ambient wall-clock read on the replay path of
# SLOEngine.evaluate (the declared seam discipline says `now` is the
# only clock door).
SLO_NEEDLE = "        else:\n            t = now"
SLO_MUTANT = SLO_NEEDLE + "\n        t = time.time()"
# Acceptance mutation 2: drop canonical ordering from the DFMJ1 frame
# writer.
MJ_NEEDLE = "payload = json.dumps(snapshot, sort_keys=True).encode()"
MJ_MUTANT = "payload = json.dumps(snapshot).encode()"

SLOS = [
    {
        "name": "dw_avail",
        "objective": "availability",
        "good_metric": "dw_good_total",
        "total_metric": "dw_all_total",
        "target": 0.9,
        "fast_window_s": 60.0,
        "slow_window_s": 600.0,
    }
]


def _witness():
    w = dfdet.witness()
    if w is None:
        pytest.skip("determinism witness disabled (DF_DET_WITNESS=0)")
    return w


_REAL_MODULES = None


def _real_modules():
    """Parsed Modules for the full tree, loaded once per session — the
    clean analysis and both static acceptance mutants below each need a
    whole-program view and the parse dominates the build."""
    global _REAL_MODULES
    if _REAL_MODULES is None:
        # Same roots as test_dflint's det battery — share its session
        # cache (Modules are read-only to Program and the analyses).
        from tests.test_dflint import _real_tree_modules

        _REAL_MODULES = _real_tree_modules()
    return _REAL_MODULES


@pytest.fixture(scope="module")
def analysis():
    from tools.dflint.detrules import DetAnalysis
    from tools.dflint.program import Program

    return DetAnalysis(Program(list(_real_modules())), REPO)


def _snapshots():
    """Five cumulative journal-style snapshots of one synthetic run."""
    from dragonfly2_tpu.utils.metrics import Registry

    reg = Registry()
    good = reg.counter("dw_good_total")
    total = reg.counter("dw_all_total")
    snaps = []
    for seq in range(1, 6):
        good.inc(9.0)
        total.inc(10.0)
        snaps.append({
            "v": 1, "service": "dw", "run_id": "run-dw", "pid": 7,
            "seq": seq, "ts": 100.0 * seq, "metrics": reg.snapshot(),
        })
    return snaps


def _spans():
    base = 1_000_000_000
    mk = lambda sid, parent, name, svc, s, e: {  # noqa: E731
        "trace_id": "t1", "span_id": sid, "parent_id": parent,
        "name": name, "service": svc, "start_ns": base + s, "end_ns": base + e,
        "status": "OK", "status_message": "", "attrs": {},
    }
    return [
        mk("a", "", "announce", "scheduler", 0, 90_000_000),
        mk("b", "a", "score", "scheduler", 5_000_000, 50_000_000),
        mk("c", "a", "persist", "manager", 55_000_000, 85_000_000),
    ]


def _drive_workloads():
    """Exercise every in-process replay root once, armed, through the
    real public APIs."""
    import numpy as np

    import tools.trace_assemble as ta
    from dragonfly2_tpu.qos.accounting import TenantAccounting
    from dragonfly2_tpu.qos.autopilot import SLOAutopilot
    from dragonfly2_tpu.rollout import evaluation as ev
    from dragonfly2_tpu.rollout.controller import (
        RolloutController,
        RolloutGuardrails,
    )
    from dragonfly2_tpu.rollout.shadow import SHADOW_COLUMNS
    from dragonfly2_tpu.scheduler.sharding import ShardRing
    from dragonfly2_tpu.utils.slo import replay_fleet

    snaps = _snapshots()
    eng = replay_fleet(snaps, SLOS)
    eng.evaluate(500.0)
    ap = SLOAutopilot.replay(snaps, SLOS)
    assert len(ap.decisions) == len(snaps)

    acct = TenantAccounting(now=0.0)
    for step in range(50):
        acct.note_at("tenant-%d" % (step % 4), 0.05 * (step + 1))
    acct.snapshot()

    ctl = RolloutController.__new__(RolloutController)
    ctl.guardrails = RolloutGuardrails()
    ctl._breach({
        "psi_max": 0.01,
        "regret_at_k": {"candidate": 0.1, "active": 0.2, "k": 4},
        "inversion_rate": {"candidate": 0.1, "active": 0.2},
    })

    rng = np.random.default_rng(3)
    n = 64
    col = {name: i for i, name in enumerate(SHADOW_COLUMNS)}
    shadow = np.zeros((n, len(SHADOW_COLUMNS)), dtype=np.float32)
    shadow[:, col["announce_seq"]] = np.arange(n) // 8
    shadow[:, col["candidate_version"]] = 1
    shadow[:, col["src_bucket"]] = rng.integers(0, 16, n)
    shadow[:, col["dst_bucket"]] = rng.integers(0, 16, n)
    shadow[:, col["active_rank"]] = rng.integers(0, 8, n)
    shadow[:, col["candidate_rank"]] = rng.integers(0, 8, n)
    dl = np.zeros((32, 3), dtype=np.float32)
    dl[:, 0] = rng.integers(0, 16, 32)
    dl[:, 1] = rng.integers(0, 16, 32)
    dl[:, 2] = rng.random(32)
    ev.evaluate_shadow(shadow, dl, k=3, psi_max=0.05)

    ring = ShardRing({"s-%d" % i: "" for i in range(4)})
    loads = {"s-%d" % i: float(i) for i in range(4)}
    for i in range(32):
        ring.owner("key-%d" % i)
        ring.pick("key-%d" % i, load_of=lambda sid: loads[sid])

    traces = ta.assemble(_spans())
    for tid, tspans in traces.items():
        ta.critical_path(tspans)
        ta.summarize_trace(tid, tspans)


class TestDetWitness:
    def test_witness_wraps_every_declared_root(self, analysis):
        w = _witness()
        declared = set(analysis.replay_root_index())
        assert declared, "no replay roots resolved statically"
        assert declared == set(w.wrapped_roots), (
            "runtime witness and static resolver disagree on the root "
            f"set: static={sorted(declared)} "
            f"runtime={sorted(w.wrapped_roots)}"
        )

    def test_recorder_is_armed_only_under_a_root(self):
        _witness()
        with dfdet.isolated() as w:
            time.time()  # disarmed: must NOT record
            assert w.snapshot() == []
            with dfdet.armed("slo.evaluate"):
                time.time()
            snap = w.snapshot()
        assert len(snap) == 1
        assert snap[0]["source"] == "time.time"
        assert snap[0]["root"] == "slo.evaluate"
        assert snap[0]["relpath"] == "tests/test_zz_detwitness.py"

    def test_session_observations_have_no_static_gaps(self, analysis):
        from tools.dflint.detrules import det_witness_gaps

        w = _witness()
        _drive_workloads()
        gaps = det_witness_gaps(analysis, w.snapshot())
        assert not gaps, (
            "static taint-report gaps (fix tools/dflint/detrules.py / "
            "records/determinism_contracts.py, not this test):\n  "
            + "\n  ".join(gaps)
        )

    def test_unknown_site_is_a_gap(self, analysis):
        from tools.dflint.detrules import det_witness_gaps

        fake = [{
            "relpath": SLO_RELPATH, "lineno": 1,
            "source": "time.time", "root": "slo.evaluate", "count": 1,
        }]
        gaps = det_witness_gaps(analysis, fake)
        assert gaps and "time.time" in gaps[0]

    def test_undeclared_root_name_is_a_gap(self, analysis):
        from tools.dflint.detrules import det_witness_gaps

        fake = [{
            "relpath": SLO_RELPATH, "lineno": 1,
            "source": "time.time", "root": "no.such_root", "count": 1,
        }]
        gaps = det_witness_gaps(analysis, fake)
        assert gaps and "no.such_root" in gaps[0]

    def test_clean_tree_has_empty_findings(self, analysis):
        assert analysis.findings() == []

    def test_bench_disarm_stamp_sees_installed_witness(self):
        """bench_sched stamps ``det_witness_disarmed`` into its report;
        in this process (conftest installed the witness) the stamp must
        read armed, so only genuinely witness-less bench runs carry the
        True flag."""
        _witness()  # skip when DF_DET_WITNESS=0
        sys.path.insert(0, str(REPO / "tools"))
        try:
            from bench_sched import _det_witness_disarmed
        finally:
            sys.path.pop(0)
        assert _det_witness_disarmed() is False


def _mutated_analysis(relpath, needle, repl):
    from tools.dflint.core import Module
    from tools.dflint.detrules import DetAnalysis
    from tools.dflint.program import Program

    modules = []
    hit = False
    for m in _real_modules():
        if m.relpath == relpath:
            assert needle in m.source, f"needle drifted in {relpath}"
            m = Module(m.path, relpath, m.source.replace(needle, repl))
            hit = True
        modules.append(m)
    assert hit, f"{relpath} not collected"
    return DetAnalysis(Program(modules), REPO)


class TestAcceptanceMutationsStatic:
    def test_wall_clock_in_evaluate_fails_df018(self):
        from tools.dflint.detrules import RULE_DET

        a = _mutated_analysis(SLO_RELPATH, SLO_NEEDLE, SLO_MUTANT)
        hits = [f for f in a.findings() if f.rule == RULE_DET]
        assert len(hits) == 1
        f = hits[0]
        assert f.path == SLO_RELPATH
        assert "time.time" in f.message
        assert "SLOEngine.evaluate" in (f.qual or "")

    def test_sort_keys_drop_fails_df019(self):
        from tools.dflint.detrules import RULE_CANON

        a = _mutated_analysis(MJ_RELPATH, MJ_NEEDLE, MJ_MUTANT)
        hits = [f for f in a.findings() if f.rule == RULE_CANON]
        assert len(hits) == 1
        f = hits[0]
        assert f.path == MJ_RELPATH
        assert "sort_keys" in f.message
        assert "metric_journal.frame" in f.message


class TestAcceptanceMutationDynamic:
    def test_mutant_evaluate_observed_and_flagged(self, analysis):
        """The time.time() mutant, executed ARMED, is recorded at its
        call site — and that site maps nowhere in the static taint
        knowledge of the REAL tree, so the cross-check flags it."""
        from tools.dflint.detrules import det_witness_gaps

        _witness()
        src = (REPO / SLO_RELPATH).read_text(encoding="utf-8")
        assert SLO_NEEDLE in src
        mutated = src.replace(SLO_NEEDLE, SLO_MUTANT)
        mod = types.ModuleType("dragonfly2_tpu.utils._slo_det_mutant")
        mod.__package__ = "dragonfly2_tpu.utils"
        mod.__file__ = str(REPO / SLO_RELPATH)
        sys.modules[mod.__name__] = mod
        try:
            exec(compile(mutated, mod.__file__, "exec"), mod.__dict__)
            eng = mod.SLOEngine(SLOS)
            for snap in _snapshots():
                eng.ingest_snapshot(snap)
            with dfdet.isolated() as w:
                with dfdet.armed("slo.evaluate"):
                    eng.evaluate(600.0)
                observed = w.snapshot()
        finally:
            sys.modules.pop(mod.__name__, None)
        times = [o for o in observed if o["source"] == "time.time"]
        assert times, f"mutant clock read not observed: {observed}"
        assert times[0]["relpath"] == SLO_RELPATH
        gaps = det_witness_gaps(analysis, times)
        assert gaps, "mutant ambient read excused by the static report"
        assert "time.time" in gaps[0]


def _run_child(args, hashseed, cwd=None):
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = str(hashseed)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(REPO / "tests" / "_det_child.py"), *args],
        capture_output=True, timeout=240, cwd=cwd or str(REPO), env=env,
    )
    assert proc.returncode == 0, (
        f"det child failed (seed {hashseed}):\n{proc.stderr.decode()}"
    )
    return proc.stdout


@pytest.fixture(scope="module")
def det_workdir(tmp_path_factory):
    """Journal bytes written ONCE; both child invocations replay the
    same files."""
    from dragonfly2_tpu.utils.metric_journal import encode_frame

    work = tmp_path_factory.mktemp("detrun")
    for pi in range(2):
        frames = b""
        for snap in _snapshots():
            snap = dict(snap)
            snap["service"] = f"svc-{pi}"
            snap["run_id"] = f"run-{pi}"
            snap["pid"] = 100 + pi
            snap["ts"] = float(snap["ts"]) + pi
            frames += encode_frame(snap)
        (work / f"proc{pi}.dfmj").write_bytes(frames)
    (work / "slos.json").write_text(json.dumps(SLOS), encoding="utf-8")
    (work / "spans.json").write_text(json.dumps(_spans()), encoding="utf-8")
    return work


class TestDualRun:
    def test_all_roots_byte_identical_across_hashseeds(self, det_workdir):
        out0 = _run_child(["roots", str(det_workdir)], hashseed=0)
        out42 = _run_child(["roots", str(det_workdir)], hashseed=42)
        assert out0, "child produced no output"
        decisions = json.loads(out0)
        # Every declared root reported a decision payload.
        from dragonfly2_tpu.records.determinism_contracts import (
            DETERMINISM_CONTRACTS,
        )

        assert set(decisions) == set(DETERMINISM_CONTRACTS["replay_roots"])
        assert out0 == out42, (
            "replay-root decision JSON diverged across PYTHONHASHSEED"
        )

    def test_real_writer_frame_bytes_are_seed_independent(self):
        real = str(REPO / MJ_RELPATH)
        outs = {_run_child(["drill", real], hashseed=s) for s in (0, 42)}
        assert len(outs) == 1, "canonical DFMJ1 frame diverged across seeds"

    def test_sort_keys_drop_diverges_across_hashseeds(self, tmp_path):
        src = (REPO / MJ_RELPATH).read_text(encoding="utf-8")
        assert MJ_NEEDLE in src
        mutant = tmp_path / "metric_journal_mutant.py"
        mutant.write_text(
            src.replace(MJ_NEEDLE, MJ_MUTANT), encoding="utf-8"
        )
        outs = {
            _run_child(["drill", str(mutant)], hashseed=s)
            for s in (0, 1, 2, 42)
        }
        assert len(outs) > 1, (
            "sort_keys-dropped writer still produced identical bytes "
            "across PYTHONHASHSEED values — the divergence drill lost "
            "its teeth"
        )
