"""sync_peers job and image-manifest preheat resolution."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dragonfly2_tpu.jobs import (
    ImageResolver,
    JobQueue,
    SyncPeers,
    Worker,
    make_sync_peers_handler,
    parse_manifest_url,
    preheat_image,
)
from dragonfly2_tpu.jobs.preheat import PREHEAT
from dragonfly2_tpu.manager import ClusterManager, SchedulerInstance
from dragonfly2_tpu.scheduler import Resource
from dragonfly2_tpu.scheduler.resource import Host


def make_host(i):
    return Host(
        id=f"sp-host-{i}", hostname=f"sp-{i}", ip=f"10.9.0.{i}",
        port=8002, download_port=8001,
    )


class TestSyncPeers:
    def test_merge_and_inactive_marking(self):
        resource = Resource()
        for i in range(3):
            resource.store_host(make_host(i))
        broker = JobQueue()
        clusters = ClusterManager()
        sched = clusters.register_scheduler(
            SchedulerInstance(id="sched-A", cluster_id="c1", ip="1.1.1.1", port=1)
        )
        worker = Worker(broker, f"scheduler:{sched.id}")
        worker.register("sync_peers", make_sync_peers_handler(resource))
        worker.serve()
        try:
            sp = SyncPeers(broker, clusters, job_timeout_s=10.0)
            assert sp.run_once() == 1
            peers = sp.list_peers("sched-A", active_only=True)
            assert {p.id for p in peers} == {f"sp-host-{i}" for i in range(3)}
            # Host 1 vanishes from the scheduler → flips inactive.
            resource.host_manager.delete("sp-host-1")
            sp.run_once()
            active = {p.id for p in sp.list_peers("sched-A", active_only=True)}
            assert active == {"sp-host-0", "sp-host-2"}
            all_recs = {p.id: p.active for p in sp.list_peers("sched-A")}
            assert all_recs["sp-host-1"] is False
        finally:
            worker.stop()

    def test_dead_scheduler_inventory_goes_inactive(self):
        """A scheduler dropping out of the active set (keepalive expiry)
        must not leave its peers reported live forever."""
        import time as _time

        resource = Resource()
        resource.store_host(make_host(9))
        broker = JobQueue()
        clusters = ClusterManager(keepalive_ttl=0.2)
        sched = clusters.register_scheduler(
            SchedulerInstance(id="dying", cluster_id="c1", ip="1.1.1.1", port=1)
        )
        worker = Worker(broker, f"scheduler:{sched.id}")
        worker.register("sync_peers", make_sync_peers_handler(resource))
        worker.serve()
        try:
            sp = SyncPeers(broker, clusters, job_timeout_s=10.0)
            sp.run_once()
            assert sp.list_peers("dying", active_only=True)
            _time.sleep(0.3)  # keepalive TTL expires, scheduler vanishes
            sp.run_once()
            assert sp.list_peers("dying", active_only=True) == []
        finally:
            worker.stop()

    def test_job_records_pruned(self):
        import time as _time

        resource = Resource()
        broker = JobQueue()
        clusters = ClusterManager()
        sched = clusters.register_scheduler(
            SchedulerInstance(id="s", cluster_id="c", ip="1.1.1.1", port=1)
        )
        worker = Worker(broker, f"scheduler:{sched.id}")
        worker.register("sync_peers", make_sync_peers_handler(resource))
        worker.serve()
        try:
            sp = SyncPeers(broker, clusters, interval_s=0.001,
                           job_timeout_s=5.0, prune_age_s=0.01)
            for _ in range(5):
                sp.run_once()
            _time.sleep(0.05)
            sp.run_once()  # prune of records older than 10×interval runs here
            assert len(broker.jobs) <= 2  # old terminal records gone
        finally:
            worker.stop()

    def test_consumerless_queue_bounded(self):
        """No worker ever attaches: the backlog cap evicts the oldest and
        prune reaps expired PENDING records."""
        import time as _time

        broker = JobQueue(max_backlog=5)
        jobs = [
            broker.enqueue("sync_peers", {}, queue_name="dead",
                           expires_at=_time.time() + 0.01)
            for _ in range(12)
        ]
        assert broker._q("dead").qsize() <= 5
        assert sum(1 for j in jobs if "evicted" in j.error) >= 7
        _time.sleep(0.05)
        broker.prune(max_age_s=0.01)
        assert len(broker.jobs) == 0  # expired PENDING + evicted all reaped

    def test_expired_jobs_not_replayed(self):
        import time as _time

        broker = JobQueue()
        job = broker.enqueue("sync_peers", {}, queue_name="q",
                             expires_at=_time.time() - 1)
        worker = Worker(broker, "q")
        worker.register("sync_peers", lambda a: ["should-not-run"])
        worker.drain()
        assert job.state.value == "FAILURE" and "expired" in job.error

    def test_unanswered_scheduler_skipped(self):
        broker = JobQueue()
        clusters = ClusterManager()
        clusters.register_scheduler(
            SchedulerInstance(id="dead", cluster_id="c1", ip="1.1.1.1", port=1)
        )
        sp = SyncPeers(broker, clusters, job_timeout_s=0.1)
        assert sp.run_once() == 0  # no worker: timeout, no crash


LAYERS = ["sha256:l1", "sha256:l2", "sha256:l3"]
TOKEN = "reg-token-1"


class _RegistryHandler(BaseHTTPRequestHandler):
    """Minimal distribution registry: token flow + manifest list + blobs."""

    require_auth = True
    blobs = {}  # digest → bytes (authenticated range-GET endpoint)

    def _json(self, code, payload, ctype="application/json", extra=None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        host = f"127.0.0.1:{self.server.server_address[1]}"
        if self.path.startswith("/token"):
            self._json(200, {"token": TOKEN})
            return
        if self.require_auth and self.headers.get("Authorization") != f"Bearer {TOKEN}":
            self._json(
                401, {"errors": [{"code": "UNAUTHORIZED"}]},
                extra={
                    "WWW-Authenticate":
                    f'Bearer realm="http://{host}/token",service="reg"'
                },
            )
            return
        if self.path == "/v2/proj/app/manifests/v1":
            # Manifest LIST with two platforms.
            self._json(
                200,
                {
                    "manifests": [
                        {"digest": "sha256:amd", "platform":
                         {"os": "linux", "architecture": "amd64"}},
                        {"digest": "sha256:arm", "platform":
                         {"os": "linux", "architecture": "arm64"}},
                    ]
                },
                ctype="application/vnd.oci.image.index.v1+json",
            )
        elif self.path == "/v2/proj/app/manifests/sha256:amd":
            self._json(
                200,
                {"layers": [{"digest": d} for d in LAYERS[:2]]},
                ctype="application/vnd.oci.image.manifest.v1+json",
            )
        elif self.path == "/v2/proj/app/manifests/sha256:arm":
            self._json(
                200,
                {"layers": [{"digest": LAYERS[2]}]},
                ctype="application/vnd.oci.image.manifest.v1+json",
            )
        elif self.path.startswith("/v2/proj/app/blobs/"):
            digest = self.path.rsplit("/", 1)[1]
            blob = self.blobs.get(digest)
            if blob is None:
                self._json(404, {})
                return
            rng = self.headers.get("Range")
            body = blob
            code = 200
            if rng:
                spec = rng.split("=", 1)[1]
                s, e = spec.split("-")
                body = blob[int(s): int(e) + 1]
                code = 206
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._json(404, {})

    def do_HEAD(self):
        if self.require_auth and self.headers.get("Authorization") != f"Bearer {TOKEN}":
            self.send_error(401)
            return
        digest = self.path.rsplit("/", 1)[1]
        blob = self.blobs.get(digest)
        if blob is None or "/blobs/" not in self.path:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()

    def log_message(self, *a):
        pass


@pytest.fixture
def registry():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _RegistryHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


class TestImageResolver:
    def test_parse_manifest_url(self):
        base, repo, ref = parse_manifest_url(
            "https://reg.example/v2/lib/nginx/manifests/1.25"
        )
        assert base == "https://reg.example"
        assert repo == "lib/nginx" and ref == "1.25"
        with pytest.raises(ValueError):
            parse_manifest_url("https://reg.example/lib/nginx:1.25")

    def test_token_flow_and_platform_filter(self, registry):
        r = ImageResolver(username="u", password="p", platform="linux/amd64")
        resolved = r.resolve_layers(f"{registry}/v2/proj/app/manifests/v1")
        assert resolved.urls == [
            f"{registry}/v2/proj/app/blobs/{d}" for d in LAYERS[:2]
        ]
        assert resolved.headers["Authorization"] == f"Bearer {TOKEN}"

    def test_all_platforms_when_unspecified(self, registry):
        r = ImageResolver(username="u", password="p")
        resolved = r.resolve_layers(f"{registry}/v2/proj/app/manifests/v1")
        assert len(resolved.urls) == 3

    def test_no_platform_match_raises(self, registry):
        r = ImageResolver(username="u", password="p", platform="windows/amd64")
        with pytest.raises(LookupError):
            r.resolve_layers(f"{registry}/v2/proj/app/manifests/v1")

    def test_preheat_carries_auth_to_blob_fetch(self, registry, tmp_path):
        """The pull token must reach the actual blob GETs: a seed daemon
        preheating a private registry downloads layer bytes end to end."""
        from dragonfly2_tpu.daemon import Daemon
        from dragonfly2_tpu.jobs import Worker, preheat_image
        from dragonfly2_tpu.jobs.preheat import make_preheat_handler
        from dragonfly2_tpu.scheduler import (
            Evaluator,
            NetworkTopology,
            Resource,
            SchedulerService,
            Scheduling,
            SchedulingConfig,
        )
        from dragonfly2_tpu.scheduler.resource import Host
        from dragonfly2_tpu.source import HTTPSourceClient, PieceSourceFetcher, SourceRegistry

        # Registry fixture serves authenticated blobs too.
        blob_bytes = {d: bytes([i]) * 8192 for i, d in enumerate(LAYERS)}
        _RegistryHandler.blobs = blob_bytes

        res = Resource()
        sched = SchedulerService(
            res,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            None,
            NetworkTopology(res.host_manager),
        )
        host = Host(id="seed-0", hostname="seed-0", ip="127.0.0.1",
                    port=8002, download_port=8001)
        res.store_host(host)
        src_registry = SourceRegistry()
        src_registry.register("http", HTTPSourceClient())
        fetcher = PieceSourceFetcher(registry=src_registry)
        seed = Daemon(host, sched, storage_root=str(tmp_path / "seed"),
                      source_fetcher=fetcher, prefer_native=False)
        broker = JobQueue()
        worker = Worker(broker, "scheduler:s1")
        worker.register(
            PREHEAT,
            make_preheat_handler(seed, content_length_for=fetcher.content_length),
        )
        resolver = ImageResolver(username="u", password="p",
                                 platform="linux/amd64")
        job = preheat_image(
            broker, f"{registry}/v2/proj/app/manifests/v1",
            ["scheduler:s1"], resolver, piece_size=4096,
        )
        worker.drain()
        state = broker.group_state(job.group.id)
        failures = [j.error for j in broker.jobs.values() if j.error]
        assert state.value == "SUCCESS", failures
        # Bytes are real layer content, fetched WITH the token.
        for d in LAYERS[:2]:
            url = f"{registry}/v2/proj/app/blobs/{d}"
            from dragonfly2_tpu.utils import idgen

            tid = idgen.task_id(url)
            assert seed.read_task_bytes(tid) == blob_bytes[d]

    def test_preheat_image_fans_out_layers(self, registry):
        broker = JobQueue()
        r = ImageResolver(username="u", password="p", platform="linux/amd64")
        captured = {}
        worker = Worker(broker, "scheduler:s1")
        worker.register(PREHEAT, lambda args: captured.update(args) or {})
        job = preheat_image(
            broker, f"{registry}/v2/proj/app/manifests/v1",
            ["scheduler:s1"], r,
        )
        worker.drain()
        assert broker.group_state(job.group.id).value == "SUCCESS"
        assert captured["urls"] == [
            f"{registry}/v2/proj/app/blobs/{d}" for d in LAYERS[:2]
        ]
        # Auth header rides along for the seed daemons' blob fetches.
        assert captured["headers"]["Authorization"] == f"Bearer {TOKEN}"
