"""Manager HA: replicated StateBackend, lease/fencing, hot standby,
and transparent client failover (DESIGN.md §20; ISSUE 9).

In-process coverage of the replication subsystem; the cross-process
leader-SIGKILL-with-standby drill lives in tests/test_manager_recovery.py.
"""

from __future__ import annotations

import json
import logging
import random
import urllib.error
import urllib.request

import numpy as np
import pytest

from dragonfly2_tpu.manager.cluster import ClusterManager
from dragonfly2_tpu.manager.registry import KVBlobStore, ModelRegistry
from dragonfly2_tpu.manager.replication import (
    REPLICATION_AUTH_HEADER,
    LogFollower,
    NotLeaderError,
    ReplicatedStateBackend,
    StaleTermError,
    sign_lease,
    sign_replication_request,
    verify_lease,
)
from dragonfly2_tpu.manager.rest import ManagerRESTServer
from dragonfly2_tpu.manager.state import MemoryBackend, SQLiteBackend
from dragonfly2_tpu.rpc.resolver import ManagerEndpoints
from dragonfly2_tpu.rpc.retry import CircuitBreaker, DecorrelatedJitterBackoff
from dragonfly2_tpu.utils import faultinject


def _leader(clock, **kw):
    kw.setdefault("node_id", "L")
    kw.setdefault("lease_ttl_s", 10.0)
    return ReplicatedStateBackend(
        MemoryBackend(), role="leader", clock=clock, **kw
    )


def _standby(clock, **kw):
    kw.setdefault("node_id", "F")
    kw.setdefault("lease_ttl_s", 10.0)
    return ReplicatedStateBackend(
        MemoryBackend(), role="standby", clock=clock, **kw
    )


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# Op log + leader commit path
# ---------------------------------------------------------------------------


class TestOpLog:
    def test_every_write_appends_term_seq_before_commit(self):
        clock = _Clock()
        b = _leader(clock)
        t = b.table("models")
        t.put("m1", {"id": "m1"})
        t.put_many({"m2": {"id": "m2"}, "m3": {"id": "m3"}})
        t.delete("m3")
        entries = b.log.entries_since(0)
        assert [(e["seq"], e["term"], e["op"]) for e in entries] == [
            (1, 1, "put_many"), (2, 1, "put_many"), (3, 1, "delete"),
        ]
        assert all(e["ns"] == "models" for e in entries)
        assert b.table("models").load_all() == {
            "m1": {"id": "m1"}, "m2": {"id": "m2"},
        }

    def test_failed_data_commit_discards_the_log_entry(self, tmp_path):
        """The crash witness for a fault injected between the WAL
        append and the data commit: the caller is TOLD the write failed,
        so the appended entry must not survive — it would ship to
        followers (and replay at boot) as a write the leader's own table
        never took, and the next successful commit would advance the
        applied watermark past it, making the divergence permanent."""
        db = str(tmp_path / "s.db")
        b = ReplicatedStateBackend(SQLiteBackend(db), node_id="L")
        b.table("models").put("m1", {"id": "m1"})
        # Drop exactly the DATA commit (the models-namespace put); the
        # log append (replication_log namespace) has already landed.
        inj = faultinject.FaultInjector([
            faultinject.FaultSpec(site="state.put.models", kind="drop", at=(0,)),
        ])
        with faultinject.installed(inj):
            with pytest.raises(ConnectionError):
                b.table("models").put("m2", {"id": "m2"})
        # The failed write's entry is gone: nothing ships, and a later
        # commit (seq 3 > failed seq 2) must not strand a divergence.
        assert [e["seq"] for e in b.log.entries_since(0)] == [1]
        b.table("models").put("m3", {"id": "m3"})
        follower = _standby(_Clock())
        follower.apply_ops(b.log.entries_since(0))
        assert follower.table("models").load_all() == {
            "m1": {"id": "m1"}, "m3": {"id": "m3"},
        }
        b.close()

        b2 = ReplicatedStateBackend(SQLiteBackend(db), node_id="L")
        assert b2.table("models").load_all() == {
            "m1": {"id": "m1"}, "m3": {"id": "m3"},
        }, "boot replay must not resurrect a write the caller saw fail"
        b2.close()

    def test_crash_between_append_and_commit_replays_at_boot(self, tmp_path):
        """The write-ahead contract: a genuine CRASH (process death
        after the log append, before the data commit — the caller never
        got an answer) converges by idempotent replay at boot."""
        db = str(tmp_path / "s.db")
        b = ReplicatedStateBackend(SQLiteBackend(db), node_id="L")
        b.table("models").put("m1", {"id": "m1"})
        # Simulate the torn stop: the log row is durably appended but
        # the process dies before fn() runs (no discard, no data row).
        b.log.append({
            "term": b.term, "ns": "models", "op": "put_many",
            "items": {"m2": {"id": "m2"}},
        })
        assert b.log.seq == 2
        b.close()

        b2 = ReplicatedStateBackend(SQLiteBackend(db), node_id="L")
        assert b2.table("models").load_all() == {
            "m1": {"id": "m1"}, "m2": {"id": "m2"},
        }, "boot replay must apply the logged-but-uncommitted tail"
        b2.close()

    def test_log_survives_restart_and_seq_continues(self, tmp_path):
        db = str(tmp_path / "s.db")
        b = ReplicatedStateBackend(SQLiteBackend(db), node_id="L")
        b.table("crud").put("a", {"v": 1})
        b.close()
        b2 = ReplicatedStateBackend(SQLiteBackend(db), node_id="L")
        b2.table("crud").put("b", {"v": 2})
        assert [e["seq"] for e in b2.log.entries_since(0)] == [1, 2]
        b2.close()


# ---------------------------------------------------------------------------
# Follower application, snapshot bootstrap, lag
# ---------------------------------------------------------------------------


class TestFollowerApply:
    def test_snapshot_then_incremental_tail(self):
        clock = _Clock()
        leader = _leader(clock)
        t = leader.table("models")
        t.put("m1", {"id": "m1"})
        t.put("gone", {"id": "gone"})
        t.delete("gone")

        follower = _standby(clock)
        # Standby boot-time rows (e.g. ensure_default_cluster analog)
        # that the leader deleted/never had must not survive the sync.
        with follower.applying():
            follower.table("models").put("stale", {"id": "stale"})
        follower.apply_snapshot(leader.snapshot())
        assert follower.table("models").load_all() == {"m1": {"id": "m1"}}
        assert follower.log.applied == leader.log.seq

        t.put("m2", {"id": "m2"})
        touched = follower.apply_ops(
            leader.log.entries_since(follower.log.applied)
        )
        assert touched == {"models"}
        assert follower.table("models").get("m2") == {"id": "m2"}

    def test_apply_is_idempotent_and_skips_applied_seqs(self):
        clock = _Clock()
        leader = _leader(clock)
        leader.table("models").put("m1", {"id": "m1"})
        follower = _standby(clock)
        entries = leader.log.entries_since(0)
        follower.apply_ops(entries)
        follower.apply_ops(entries)  # duplicate delivery
        assert follower.log.applied == 1
        assert follower.table("models").load_all() == {"m1": {"id": "m1"}}

    def test_replication_namespaces_never_ship_in_snapshots(self):
        clock = _Clock()
        leader = _leader(clock)
        leader.table("models").put("m1", {"id": "m1"})
        snap = leader.snapshot()
        assert "replication_log" not in snap["namespaces"]
        assert "replication_meta" not in snap["namespaces"]


# ---------------------------------------------------------------------------
# Lease, fencing, split brain
# ---------------------------------------------------------------------------


class TestLeaseAndFencing:
    def test_lease_signature_authenticates_leader_and_term(self):
        sig = sign_lease("secret", "L", 3)
        lease = {"leader_id": "L", "term": 3, "sig": sig}
        assert verify_lease("secret", lease)
        assert not verify_lease("other-secret", lease)
        assert not verify_lease("secret", dict(lease, term=4))
        assert not verify_lease("secret", dict(lease, leader_id="evil"))

    def test_standby_rejects_writes(self):
        follower = _standby(_Clock())
        with pytest.raises(NotLeaderError):
            follower.table("models").put("x", {})

    def test_expired_lease_fences_the_leader(self):
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=5.0)
        leader.table("models").put("m1", {"id": "m1"})
        clock.t = 4.0  # inside the TTL: renewal extends
        leader.renew_lease()
        clock.t = 8.0
        leader.table("models").put("m2", {"id": "m2"})
        clock.t = 10.0  # past expiry, no renewal
        with pytest.raises(NotLeaderError):
            leader.table("models").put("m3", {"id": "m3"})

    def test_renewing_an_expired_lease_steps_down_not_resurrects(self):
        """The split-brain fix: a paused/partitioned leader's LeaseKeeper
        must NOT re-extend a lease that already lapsed — past expiry a
        standby may hold term+1 and nothing pushes that term back here
        (followers pull).  Renewal past expiry demotes permanently."""
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=5.0)
        leader.table("models").put("m1", {"id": "m1"})
        clock.t = 20.0  # the pause: lease long dead
        with pytest.raises(NotLeaderError):
            leader.renew_lease()
        assert leader.role == "standby"
        with pytest.raises(NotLeaderError):
            leader.table("models").put("z", {"id": "z"})
        # No resurrection path: renewing again still refuses.
        with pytest.raises(NotLeaderError):
            leader.renew_lease()

    def test_restarted_leader_defers_to_peer_with_higher_term(self):
        """A restarted fenced leader (role=leader in its config) probes
        ha.peers at boot and joins as a standby when a successor holds a
        higher term."""
        from dragonfly2_tpu.manager.replication import probe_peer_term

        clock = _Clock()
        successor = _standby(clock, lease_ttl_s=30.0)
        successor.promote()  # term 2
        rest = _rest_for(successor)
        try:
            term, url = probe_peer_term([rest.url, "http://127.0.0.1:9"])
            assert (term, url) == (2, rest.url)
            old = _leader(clock, lease_ttl_s=30.0)  # reboots at term 1
            if term > old.term:
                old.observe_term(term)
            assert old.role == "standby"
            with pytest.raises(NotLeaderError):
                old.table("models").put("z", {"id": "z"})
        finally:
            rest.stop()

    def test_split_brain_old_leader_post_lease_write_rejected_by_term(self):
        """The acceptance split-brain fence: leader pauses past its
        lease, follower promotes with term+1 — the zombie can neither
        commit locally (lease) nor ship its history (term)."""
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=5.0)
        leader.table("models").put("m1", {"id": "m1"})
        follower = _standby(clock, lease_ttl_s=5.0)
        follower.apply_snapshot(leader.snapshot())

        clock.t = 10.0  # leader paused past lease expiry
        follower.promote()
        assert follower.role == "leader" and follower.term == 2
        follower.table("models").put("f1", {"id": "f1"})

        # Zombie's own commit gate refuses...
        with pytest.raises(NotLeaderError):
            leader.table("models").put("z", {"id": "z"})
        # ...and even a hand-shipped term-1 op is rejected by term.
        zombie_op = {
            "seq": follower.log.seq + 1, "term": 1, "ns": "models",
            "op": "put_many", "items": {"z": {"id": "z"}},
        }
        with pytest.raises(StaleTermError):
            follower.apply_ops([zombie_op])
        assert follower.table("models").get("z") is None

        # The fenced leader observing the new term demotes permanently.
        leader.observe_term(follower.term)
        assert leader.role == "standby"
        with pytest.raises(NotLeaderError):
            leader.renew_lease()

    def test_promotion_is_counted_and_roles_exported(self):
        from dragonfly2_tpu.rpc.metrics import MANAGER_ROLE

        clock = _Clock()
        follower = _standby(clock)
        before_leader = MANAGER_ROLE.value(role="leader")
        follower.promote()
        assert follower.status()["failovers"] == 1
        assert MANAGER_ROLE.value(role="leader") == 1.0 >= before_leader


# ---------------------------------------------------------------------------
# LogFollower over the real REST surface
# ---------------------------------------------------------------------------


def _rest_for(backend, registry=None):
    server = ManagerRESTServer(
        registry if registry is not None else ModelRegistry(backend=backend),
        ClusterManager(),
        state_backend=backend,
        ha=backend,
    )
    server.serve()
    return server


class TestFollowerOverREST:
    def test_tail_apply_health_and_lag(self):
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=30.0)
        registry = ModelRegistry(KVBlobStore(leader), backend=leader)
        rest = _rest_for(leader, registry)
        follower_backend = _standby(clock, lease_ttl_s=30.0)
        follower = LogFollower(
            follower_backend, rest.url, clock=clock, poll_interval_s=0.05
        )
        try:
            registry.create_model(
                name="m", type="mlp", scheduler_id="s", artifact=b"\x01" * 8,
            )
            follower.poll_once()
            health = follower.health()
            assert health["applied_seq"] == health["leader_seq"] > 0
            assert health["lag_seconds"] == 0.0
            assert not follower.promoted
            # The replicated registry row AND its blob row arrived.
            reloaded = ModelRegistry(
                KVBlobStore(follower_backend), backend=follower_backend
            )
            m = reloaded.list(scheduler_id="s", name="m")[0]
            assert reloaded.load_artifact(m) == b"\x01" * 8
        finally:
            rest.stop()

    def test_replication_routes_and_standby_503(self):
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=30.0)
        rest = _rest_for(leader)
        try:
            with urllib.request.urlopen(
                rest.url + "/api/v1/replication:status", timeout=5
            ) as r:
                status = json.loads(r.read())
            assert status["role"] == "leader"
            assert verify_lease(leader.lease_secret, status["lease"])
        finally:
            rest.stop()

        standby = _standby(clock)
        rest2 = _rest_for(standby)
        try:
            # Reads answer; writes 503 with Retry-After.
            with urllib.request.urlopen(
                rest2.url + "/api/v1/healthy", timeout=5
            ) as r:
                assert json.loads(r.read())["role"] == "standby"
            req = urllib.request.Request(
                rest2.url + "/api/v1/models",
                data=json.dumps({"name": "m"}).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5)
            assert err.value.code == 503
            assert err.value.headers.get("Retry-After") == "1"
        finally:
            rest2.stop()

    def test_lease_expiry_takeover_over_the_wire(self):
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=4.0)
        rest = _rest_for(leader)
        follower_backend = _standby(clock, lease_ttl_s=4.0)
        promoted = []
        follower = LogFollower(
            follower_backend, rest.url, clock=clock,
            on_promote=lambda: promoted.append(True),
        )
        try:
            leader.table("models").put("m1", {"id": "m1"})
            follower.poll_once()  # fresh lease observed
            assert follower.health()["lease_remaining_s"] > 0
        finally:
            rest.stop()  # the leader dies
        # Lease still fresh: no premature takeover.
        follower.poll_once()
        assert not follower.promoted
        # Lease ages out (+ grace) with the leader unreachable → promote.
        clock.t = 20.0
        follower.poll_once()
        assert follower.promoted and promoted == [True]
        assert follower_backend.role == "leader"
        assert follower_backend.term == 2
        assert follower_backend.table("models").get("m1") == {"id": "m1"}
        follower_backend.table("models").put("m2", {"id": "m2"})


# ---------------------------------------------------------------------------
# Replication-fetch auth: the data routes demand the shared secret
# ---------------------------------------------------------------------------


class TestReplicationAuth:
    def test_log_and_snapshot_refuse_unauthenticated_fetches(self):
        """The :log/:snapshot routes carry every namespace — users/PATs
        credential rows included on default deployments — so a fetch
        without proof of the lease_secret must 403, not dump state."""
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=30.0)
        leader.table("users").put("root", {"password_hash": "h", "salt": "s"})
        rest = _rest_for(leader)
        try:
            for route in ("replication:snapshot", "replication:log"):
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(
                        f"{rest.url}/api/v1/{route}", timeout=5
                    )
                assert err.value.code == 403
                # A token signed with the WRONG secret fails too.
                req = urllib.request.Request(
                    f"{rest.url}/api/v1/{route}",
                    headers={REPLICATION_AUTH_HEADER: sign_replication_request(
                        "not-the-secret", f"/api/v1/{route}"
                    )},
                )
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(req, timeout=5)
                assert err.value.code == 403
        finally:
            rest.stop()

    def test_secret_holder_fetches_and_follower_sends_the_header(self):
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=30.0)
        leader.table("crud").put("m1", {"id": "m1"})
        rest = _rest_for(leader)
        try:
            path = "/api/v1/replication:log"
            req = urllib.request.Request(
                rest.url + path + "?from_seq=0",
                headers={REPLICATION_AUTH_HEADER: sign_replication_request(
                    leader.lease_secret, path
                )},
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                payload = json.loads(r.read())
            assert [e["seq"] for e in payload["entries"]] == [1]
            # The LogFollower authenticates transparently (shared secret).
            follower = _standby(clock, lease_ttl_s=30.0)
            LogFollower(follower, rest.url, clock=clock).poll_once()
            assert follower.table("crud").get("m1") == {"id": "m1"}
        finally:
            rest.stop()

    def test_ha_config_refuses_the_default_lease_secret(self):
        from dragonfly2_tpu.config.schema import (
            DEFAULT_LEASE_SECRET,
            ConfigError,
            HASection,
        )

        # The schema default and the backend constructor default are the
        # same placeholder (kept in sync by hand across the layers).
        import inspect

        sig = inspect.signature(ReplicatedStateBackend.__init__)
        assert sig.parameters["lease_secret"].default == DEFAULT_LEASE_SECRET

        HASection().validate()  # HA off: the placeholder is fine
        with pytest.raises(ConfigError):
            HASection(enable=True).validate()
        with pytest.raises(ConfigError):
            HASection(replicate_from="http://leader:1").validate()
        with pytest.raises(ConfigError):
            HASection(enable=True, lease_secret="short").validate()
        HASection(enable=True, lease_secret="x" * 16).validate()


# ---------------------------------------------------------------------------
# Log compaction: bounded growth, snapshot fallback past the floor
# ---------------------------------------------------------------------------


class TestLogCompaction:
    def test_leader_truncates_below_the_retention_window(self):
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=3600.0)
        leader.COMPACT_EVERY = 8
        leader.RETAIN_OPS = 8
        t = leader.table("models")
        for i in range(40):
            t.put(f"m{i}", {"id": f"m{i}"})
        entries = leader.log.entries_since(0)
        assert len(entries) <= 8 + leader.COMPACT_EVERY
        assert leader.log.floor > 1
        assert entries[0]["seq"] == leader.log.floor
        # Data state is complete regardless of what the log retains.
        assert len(t.load_all()) == 40

    def test_truncation_never_eats_the_unapplied_tail(self, tmp_path):
        db = str(tmp_path / "s.db")
        b = ReplicatedStateBackend(SQLiteBackend(db), node_id="L")
        b.table("models").put("m1", {"id": "m1"})
        # A crash-pending entry (appended, data commit never ran)...
        b.log.append({
            "term": b.term, "ns": "models", "op": "put_many",
            "items": {"m2": {"id": "m2"}},
        })
        # ...survives any truncation request, however aggressive.
        b.log.truncate_below(10_000)
        assert [e["seq"] for e in b.log.pending()] == [2]
        b.close()
        b2 = ReplicatedStateBackend(SQLiteBackend(db), node_id="L")
        assert b2.table("models").get("m2") == {"id": "m2"}
        b2.close()

    def test_sqlite_range_scan_matches_the_base_filter(self, tmp_path):
        sql = SQLiteBackend(str(tmp_path / "s.db")).table("ns")
        mem = MemoryBackend().table("ns")
        for t in (sql, mem):
            for i in range(10):
                t.put(f"{i:020d}", {"i": i})
        assert sql.load_range(f"{4:020d}") == mem.load_range(f"{4:020d}")
        sql.delete_range(f"{3:020d}")
        mem.delete_range(f"{3:020d}")
        assert sql.load_all() == mem.load_all()
        assert sorted(sql.load_all()) == [f"{i:020d}" for i in range(3, 10)]

    def test_follower_behind_the_floor_rebootstraps_via_snapshot(self):
        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=3600.0)
        leader.COMPACT_EVERY = 4
        leader.RETAIN_OPS = 4
        rest = _rest_for(leader)
        follower_backend = _standby(clock, lease_ttl_s=3600.0)
        follower = LogFollower(follower_backend, rest.url, clock=clock)
        try:
            leader.table("crud").put("m0", {"id": "m0"})
            follower.poll_once()  # bootstrapped + caught up
            assert follower_backend.log.applied == leader.log.seq
            # The leader races far ahead; compaction truncates past the
            # follower's watermark.
            for i in range(1, 30):
                leader.table("crud").put(f"m{i}", {"id": f"m{i}"})
            assert leader.log.floor > follower_backend.log.applied + 1
            follower.poll_once()
            assert follower_backend.log.applied == leader.log.seq
            assert len(follower_backend.table("crud").load_all()) == 30
            assert follower.lag_seconds() == 0.0
        finally:
            rest.stop()


# ---------------------------------------------------------------------------
# Client failover: ManagerEndpoints
# ---------------------------------------------------------------------------


def _http_503():
    import io

    return urllib.error.HTTPError(
        "http://x", 503, "standby", {}, io.BytesIO(b"{}")
    )


class TestManagerEndpoints:
    def test_parses_comma_spec_and_is_sticky(self):
        eps = ManagerEndpoints("http://a:1, http://b:2")
        assert eps.all() == ["http://a:1", "http://b:2"]
        calls = []

        def fn(base):
            calls.append(base)
            if base == "http://a:1":
                raise ConnectionError("down")
            return "ok"

        assert eps.call(fn) == "ok"
        assert calls == ["http://a:1", "http://b:2"]
        # Sticky: the next call goes straight to the survivor.
        assert eps.call(fn) == "ok"
        assert calls[-1] == "http://b:2"

    def test_503_fails_over_but_404_propagates(self):
        eps = ManagerEndpoints(["http://a:1", "http://b:2"])
        seen = []

        def standby_then_ok(base):
            seen.append(base)
            if base == "http://a:1":
                raise _http_503()
            return "leader"

        assert eps.call(standby_then_ok) == "leader"
        assert seen == ["http://a:1", "http://b:2"]

        import io

        def not_found(base):
            raise urllib.error.HTTPError(
                base, 404, "nope", {}, io.BytesIO(b"{}")
            )

        with pytest.raises(urllib.error.HTTPError):
            eps.call(not_found)

    def test_all_down_raises_last_error_and_counts_failovers(self):
        from dragonfly2_tpu.rpc.metrics import (
            MANAGER_ENDPOINT_FAILOVERS_TOTAL,
        )

        eps = ManagerEndpoints("http://a:1,http://b:2", client="t-all-down")
        before = MANAGER_ENDPOINT_FAILOVERS_TOTAL.value(client="t-all-down")

        def dead(base):
            raise ConnectionError(base)

        with pytest.raises(ConnectionError):
            eps.call(dead)
        after = MANAGER_ENDPOINT_FAILOVERS_TOTAL.value(client="t-all-down")
        assert after == before + 2  # one rotation per dead endpoint

    def test_shared_instance_moves_every_client(self):
        """The cli/scheduler wiring claim: one resolver instance shared
        by two clients — the first failover moves both."""
        from dragonfly2_tpu.jobs.remote import RemoteJobClient
        from dragonfly2_tpu.rollout.client import RolloutRESTClient

        eps = ManagerEndpoints("http://a:1,http://b:2")
        jobs = RemoteJobClient(eps)
        rollout = RolloutRESTClient(eps)
        assert jobs.endpoints is rollout.endpoints is eps
        eps.failover("http://a:1")
        assert jobs.base == rollout.base_url == "http://b:2"


# ---------------------------------------------------------------------------
# Jittered backoff (satellite): spread + reproducibility
# ---------------------------------------------------------------------------


class TestDecorrelatedJitterBackoff:
    def test_seeded_schedule_is_reproducible(self):
        a = DecorrelatedJitterBackoff(base=1.0, cap=30.0, rng=random.Random(7))
        b = DecorrelatedJitterBackoff(base=1.0, cap=30.0, rng=random.Random(7))
        assert [a.next() for _ in range(8)] == [b.next() for _ in range(8)]

    def test_spread_grows_decorrelated_and_capped(self):
        bo = DecorrelatedJitterBackoff(base=1.0, cap=10.0, rng=random.Random(3))
        seq = [bo.next() for _ in range(64)]
        assert all(1.0 <= v <= 10.0 for v in seq)
        assert len({round(v, 6) for v in seq}) > 32, "no spread = herd"
        # Two differently-seeded fleets do NOT synchronize.
        other = DecorrelatedJitterBackoff(
            base=1.0, cap=10.0, rng=random.Random(4)
        )
        assert [other.next() for _ in range(8)] != seq[:8]

    def test_reset_returns_to_base_envelope(self):
        bo = DecorrelatedJitterBackoff(base=1.0, cap=60.0, rng=random.Random(5))
        for _ in range(10):
            bo.next()
        bo.reset()
        assert bo.next() <= 3.0  # uniform(base, base*3)

    def test_cluster_client_and_dynconfig_take_seeded_rngs(self):
        from dragonfly2_tpu.manager.dynconfig import Dynconfig
        from dragonfly2_tpu.rpc.cluster_client import RemoteClusterClient

        c1 = RemoteClusterClient(
            "http://m:1", backoff_rng=random.Random(11),
            keepalive_interval_s=20.0,
        )
        c2 = RemoteClusterClient(
            "http://m:1", backoff_rng=random.Random(11),
            keepalive_interval_s=20.0,
        )
        assert [c1._backoff.next() for _ in range(5)] == [
            c2._backoff.next() for _ in range(5)
        ]

        def failing():
            raise ConnectionError("manager down")

        d1 = Dynconfig(failing, refresh_interval=60.0,
                       backoff_rng=random.Random(12))
        d2 = Dynconfig(failing, refresh_interval=60.0,
                       backoff_rng=random.Random(12))
        assert d1.refresh() is False and d1.last_refresh_ok is False
        assert d2.refresh() is False
        assert [d1._backoff.next() for _ in range(5)] == [
            d2._backoff.next() for _ in range(5)
        ]


# ---------------------------------------------------------------------------
# SQLite hardening (satellite)
# ---------------------------------------------------------------------------


class TestSQLiteHardening:
    def test_busy_timeout_and_wal_set_at_open(self, tmp_path):
        b = SQLiteBackend(str(tmp_path / "s.db"))
        assert b._conn.execute("PRAGMA busy_timeout").fetchone()[0] == 5000
        assert (
            b._conn.execute("PRAGMA journal_mode").fetchone()[0].lower()
            == "wal"
        )
        b.close()

    def test_close_is_idempotent(self, tmp_path):
        b = SQLiteBackend(str(tmp_path / "s.db"))
        b.close()
        b.close()  # second close: no "closed database" explosion

    def test_migration_commits_all_namespaces_in_one_transaction(
        self, tmp_path
    ):
        """Crash mid-migration → NOTHING imported (the idempotency check
        re-imports next boot); never a half-migrated backend."""
        import sqlite3

        from dragonfly2_tpu.manager.state import migrate_legacy_sqlite

        models_db = str(tmp_path / "manager.db")
        conn = sqlite3.connect(models_db)
        conn.execute(
            "CREATE TABLE models (id TEXT PRIMARY KEY, name TEXT, type TEXT,"
            " version INTEGER, scheduler_id TEXT, state TEXT, evaluation "
            "TEXT, blob_key TEXT, created_at REAL, updated_at REAL)"
        )
        conn.execute(
            "INSERT INTO models VALUES ('m1','r','mlp',1,'s','active',"
            "'{}','b',1.0,2.0)"
        )
        conn.commit(); conn.close()
        crud_db = str(tmp_path / "crud.db")
        conn = sqlite3.connect(crud_db)
        conn.execute(
            "CREATE TABLE crud_rows (kind TEXT, id TEXT, value TEXT, "
            "PRIMARY KEY (kind, id))"
        )
        conn.execute(
            "INSERT INTO crud_rows VALUES ('application','a1','{\"id\": "
            "\"a1\"}')"
        )
        conn.commit(); conn.close()

        backend = SQLiteBackend(str(tmp_path / "state.db"))
        # Drop at the second namespace's seam: with per-namespace
        # transactions this would leave models imported and crud not.
        inj = faultinject.FaultInjector([
            faultinject.FaultSpec(site="state.put.crud", kind="drop", at=(0,)),
        ])
        with faultinject.installed(inj):
            with pytest.raises(ConnectionError):
                migrate_legacy_sqlite(
                    backend, models_db=models_db, crud_db=crud_db
                )
        assert backend.table("models").load_all() == {}, (
            "partial migration committed — the one-transaction contract "
            "is torn"
        )
        assert backend.table("crud").load_all() == {}
        # Next boot: full import succeeds and is idempotent.
        counts = migrate_legacy_sqlite(
            backend, models_db=models_db, crud_db=crud_db
        )
        assert counts == {"models": 1, "crud": 1}
        assert migrate_legacy_sqlite(
            backend, models_db=models_db, crud_db=crud_db
        ) == {}
        backend.close()


# ---------------------------------------------------------------------------
# Circuit-breaker visibility (satellite)
# ---------------------------------------------------------------------------


class TestBreakerVisibility:
    def test_state_gauge_tracks_transitions(self):
        from dragonfly2_tpu.rpc.metrics import CIRCUIT_BREAKER_STATE

        clock = _Clock()
        br = CircuitBreaker(
            failure_threshold=2, reset_timeout_s=1.0, clock=clock,
            name="parent-9",
        )
        assert CIRCUIT_BREAKER_STATE.value(target="parent-9") == 0.0
        br.record_failure()
        br.record_failure()
        assert CIRCUIT_BREAKER_STATE.value(target="parent-9") == 2.0
        clock.t = 2.0
        assert br.allow()  # open -> half_open probe
        assert CIRCUIT_BREAKER_STATE.value(target="parent-9") == 1.0
        br.record_success()
        assert CIRCUIT_BREAKER_STATE.value(target="parent-9") == 0.0

    def test_transitions_log_once_not_per_call(self, caplog):
        clock = _Clock()
        br = CircuitBreaker(
            failure_threshold=1, reset_timeout_s=10.0, clock=clock,
            name="parent-log",
        )
        with caplog.at_level(logging.INFO, logger="dragonfly2_tpu.rpc.retry"):
            br.record_failure()          # closed -> open: ONE warning
            for _ in range(50):
                br.record_failure()      # still open: silent
                br.allow()               # still open: silent
        opens = [
            r for r in caplog.records if "parent-log" in r.getMessage()
        ]
        assert len(opens) == 1 and opens[0].levelno == logging.WARNING

    def test_unnamed_breaker_stays_silent(self, caplog):
        br = CircuitBreaker(failure_threshold=1)
        with caplog.at_level(logging.INFO, logger="dragonfly2_tpu.rpc.retry"):
            br.record_failure()
        assert not caplog.records


# ---------------------------------------------------------------------------
# Metrics schema (satellite)
# ---------------------------------------------------------------------------


class TestMetricsSchema:
    def test_ha_metric_names_and_labels(self):
        from dragonfly2_tpu.rpc import metrics as m

        assert m.MANAGER_ROLE.name == "manager_role"
        assert m.MANAGER_ROLE.label_names == ("role",)
        assert m.REPLICATION_LAG.name == "manager_replication_lag_seconds"
        assert m.REPLICATION_LAG.label_names == ()
        assert m.MANAGER_FAILOVERS_TOTAL.name == "manager_failovers_total"
        assert m.MANAGER_FAILOVERS_TOTAL.label_names == ("node",)
        assert (
            m.MANAGER_ENDPOINT_FAILOVERS_TOTAL.name
            == "manager_endpoint_failovers_total"
        )
        assert m.MANAGER_ENDPOINT_FAILOVERS_TOTAL.label_names == ("client",)
        assert m.CIRCUIT_BREAKER_STATE.name == "rpc_circuit_breaker_state"
        assert m.CIRCUIT_BREAKER_STATE.label_names == ("target",)

    def test_exposition_renders_the_ha_plane(self):
        from dragonfly2_tpu.rpc import metrics as m
        from dragonfly2_tpu.utils.metrics import default_registry

        m.MANAGER_ROLE.set(1.0, role="leader")
        m.REPLICATION_LAG.set(0.25)
        m.MANAGER_FAILOVERS_TOTAL.inc(node="mgr-test")
        text = default_registry.expose_text()
        assert 'manager_role{role="leader"} 1.0' in text
        assert "manager_replication_lag_seconds 0.25" in text
        assert 'manager_failovers_total{node="mgr-test"}' in text


# ---------------------------------------------------------------------------
# Zero-pinning subscriber failover (in-process half of the drill)
# ---------------------------------------------------------------------------


class TestSubscriberFailover:
    def test_model_poll_fails_over_with_zero_pinning(self):
        """Leader dies, standby serves reads: the subscriber's poll
        sweeps the endpoint list inside the client and NEVER engages the
        PR-4 pin."""
        from dragonfly2_tpu.records.features import DOWNLOAD_FEATURE_DIM
        from dragonfly2_tpu.rpc.registry_client import RemoteRegistry
        from dragonfly2_tpu.scheduler import MLEvaluator, ModelSubscriber
        from dragonfly2_tpu.trainer.export import MLPScorer, scorer_to_bytes

        rng = np.random.default_rng(0)
        weights = [(
            rng.standard_normal(
                (DOWNLOAD_FEATURE_DIM, 1)
            ).astype(np.float32),
            np.zeros(1, dtype=np.float32),
        )]
        artifact = scorer_to_bytes(MLPScorer(weights=weights))

        clock = _Clock()
        leader = _leader(clock, lease_ttl_s=60.0)
        registry = ModelRegistry(KVBlobStore(leader), backend=leader)
        rest = _rest_for(leader, registry)
        model = registry.create_model(
            name="parent-bandwidth-mlp", type="mlp", scheduler_id="s1",
            artifact=artifact,
        )
        registry.activate(model.id)

        follower_backend = _standby(clock, lease_ttl_s=60.0)
        follower = LogFollower(follower_backend, rest.url, clock=clock)
        follower.poll_once()
        standby_registry = ModelRegistry(
            KVBlobStore(follower_backend), backend=follower_backend
        )
        standby_rest = ManagerRESTServer(
            standby_registry, ClusterManager(),
            state_backend=follower_backend, ha=follower_backend,
        )
        standby_rest.serve()

        remote = RemoteRegistry(f"{rest.url},{standby_rest.url}")
        subscriber = ModelSubscriber(
            remote, MLEvaluator(), scheduler_id="s1",
        )
        try:
            assert subscriber.refresh() is True  # served by the leader
            assert subscriber.pinned is False
            rest.stop()  # leader dies; standby keeps answering reads
            assert subscriber.refresh() is False  # same version, no swap
            assert subscriber.pinned is False, (
                "poll pinned despite a live standby — failover is broken"
            )
            assert remote.base_url == standby_rest.url
        finally:
            standby_rest.stop()


# ---------------------------------------------------------------------------
# bench_report standby note (satellite)
# ---------------------------------------------------------------------------


class TestBenchReportStandbyNote:
    def test_standby_round_gets_a_note_row(self):
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
        from tools.bench_report import _row_of

        row = _row_of({
            "rc": 0, "round": 7,
            "parsed": {"value": 1.0, "unit": "rec/s", "standby": True},
        })
        assert "standby" in row["note"]
        row2 = _row_of({
            "rc": 0, "round": 8, "note": "smoke",
            "parsed": {"value": 1.0, "unit": "rec/s"},
        })
        assert "standby" not in row2["note"]
