"""Tier-1 invariant gate: dflint over every dragonfly2_tpu source file,
one parametrized test per file so a regression names the file that
broke.  A finding here means a project invariant was violated —
exception swallowing (DF001), thread hygiene (DF002), JAX trace purity
(DF003), a fault seam deleted (DF004), a leaked fd (DF005), or deadline
propagation dropped in rpc/ (DF006).

Accepted pre-existing findings live in tools/dflint/baseline.toml;
reviewed contract-true silences carry `# dflint: disable=DFxxx`
pragmas inline.  Everything else fails.
"""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:  # `python -m pytest` from elsewhere
    sys.path.insert(0, str(REPO))

from tools.dflint.baseline import Baseline  # noqa: E402
from tools.dflint.core import collect_files, load_module, run_checkers  # noqa: E402

SOURCE_FILES = collect_files([REPO / "dragonfly2_tpu"], REPO)
BASELINE = Baseline.load()


@pytest.mark.parametrize(
    "path",
    SOURCE_FILES,
    ids=[p.resolve().relative_to(REPO).as_posix() for p in SOURCE_FILES],
)
def test_dflint_clean(path):
    module = load_module(path, REPO)
    new, _accepted = BASELINE.split(run_checkers(module))
    assert not new, "dflint findings:\n" + "\n".join(f.render() for f in new)


def test_no_stale_baseline_entries():
    """Fixed violations must leave the baseline too, or the budget
    silently covers the NEXT regression in that function."""
    findings = []
    for path in SOURCE_FILES:
        findings.extend(run_checkers(load_module(path, REPO)))
    assert BASELINE.stale_keys(findings) == []
