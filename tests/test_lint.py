"""Tier-1 invariant gate: dflint over every dragonfly2_tpu source file,
one parametrized test per file so a regression names the file that
broke.  A finding here means a project invariant was violated —
exception swallowing (DF001), thread hygiene (DF002), JAX trace purity
(DF003), a fault seam deleted (DF004), a leaked fd (DF005), deadline
propagation dropped in rpc/ (DF006), hot-path hygiene (DF007) — or a
whole-program invariant broke: an indefinitely-blocking operation now
runs under a mutex (DF008), the global lock-ordering graph grew a
deadlock-capable cycle (DF009), a jit is constructed per call or a
traced def branches on a non-static arg (DF010), a host-device sync
leaked into a hot path or trace-reachable function (DF011), a
columnar dtype contract drifted from records/contracts.py (DF012), a
state machine gained an illegal transition or mirror write (DF013), a
persistence site lost its crash-consistency discipline — torn
multi-row flip, unlocked write, orphan table, dangling foreign key
(DF014), or the RPC client/server/transport method inventories
drifted apart (DF015).

The per-file checkers see one AST; DF008-DF015 come from ONE
whole-program analysis (tools/dflint/program.py +
tools/dflint/tracerules.py + tools/dflint/staterules.py) built here
once and attributed back to files, so the failing test still names
the file.

Accepted pre-existing findings live in tools/dflint/baseline.toml
(currently EMPTY — the fix sweep shipped with the rules); reviewed
contract-true silences carry `# dflint: disable=DFxxx` pragmas inline.
Everything else fails.
"""

from __future__ import annotations

import sys
from collections import defaultdict
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:  # `python -m pytest` from elsewhere
    sys.path.insert(0, str(REPO))

from tools.dflint.baseline import Baseline  # noqa: E402
from tools.dflint.core import collect_files, load_module, run_checkers  # noqa: E402
from tools.dflint.program import Program  # noqa: E402
from tools.dflint.staterules import StateAnalysis  # noqa: E402
from tools.dflint.tracerules import TraceAnalysis  # noqa: E402

SOURCE_FILES = collect_files([REPO / "dragonfly2_tpu"], REPO)
BASELINE = Baseline.load()

# Whole-tree view shared with test_dflint's session cache (read-only);
# per-file checker runs are memoized so the staleness sweep below reuses
# the parametrized tests' work instead of re-parsing the tree.
from tests.test_dflint import _df_tree_program  # noqa: E402

_PROGRAM = _df_tree_program()
_TRACE = TraceAnalysis(_PROGRAM, REPO)
_STATE = StateAnalysis(_PROGRAM, REPO)
_PROGRAM_BY_PATH = defaultdict(list)
for _f in _PROGRAM.findings() + _TRACE.findings() + _STATE.findings():
    _PROGRAM_BY_PATH[_f.path].append(_f)

_CHECKED = {}


def _per_file_findings(path):
    if path not in _CHECKED:
        module = load_module(path, REPO)
        _CHECKED[path] = (module.relpath, run_checkers(module))
    return _CHECKED[path]


@pytest.mark.parametrize(
    "path",
    SOURCE_FILES,
    ids=[p.resolve().relative_to(REPO).as_posix() for p in SOURCE_FILES],
)
def test_dflint_clean(path):
    relpath, checked = _per_file_findings(path)
    findings = list(checked)
    findings.extend(_PROGRAM_BY_PATH.get(relpath, []))
    new, _accepted = BASELINE.split(findings)
    assert not new, "dflint findings:\n" + "\n".join(f.render() for f in new)


def test_no_stale_baseline_entries():
    """Fixed violations must leave the baseline too, or the budget
    silently covers the NEXT regression in that function."""
    findings = (
        list(_PROGRAM.findings()) + list(_TRACE.findings())
        + list(_STATE.findings())
    )
    for path in SOURCE_FILES:
        findings.extend(_per_file_findings(path)[1])
    assert BASELINE.stale_keys(findings) == []
