"""Security: CA issuance + chain validation, mTLS piece transfer, tokens
and REST RBAC enforcement."""

import json
import ssl
import urllib.error
import urllib.request

import pytest

from dragonfly2_tpu.security import (
    CertificateAuthority,
    PeerIdentity,
    Role,
    TokenIssuer,
    TokenVerifier,
    client_context,
    server_context,
)


class TestCA:
    def test_issue_and_chain_validates(self, tmp_path):
        ca = CertificateAuthority()
        ident = PeerIdentity.issue(
            ca, common_name="daemon-1", hostnames=["daemon-1"], ips=["127.0.0.1"]
        )
        from cryptography import x509
        from cryptography.hazmat.primitives.asymmetric import ec

        cert = x509.load_pem_x509_certificate(ident.cert_pem)
        ca_cert = x509.load_pem_x509_certificate(ident.ca_pem)
        # Signed by the CA (signature verification against the CA key).
        ca_cert.public_key().verify(
            cert.signature,
            cert.tbs_certificate_bytes,
            ec.ECDSA(cert.signature_hash_algorithm),
        )
        san = cert.extensions.get_extension_for_class(x509.SubjectAlternativeName)
        assert "daemon-1" in san.value.get_values_for_type(x509.DNSName)
        paths = ident.write(str(tmp_path / "id"))
        assert set(paths) == {"key", "cert", "ca"}

    def test_bad_csr_rejected(self):
        ca = CertificateAuthority()
        with pytest.raises(Exception):
            ca.sign_csr(b"-----BEGIN CERTIFICATE REQUEST-----\nnope\n-----END CERTIFICATE REQUEST-----\n")


class TestMTLSPieceTransfer:
    def test_mutual_tls_roundtrip_and_reject_anonymous(self, tmp_path):
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.rpc import PieceHTTPServer

        ca = CertificateAuthority()
        server_id = PeerIdentity.issue(
            ca, common_name="parent", hostnames=["localhost"], ips=["127.0.0.1"]
        )
        client_id = PeerIdentity.issue(ca, common_name="child")

        st = DaemonStorage(str(tmp_path / "s"), prefer_native=False)
        st.register_task("t", piece_size=1024, content_length=1024)
        st.write_piece("t", 0, b"secret" * 100)
        server = PieceHTTPServer(
            UploadManager(st), ssl_context=server_context(server_id)
        )
        server.serve()
        try:
            url = f"https://127.0.0.1:{server.port}/pieces/t/0"
            ctx = client_context(client_id)
            ctx.check_hostname = False  # IP connect in test
            with urllib.request.urlopen(url, context=ctx, timeout=5) as resp:
                assert resp.read() == b"secret" * 100

            # Anonymous client (no cert) must be rejected by mTLS.
            anon = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            anon.check_hostname = False
            anon.verify_mode = ssl.CERT_NONE
            with pytest.raises((urllib.error.URLError, ssl.SSLError, ConnectionError, OSError)):
                urllib.request.urlopen(url, context=anon, timeout=5).read()
        finally:
            server.stop()


class TestTokens:
    def test_roundtrip_roles_expiry(self):
        issuer = TokenIssuer(b"super-secret-key-0123456789")
        verifier = TokenVerifier(b"super-secret-key-0123456789")
        tok = issuer.issue("daemon-1", Role.PEER)
        claims = verifier.verify(tok)
        assert claims.subject == "daemon-1" and claims.role is Role.PEER
        assert verifier.authorize(tok, Role.PEER) is not None
        assert verifier.authorize(tok, Role.OPERATOR) is None  # insufficient
        # Tampered token fails.
        assert verifier.verify(tok[:-4] + "AAAA") is None
        # Wrong secret fails.
        assert TokenVerifier(b"another-secret-key-xxxxxxxx").verify(tok) is None
        # Expired token fails.
        old = issuer.issue("x", Role.ADMIN, ttl_s=-1)
        assert verifier.verify(old) is None

    def test_weak_secret_rejected(self):
        with pytest.raises(ValueError):
            TokenIssuer(b"short")


class TestRESTAuth:
    def test_mutations_require_operator(self):
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        secret = b"manager-secret-0123456789abcd"
        registry = ModelRegistry()
        m = registry.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"a")
        server = ManagerRESTServer(
            registry, ClusterManager(), token_verifier=TokenVerifier(secret)
        )
        server.serve()
        try:
            url = server.url + f"/api/v1/models/{m.id}:activate"
            # No token → 401.
            req = urllib.request.Request(url, data=b"", method="POST")
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 401
            # PEER-role token → still 401 for activation.
            issuer = TokenIssuer(secret)
            peer_tok = issuer.issue("d", Role.PEER)
            req = urllib.request.Request(
                url, data=b"", method="POST",
                headers={"Authorization": f"Bearer {peer_tok}"},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 401
            # OPERATOR token → activation succeeds.
            op_tok = issuer.issue("ops", Role.OPERATOR)
            req = urllib.request.Request(
                url, data=b"", method="POST",
                headers={"Authorization": f"Bearer {op_tok}"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert json.loads(resp.read())["state"] == "active"
            # Reads stay open.
            with urllib.request.urlopen(server.url + "/api/v1/models", timeout=5) as r:
                assert json.loads(r.read())
        finally:
            server.stop()


class TestClientSideWiring:
    def test_mtls_piece_fetcher_end_to_end(self, tmp_path):
        """The framework's own fetcher (not hand-rolled urllib) fetches
        through mutual TLS."""
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.rpc import HTTPPieceFetcher, PieceHTTPServer

        ca = CertificateAuthority()
        server_id = PeerIdentity.issue(ca, common_name="p", ips=["127.0.0.1"])
        client_id = PeerIdentity.issue(ca, common_name="c")

        st = DaemonStorage(str(tmp_path / "s"), prefer_native=False)
        st.register_task("t", piece_size=512, content_length=1024)
        st.write_piece("t", 0, b"a" * 512)
        st.write_piece("t", 1, b"b" * 512)
        server = PieceHTTPServer(UploadManager(st), ssl_context=server_context(server_id))
        server.serve()
        try:
            assert server._svc.url.startswith("https://")
            ctx = client_context(client_id)
            ctx.check_hostname = False
            fetcher = HTTPPieceFetcher(
                lambda hid: ("127.0.0.1", server.port), ssl_context=ctx
            )
            assert fetcher.fetch("p", "t", 0) == b"a" * 512
            assert list(fetcher.piece_bitmap("p", "t")) == [1, 1]
        finally:
            server.stop()

    def test_remote_registry_with_token(self):
        """RemoteRegistry authenticates against an RBAC-enabled manager:
        PEER token creates models (the trainer's flow), OPERATOR activates."""
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer
        from dragonfly2_tpu.rpc import RemoteRegistry

        secret = b"manager-secret-0123456789abcd"
        issuer = TokenIssuer(secret)
        server = ManagerRESTServer(
            ModelRegistry(), ClusterManager(), token_verifier=TokenVerifier(secret)
        )
        server.serve()
        try:
            # Trainer-side client with a PEER token can create…
            peer_reg = RemoteRegistry(server.url, token=issuer.issue("trainer", Role.PEER))
            m = peer_reg.create_model(
                name="m", type="mlp", scheduler_id="s", artifact=b"w"
            )
            # …but not activate.
            with pytest.raises(RuntimeError):
                peer_reg.activate(m.id)
            # No token at all → refused.
            anon = RemoteRegistry(server.url)
            with pytest.raises(RuntimeError):
                anon.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"")
            # Operator client activates; artifact pull (a read) works.
            op_reg = RemoteRegistry(server.url, token=issuer.issue("ops", Role.OPERATOR))
            assert op_reg.activate(m.id).state.value == "active"
            assert op_reg.load_artifact(m) == b"w"
        finally:
            server.stop()
