"""Security: CA issuance + chain validation, mTLS piece transfer, tokens
and REST RBAC enforcement."""

import json
import ssl
import urllib.error
import urllib.request

import pytest

from dragonfly2_tpu.security import (
    CertificateAuthority,
    PeerIdentity,
    Role,
    TokenIssuer,
    TokenVerifier,
    client_context,
    server_context,
)

# The CA/mTLS surface is gated off when `cryptography` is absent
# (security/__init__.py exports None); token auth below still runs.
requires_crypto = pytest.mark.skipif(
    CertificateAuthority is None, reason="`cryptography` not installed"
)


@requires_crypto
class TestCA:
    def test_issue_and_chain_validates(self, tmp_path):
        ca = CertificateAuthority()
        ident = PeerIdentity.issue(
            ca, common_name="daemon-1", hostnames=["daemon-1"], ips=["127.0.0.1"]
        )
        from cryptography import x509
        from cryptography.hazmat.primitives.asymmetric import ec

        cert = x509.load_pem_x509_certificate(ident.cert_pem)
        ca_cert = x509.load_pem_x509_certificate(ident.ca_pem)
        # Signed by the CA (signature verification against the CA key).
        ca_cert.public_key().verify(
            cert.signature,
            cert.tbs_certificate_bytes,
            ec.ECDSA(cert.signature_hash_algorithm),
        )
        san = cert.extensions.get_extension_for_class(x509.SubjectAlternativeName)
        assert "daemon-1" in san.value.get_values_for_type(x509.DNSName)
        paths = ident.write(str(tmp_path / "id"))
        assert set(paths) == {"key", "cert", "ca"}

    def test_bad_csr_rejected(self):
        ca = CertificateAuthority()
        with pytest.raises(Exception):
            ca.sign_csr(b"-----BEGIN CERTIFICATE REQUEST-----\nnope\n-----END CERTIFICATE REQUEST-----\n")


@requires_crypto
class TestMTLSPieceTransfer:
    def test_mutual_tls_roundtrip_and_reject_anonymous(self, tmp_path):
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.rpc import PieceHTTPServer

        ca = CertificateAuthority()
        server_id = PeerIdentity.issue(
            ca, common_name="parent", hostnames=["localhost"], ips=["127.0.0.1"]
        )
        client_id = PeerIdentity.issue(ca, common_name="child")

        st = DaemonStorage(str(tmp_path / "s"), prefer_native=False)
        st.register_task("t", piece_size=1024, content_length=1024)
        st.write_piece("t", 0, b"secret" * 100)
        server = PieceHTTPServer(
            UploadManager(st), ssl_context=server_context(server_id)
        )
        server.serve()
        try:
            url = f"https://127.0.0.1:{server.port}/pieces/t/0"
            ctx = client_context(client_id)
            ctx.check_hostname = False  # IP connect in test
            with urllib.request.urlopen(url, context=ctx, timeout=5) as resp:
                assert resp.read() == b"secret" * 100

            # Anonymous client (no cert) must be rejected by mTLS.
            anon = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            anon.check_hostname = False
            anon.verify_mode = ssl.CERT_NONE
            with pytest.raises((urllib.error.URLError, ssl.SSLError, ConnectionError, OSError)):
                urllib.request.urlopen(url, context=anon, timeout=5).read()
        finally:
            server.stop()


@requires_crypto
class TestWireIssuance:
    """Manager-backed certificate issuance (VERDICT r3 next-#5): the
    certify analog — CSR over the wire, cluster-CA-signed cert back
    (pkg/issuer, scheduler.go:186-222, security_server.go)."""

    def _manager(self, **kw):
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        server = ManagerRESTServer(
            ModelRegistry(), ClusterManager(), ca=CertificateAuthority(), **kw
        )
        server.serve()
        return server

    def test_rest_issuance_chain_validates(self):
        server = self._manager()
        try:
            ident = PeerIdentity.request_from_manager(
                server.url, common_name="daemon-9",
                hostnames=["daemon-9"], ips=["127.0.0.1"],
            )
            from cryptography import x509
            from cryptography.hazmat.primitives.asymmetric import ec

            cert = x509.load_pem_x509_certificate(ident.cert_pem)
            ca_cert = x509.load_pem_x509_certificate(ident.ca_pem)
            ca_cert.public_key().verify(
                cert.signature, cert.tbs_certificate_bytes,
                ec.ECDSA(cert.signature_hash_algorithm),
            )
            san = cert.extensions.get_extension_for_class(
                x509.SubjectAlternativeName
            )
            assert "daemon-9" in san.value.get_values_for_type(x509.DNSName)
            # Trust-root fetch (open read).
            with urllib.request.urlopen(
                server.url + "/api/v1/certs:ca", timeout=5
            ) as resp:
                assert json.loads(resp.read())["ca_pem"] == ident.ca_pem.decode()
        finally:
            server.stop()

    def test_ttl_request_is_server_capped(self):
        """A PEER cannot mint an effectively permanent cert: requested
        TTLs clamp to MAX_CERT_TTL server-side (revocation = non-renewal)."""
        import datetime

        from dragonfly2_tpu.security.ca import MAX_CERT_TTL

        server = self._manager()
        try:
            ident = PeerIdentity.request_from_manager(
                server.url, common_name="greedy", ttl_hours=87_600  # 10 years
            )
            from cryptography import x509

            cert = x509.load_pem_x509_certificate(ident.cert_pem)
            validity = (
                cert.not_valid_after_utc - datetime.datetime.now(
                    datetime.timezone.utc
                )
            )
            assert validity <= MAX_CERT_TTL + datetime.timedelta(minutes=10)
        finally:
            server.stop()

    def test_rest_issuance_rejects_garbage_csr(self):
        server = self._manager()
        try:
            req = urllib.request.Request(
                server.url + "/api/v1/certs:issue",
                data=json.dumps({"csr_pem": "not a csr"}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 400
        finally:
            server.stop()

    def test_issuance_requires_peer_role_when_rbac_on(self):
        secret = b"manager-secret-0123456789abcd"
        server = self._manager(token_verifier=TokenVerifier(secret))
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                PeerIdentity.request_from_manager(
                    server.url, common_name="anon"
                )
            assert exc.value.code == 401
            # With a PEER token the same request succeeds.
            tok = TokenIssuer(secret).issue("daemon-1", Role.PEER)
            ident = PeerIdentity.request_from_manager(
                server.url, common_name="daemon-1", token=tok
            )
            assert b"BEGIN CERTIFICATE" in ident.cert_pem
        finally:
            server.stop()

    def test_grpc_issuance_twin(self):
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.rpc.grpc_transport import (
            GRPCRemoteRegistry,
            ManagerGRPCServer,
        )
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography import x509
        from cryptography.x509.oid import NameOID

        ca = CertificateAuthority()
        server = ManagerGRPCServer(ModelRegistry(), ClusterManager(), ca=ca)
        server.serve()
        try:
            key = ec.generate_private_key(ec.SECP256R1())
            csr = (
                x509.CertificateSigningRequestBuilder()
                .subject_name(x509.Name([
                    x509.NameAttribute(NameOID.COMMON_NAME, "sched-1")
                ]))
                .sign(key, hashes.SHA256())
            )
            client = GRPCRemoteRegistry(server.target)
            cert_pem, ca_pem = client.issue_certificate(
                csr.public_bytes(serialization.Encoding.PEM)
            )
            assert ca_pem == ca.cert_pem
            cert = x509.load_pem_x509_certificate(cert_pem)
            assert cert.subject.get_attributes_for_oid(
                NameOID.COMMON_NAME
            )[0].value == "sched-1"
        finally:
            server.stop()

    def test_identity_renewer_reloads_live_contexts(self, tmp_path):
        """Short-TTL certs renew WITHOUT a restart: the renewer re-issues
        at half validity and reloads the live contexts in place — an
        mTLS roundtrip still works on certs issued AFTER the servers
        were built."""
        import datetime
        import time
        import urllib.request

        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.rpc import PieceHTTPServer
        from dragonfly2_tpu.security.ca import IdentityRenewer

        ca = CertificateAuthority()
        short = datetime.timedelta(seconds=2)
        server_id = PeerIdentity.issue(
            ca, common_name="p", hostnames=["localhost"], ips=["127.0.0.1"],
            ttl=short,
        )
        client_id = PeerIdentity.issue(ca, common_name="c", ttl=short)
        sctx, cctx = server_context(server_id), client_context(client_id)

        st = DaemonStorage(str(tmp_path / "s"), prefer_native=False)
        st.register_task("t", piece_size=64, content_length=64)
        st.write_piece("t", 0, b"x" * 64)
        server = PieceHTTPServer(UploadManager(st), ssl_context=sctx)
        server.serve()
        renewers = [
            IdentityRenewer(
                server_id,
                lambda: PeerIdentity.issue(
                    ca, common_name="p", hostnames=["localhost"],
                    ips=["127.0.0.1"],
                ),
                [sctx],
                min_interval_s=0.2,
            ).start(),
            IdentityRenewer(
                client_id,
                lambda: PeerIdentity.issue(ca, common_name="c"),
                [cctx],
                min_interval_s=0.2,
            ).start(),
        ]
        try:
            deadline = time.time() + 10
            while (
                any(r.renewals == 0 for r in renewers) and time.time() < deadline
            ):
                time.sleep(0.1)
            assert all(r.renewals >= 1 for r in renewers)
            # Certs on BOTH sides are renewals now; the plane still moves.
            url = f"https://127.0.0.1:{server.port}/pieces/t/0"
            with urllib.request.urlopen(url, context=cctx, timeout=5) as r:
                assert r.read() == b"x" * 64
        finally:
            for r in renewers:
                r.stop()
            server.stop()

    def test_renewer_retries_failures_and_keeps_old_identity(self):
        """An issue failure at renewal time must keep serving on the old
        (still-valid) cert and retry — never crash, never clear state."""
        import datetime
        import time

        from dragonfly2_tpu.security.ca import IdentityRenewer

        ca = CertificateAuthority()
        ident = PeerIdentity.issue(
            ca, common_name="d", ttl=datetime.timedelta(seconds=1)
        )
        calls = {"n": 0}

        def flaky_issue():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("manager down")
            return PeerIdentity.issue(ca, common_name="d")

        ctx = client_context(ident)
        r = IdentityRenewer(
            ident, flaky_issue, [ctx], min_interval_s=0.1
        ).start()
        try:
            deadline = time.time() + 10
            while r.renewals == 0 and time.time() < deadline:
                time.sleep(0.05)
            assert r.renewals == 1
            assert calls["n"] == 3  # two failures retried, old cert kept
            assert r.identity is not ident  # fresh identity adopted
        finally:
            r.stop()

    def test_wire_issued_identities_do_mtls_piece_transfer(self, tmp_path):
        """End to end: both sides bootstrap from the manager, then move
        bytes over mutual TLS; anonymous clients stay locked out."""
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.rpc import PieceHTTPServer

        manager = self._manager()
        try:
            parent = PeerIdentity.request_from_manager(
                manager.url, common_name="parent",
                hostnames=["localhost"], ips=["127.0.0.1"],
            )
            child = PeerIdentity.request_from_manager(
                manager.url, common_name="child"
            )
            st = DaemonStorage(str(tmp_path / "s"), prefer_native=False)
            st.register_task("t", piece_size=1024, content_length=1024)
            st.write_piece("t", 0, b"wired" * 100)
            server = PieceHTTPServer(
                UploadManager(st), ssl_context=server_context(parent)
            )
            server.serve()
            try:
                url = f"https://127.0.0.1:{server.port}/pieces/t/0"
                ctx = client_context(child)
                with urllib.request.urlopen(url, context=ctx, timeout=5) as r:
                    assert r.read() == b"wired" * 100
                anon = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                anon.check_hostname = False
                anon.verify_mode = ssl.CERT_NONE
                with pytest.raises(
                    (urllib.error.URLError, ssl.SSLError, ConnectionError, OSError)
                ):
                    urllib.request.urlopen(url, context=anon, timeout=5).read()
            finally:
                server.stop()
        finally:
            manager.stop()


class TestTokens:
    def test_roundtrip_roles_expiry(self):
        issuer = TokenIssuer(b"super-secret-key-0123456789")
        verifier = TokenVerifier(b"super-secret-key-0123456789")
        tok = issuer.issue("daemon-1", Role.PEER)
        claims = verifier.verify(tok)
        assert claims.subject == "daemon-1" and claims.role is Role.PEER
        assert verifier.authorize(tok, Role.PEER) is not None
        assert verifier.authorize(tok, Role.OPERATOR) is None  # insufficient
        # Tampered token fails.
        assert verifier.verify(tok[:-4] + "AAAA") is None
        # Wrong secret fails.
        assert TokenVerifier(b"another-secret-key-xxxxxxxx").verify(tok) is None
        # Expired token fails.
        old = issuer.issue("x", Role.ADMIN, ttl_s=-1)
        assert verifier.verify(old) is None

    def test_weak_secret_rejected(self):
        with pytest.raises(ValueError):
            TokenIssuer(b"short")


class TestRESTAuth:
    def test_mutations_require_operator(self):
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        secret = b"manager-secret-0123456789abcd"
        registry = ModelRegistry()
        m = registry.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"a")
        server = ManagerRESTServer(
            registry, ClusterManager(), token_verifier=TokenVerifier(secret)
        )
        server.serve()
        try:
            url = server.url + f"/api/v1/models/{m.id}:activate"
            # No token → 401.
            req = urllib.request.Request(url, data=b"", method="POST")
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 401
            # PEER-role token → still 401 for activation.
            issuer = TokenIssuer(secret)
            peer_tok = issuer.issue("d", Role.PEER)
            req = urllib.request.Request(
                url, data=b"", method="POST",
                headers={"Authorization": f"Bearer {peer_tok}"},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 401
            # OPERATOR token → activation succeeds.
            op_tok = issuer.issue("ops", Role.OPERATOR)
            req = urllib.request.Request(
                url, data=b"", method="POST",
                headers={"Authorization": f"Bearer {op_tok}"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert json.loads(resp.read())["state"] == "active"
            # Reads stay open.
            with urllib.request.urlopen(server.url + "/api/v1/models", timeout=5) as r:
                assert json.loads(r.read())
        finally:
            server.stop()


@requires_crypto
class TestClientSideWiring:
    def test_mtls_piece_fetcher_end_to_end(self, tmp_path):
        """The framework's own fetcher (not hand-rolled urllib) fetches
        through mutual TLS."""
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.rpc import HTTPPieceFetcher, PieceHTTPServer

        ca = CertificateAuthority()
        server_id = PeerIdentity.issue(ca, common_name="p", ips=["127.0.0.1"])
        client_id = PeerIdentity.issue(ca, common_name="c")

        st = DaemonStorage(str(tmp_path / "s"), prefer_native=False)
        st.register_task("t", piece_size=512, content_length=1024)
        st.write_piece("t", 0, b"a" * 512)
        st.write_piece("t", 1, b"b" * 512)
        server = PieceHTTPServer(UploadManager(st), ssl_context=server_context(server_id))
        server.serve()
        try:
            assert server._svc.url.startswith("https://")
            ctx = client_context(client_id)
            ctx.check_hostname = False
            fetcher = HTTPPieceFetcher(
                lambda hid: ("127.0.0.1", server.port), ssl_context=ctx
            )
            assert fetcher.fetch("p", "t", 0) == b"a" * 512
            assert list(fetcher.piece_bitmap("p", "t")) == [1, 1]
        finally:
            server.stop()

    def test_remote_registry_with_token(self):
        """RemoteRegistry authenticates against an RBAC-enabled manager:
        PEER token creates models (the trainer's flow), OPERATOR activates."""
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer
        from dragonfly2_tpu.rpc import RemoteRegistry

        secret = b"manager-secret-0123456789abcd"
        issuer = TokenIssuer(secret)
        server = ManagerRESTServer(
            ModelRegistry(), ClusterManager(), token_verifier=TokenVerifier(secret)
        )
        server.serve()
        try:
            # Trainer-side client with a PEER token can create…
            peer_reg = RemoteRegistry(server.url, token=issuer.issue("trainer", Role.PEER))
            m = peer_reg.create_model(
                name="m", type="mlp", scheduler_id="s", artifact=b"w"
            )
            # …but not activate.
            with pytest.raises(RuntimeError):
                peer_reg.activate(m.id)
            # No token at all → refused.
            anon = RemoteRegistry(server.url)
            with pytest.raises(RuntimeError):
                anon.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"")
            # Operator client activates; artifact pull (a read) works.
            op_reg = RemoteRegistry(server.url, token=issuer.issue("ops", Role.OPERATOR))
            assert op_reg.activate(m.id).state.value == "active"
            assert op_reg.load_artifact(m) == b"w"
        finally:
            server.stop()
