"""Runtime ABI witness (DF020/DF021, enforced; DESIGN.md §30).

The static side (``tools/dflint/checkers/df020_abi.py``) proves three
TEXTS agree: ``records/abi_contracts.py``, the ``extern "C"`` surface
of ``native.cpp``, and the ctypes bindings.  This module closes the
loop against what the COMPILER actually produced:

- ``df_abi_manifest()`` — emitted from the same ``DF_ABI_EXPORTS`` /
  ``DF_ABI_CONSTANTS`` X-macro tables that expand into per-symbol
  ``static_assert``s — must byte-match the canonical JSON rendered from
  the registry (``sort_keys``/compact separators on both sides, so a
  single drifted offset, constant, or prototype breaks equality);
- a sentinel ``FetchDone`` memcpy'd out by ``df_abi_probe_fetchdone()``
  must round-trip through the registry's struct format with every field
  intact (each sentinel value is distinguishable by position and width,
  so a swapped or widened field cannot pass);
- the ``ps_serve_stats2`` field ORDER must hold through a real serve —
  the Python builder's dict order is part of the contract
  (``stats_fields`` in the registry), not a doc comment;
- the comparator itself is proven against gap fixtures: a doctored
  manifest and a stale registry (both directions) must produce gaps
  that name the drifted symbol.

Live halves skip clean when the native library is unavailable (same
discipline as tests/test_native_sanitizers.py); the fixture halves run
everywhere.  A failure here means the compiled .so and the declared
contracts disagree — fix native.cpp / records/abi_contracts.py (then
``make -C dragonfly2_tpu/native``), never this test.
"""

from __future__ import annotations

import json
import os

import pytest

from dragonfly2_tpu import native
from dragonfly2_tpu.records import abi_contracts
from dragonfly2_tpu.utils import dfabi

needs_native = pytest.mark.skipif(
    not native.available(), reason="native engine unavailable"
)


def _canon(obj: dict) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


class TestManifestByteMatch:
    def test_witness_installed(self):
        if os.environ.get("DF_ABI_WITNESS", "1") == "0":
            pytest.skip("ABI witness disabled via DF_ABI_WITNESS=0")
        assert dfabi.armed()

    @needs_native
    def test_live_manifest_byte_matches_registry(self):
        live = dfabi.live_manifest_bytes()
        assert live is not None, "df_abi_manifest missing from the .so"
        assert live == dfabi.expected_manifest_bytes(), (
            "compiled manifest != registry; gaps:\n  "
            + "\n  ".join(dfabi.compare(live_bytes=live))
        )
        assert dfabi.compare() == []

    @needs_native
    def test_live_manifest_shape(self):
        live = json.loads(dfabi.live_manifest_bytes().decode())
        assert live["version"] == 1
        assert set(live) == {"constants", "exports", "records", "version"}
        # every binding surface present, including the witness's own
        assert "df_abi_manifest" in live["exports"]
        assert "df_abi_probe_fetchdone" in live["exports"]
        assert live["records"]["FetchDone"]["size"] == abi_contracts.record_size(
            "FetchDone"
        )

    @needs_native
    def test_manifest_pointer_stable(self):
        # c_char_p decays to bytes through ctypes; stability here means
        # two calls return identical bytes (static storage, no per-call
        # allocation the caller would have to free).
        assert dfabi.live_manifest_bytes() == dfabi.live_manifest_bytes()


class TestProbeRoundTrip:
    @needs_native
    def test_sentinel_fetchdone_round_trips(self):
        out = dfabi.probe_fetchdone()
        assert out is not None
        assert out.pop("__returned_size__") == abi_contracts.record_size(
            "FetchDone"
        )
        assert out == dfabi.PROBE_SENTINEL

    @needs_native
    def test_sentinel_status_is_registry_constant(self):
        # one real enum value crosses the boundary: the probe's status
        # field IS kFetchStatusProto, not an arbitrary number
        assert dfabi.PROBE_SENTINEL["status"] == abi_contracts.constant(
            "kFetchStatusProto"
        )


class TestStatsFieldOrder:
    @needs_native
    def test_serve_stats_full_order_through_real_serve(self, tmp_path):
        import urllib.request

        declared = list(
            abi_contracts.ABI_CONTRACTS["stats_fields"]["ps_serve_stats2"][
                "fields"
            ]
        )
        store = native.NativePieceStore(str(tmp_path / "store"))
        try:
            task = "w" * 16
            data = bytes(range(256)) * 16
            store.create_task(task, piece_size=len(data), content_length=len(data))
            store.write_piece(task, 0, data)
            port = store.serve()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/pieces/{task}/0", timeout=10
            ) as resp:
                assert resp.read() == data
            full = store.serve_stats_full()
            # dict insertion order IS the declared field order — the
            # Python builder is named in the registry for exactly this
            assert list(full) == declared
            assert full["pieces"] >= 1
            assert full["bytes"] >= len(data)
            store.serve_stop()
        finally:
            store.close()

    @needs_native
    def test_oi_stats_order_matches_registry(self):
        declared = list(
            abi_contracts.ABI_CONTRACTS["stats_fields"]["oi_stats"]["fields"]
        )
        oi = native.NativeOnlineIngest(
            num_nodes=8, n_buckets=32, feat_dim=4, row_width=11,
            node_ttl=60.0, ring_capacity=16,
        )
        try:
            assert list(oi.stats()) == declared
        finally:
            oi.close()


class TestGapFixtures:
    """The comparator must NAME drift, both directions, on doctored
    inputs — otherwise a green witness proves nothing."""

    def test_doctored_constant_named(self):
        doc = json.loads(dfabi.expected_manifest_bytes().decode())
        doc["constants"]["kBatchBytesMax"] = 262144
        gaps = dfabi.compare(live_bytes=_canon(doc))
        assert any("kBatchBytesMax" in g and "262144" in g for g in gaps)

    def test_doctored_record_offset_named(self):
        doc = json.loads(dfabi.expected_manifest_bytes().decode())
        fields = doc["records"]["FetchDone"]["fields"]
        fields[1], fields[2] = fields[2], fields[1]  # swap status/length
        gaps = dfabi.compare(live_bytes=_canon(doc))
        assert any("FetchDone" in g for g in gaps)

    def test_stale_so_direction(self):
        # compiled manifest LACKS a symbol the registry declares
        doc = json.loads(dfabi.expected_manifest_bytes().decode())
        del doc["exports"]["ps_write_piece"]
        gaps = dfabi.compare(live_bytes=_canon(doc))
        assert any(
            "ps_write_piece" in g and "missing from the compiled" in g
            for g in gaps
        )

    def test_stale_registry_direction(self):
        # compiled manifest HAS a symbol the registry does not declare
        stale = json.loads(dfabi.expected_manifest_bytes().decode())
        del stale["exports"]["ps_write_piece"]
        gaps = dfabi.compare(
            expected_bytes=_canon(stale),
            live_bytes=dfabi.expected_manifest_bytes(),
        )
        assert any(
            "ps_write_piece" in g and "not declared" in g for g in gaps
        )

    def test_non_canonical_bytes_rejected(self):
        pretty = json.dumps(
            json.loads(dfabi.expected_manifest_bytes().decode()),
            sort_keys=True,
            indent=1,
        ).encode()
        gaps = dfabi.compare(live_bytes=pretty)
        assert any("canonical JSON" in g for g in gaps)

    def test_invalid_json_reported(self):
        gaps = dfabi.compare(live_bytes=b"\x00not json")
        assert any("not valid JSON" in g for g in gaps)

    def test_unavailable_library_reported(self, monkeypatch):
        monkeypatch.setattr(dfabi, "live_manifest_bytes", lambda: None)
        gaps = dfabi.compare()
        assert gaps and "unavailable" in gaps[0]

    def test_version_drift_reported(self):
        doc = json.loads(dfabi.expected_manifest_bytes().decode())
        doc["version"] = 2
        gaps = dfabi.compare(live_bytes=_canon(doc))
        assert any(g.startswith("version:") for g in gaps)


class TestRendererParity:
    def test_dflint_and_registry_render_identical_bytes(self):
        # dflint's reimplementation (reads the registry as a LITERAL via
        # ast.literal_eval — no import) must agree byte-for-byte with
        # the module's own renderer, or --update-abi-manifest would
        # document a different contract than the witness enforces.
        from tools.dflint.checkers import df020_abi

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        literal = df020_abi.load_contracts_text(
            open(
                os.path.join(root, df020_abi.CONTRACTS_RELPATH),
                encoding="utf-8",
            ).read()
        )
        assert df020_abi.manifest_json(literal) == abi_contracts.manifest_json()
