"""Platform layer tests: config, metrics, logging, jobs/preheat, source
clients, CLI smoke."""

import os
import sys

import numpy as np
import pytest

from dragonfly2_tpu.config import (
    ConfigError,
    SchedulerConfigFile,
    TrainerConfigFile,
    load_config,
)
from dragonfly2_tpu.jobs import JobQueue, JobState, Worker, preheat
from dragonfly2_tpu.jobs.preheat import PREHEAT, make_preheat_handler
from dragonfly2_tpu.source import FileSourceClient, PieceSourceFetcher, default_registry
from dragonfly2_tpu.utils.metrics import Registry


class TestConfig:
    def test_defaults_valid(self):
        cfg = load_config(SchedulerConfigFile, None, env=False)
        assert cfg.scheduling.candidate_parent_limit == 4
        assert cfg.scheduling.filter_parent_limit == 15
        assert cfg.network_topology.probe_count == 5
        assert cfg.trainer.interval_s == 7 * 24 * 3600.0

    def test_yaml_load_and_validate(self, tmp_path):
        path = tmp_path / "s.yaml"
        path.write_text(
            "scheduling:\n  algorithm: nt\n  candidate_parent_limit: 8\n"
            "  filter_parent_limit: 20\nserver:\n  port: 9999\n"
        )
        cfg = load_config(SchedulerConfigFile, str(path), env=False)
        assert cfg.scheduling.algorithm == "nt"
        assert cfg.server.port == 9999

    def test_invalid_rejected(self, tmp_path):
        path = tmp_path / "bad.yaml"
        path.write_text("scheduling:\n  algorithm: quantum\n")
        with pytest.raises(ConfigError):
            load_config(SchedulerConfigFile, str(path), env=False)
        path.write_text("scheduling:\n  candidate_parent_limit: 99\n")
        with pytest.raises(ConfigError):
            load_config(SchedulerConfigFile, str(path), env=False)
        path.write_text("nonsense_key: 1\n")
        with pytest.raises(ConfigError):
            load_config(SchedulerConfigFile, str(path), env=False)

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("DRAGONFLY_TRAINER_TRAINING_EPOCHS", "99")
        monkeypatch.setenv("DRAGONFLY_TRAINER_METRICS_ENABLE", "false")
        cfg = load_config(TrainerConfigFile, None)
        assert cfg.training.epochs == 99
        assert cfg.metrics.enable is False


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = Registry()
        c = reg.counter("requests_total", "reqs", ["code"])
        c.inc(code="200")
        c.inc(2, code="500")
        assert c.value(code="500") == 2
        with pytest.raises(ValueError):
            c.inc(-1, code="200")
        g = reg.gauge("peers", "live peers")
        g.set(5)
        g.dec()
        assert g.value() == 4
        h = reg.histogram("latency_seconds", "lat", buckets=(0.1, 1, 10))
        h.observe(0.05)
        h.observe(5)
        text = reg.expose_text()
        assert 'requests_total{code="500"} 2' in text
        assert "# TYPE latency_seconds histogram" in text
        assert 'latency_seconds_bucket{le="0.1"} 1' in text
        assert 'latency_seconds_bucket{le="+Inf"} 2' in text

    def test_reregistration_returns_same(self):
        reg = Registry()
        a = reg.counter("x", "x")
        b = reg.counter("x", "x")
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("x", "x")


class TestJobs:
    def test_group_job_aggregation(self):
        broker = JobQueue()
        group = broker.create_group_job(
            "t", {"q1": {"v": 1}, "q2": {"v": 2}}
        )
        assert broker.group_state(group.id) is JobState.PENDING
        w1, w2 = Worker(broker, "q1"), Worker(broker, "q2")
        w1.register("t", lambda args: args["v"])
        w2.register("t", lambda args: args["v"])
        assert w1.drain() == 1
        assert broker.group_state(group.id) is JobState.PENDING  # q2 pending
        assert w2.drain() == 1
        assert broker.group_state(group.id) is JobState.SUCCESS

    def test_group_failure_propagates(self):
        broker = JobQueue()
        group = broker.create_group_job("t", {"q1": {}, "q2": {}})
        w1, w2 = Worker(broker, "q1"), Worker(broker, "q2")
        w1.register("t", lambda args: None)

        def boom(args):
            raise RuntimeError("nope")

        w2.register("t", boom)
        w1.drain()
        w2.drain()
        assert broker.group_state(group.id) is JobState.FAILURE

    def test_preheat_warms_seed_daemon(self, tmp_path):
        from tests.test_daemon import _Swarm

        swarm = _Swarm(tmp_path, n_hosts=3)
        broker = JobQueue()
        worker = Worker(broker, "scheduler-1")
        worker.register(
            PREHEAT,
            make_preheat_handler(
                swarm.daemons[0], content_length_for=lambda url: 2 * 65536
            ),
        )
        job = preheat(
            broker,
            ["https://origin/preheat-me"],
            ["scheduler-1"],
            piece_size=65536,
        )
        worker.drain()
        assert broker.group_state(job.group.id) is JobState.SUCCESS
        # The content is now warm: a fresh peer downloads P2P.
        r = swarm.daemons[1].download("https://origin/preheat-me", piece_size=65536)
        assert r.ok and not r.back_to_source


class TestSource:
    def test_file_client_roundtrip(self, tmp_path):
        path = tmp_path / "blob.bin"
        payload = bytes(range(256)) * 100
        path.write_bytes(payload)
        fetcher = PieceSourceFetcher()
        url = f"file://{path}"
        assert fetcher.content_length(url) == len(payload)
        assert fetcher.fetch(url, 0, 1000) == payload[:1000]
        assert fetcher.fetch(url, 3, 1000) == payload[3000:4000]

    def test_unknown_scheme_raises(self):
        with pytest.raises(KeyError):
            default_registry.client_for("s3://bucket/key")


class TestCLI:
    def test_dfget_file_url(self, tmp_path, capsys):
        from dragonfly2_tpu.cli.dfget import run as dfget

        src = tmp_path / "src.bin"
        payload = os.urandom(300_000)
        src.write_bytes(payload)
        out = tmp_path / "out.bin"
        rc = dfget(
            [
                f"file://{src}",
                "-O", str(out),
                "--piece-size", "65536",
                "--work-dir", str(tmp_path / "work"),
            ]
        )
        assert rc == 0
        assert out.read_bytes() == payload

    def test_dfcache_import_stat_export(self, tmp_path, capsys):
        from dragonfly2_tpu.cli.dfcache import run as dfcache

        src = tmp_path / "artifact.bin"
        payload = os.urandom(150_000)
        src.write_bytes(payload)
        work = str(tmp_path / "cache")
        assert dfcache(["import", str(src), "--work-dir", work, "--piece-size", "65536"]) == 0
        cache_id = capsys.readouterr().out.split(" as ")[1].split(" ")[0]
        assert dfcache(["stat", cache_id, "--work-dir", work]) == 0
        out = tmp_path / "restored.bin"
        assert dfcache(["export", cache_id, "-O", str(out), "--work-dir", work]) == 0
        assert out.read_bytes() == payload

    def test_scheduler_simulate(self, tmp_path, capsys):
        from dragonfly2_tpu.cli.scheduler import run as sched

        cfg = tmp_path / "s.yaml"
        cfg.write_text(f"storage:\n  dir: {tmp_path}/records\n")
        rc = sched(["--config", str(cfg), "--simulate", "40"])
        assert rc == 0
        assert "download records" in capsys.readouterr().out

    def test_trainer_train_once(self, tmp_path, capsys, cluster):
        from dragonfly2_tpu.cli.trainer import run as trainer
        from dragonfly2_tpu.records.columnar import ColumnarWriter
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS

        shard_dir = tmp_path / "shards"
        shard_dir.mkdir()
        rows = cluster.generate_feature_rows(2000, seed=5)
        with ColumnarWriter(str(shard_dir / "download-0.dfc"), DOWNLOAD_COLUMNS) as w:
            w.append(rows)
        cfg = tmp_path / "t.yaml"
        cfg.write_text("training:\n  epochs: 3\n")
        rc = trainer(["--config", str(cfg), "--train-once", str(shard_dir)])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "registered parent-bandwidth-mlp v1" in out

    def test_manager_list_models(self, tmp_path, capsys):
        from dragonfly2_tpu.cli.manager import run as manager

        cfg = tmp_path / "m.yaml"
        cfg.write_text(f"registry:\n  blob_dir: {tmp_path}/blobs\n")
        assert manager(["--config", str(cfg), "--list-models"]) == 0
        assert "registry empty" in capsys.readouterr().out


class TestSmallKernel:
    def test_tcp_ping_and_pinger(self):
        from http.server import BaseHTTPRequestHandler
        from dragonfly2_tpu.rpc._server import ThreadedHTTPService
        from dragonfly2_tpu.utils.ping import make_host_pinger, tcp_ping

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a): pass

        svc = ThreadedHTTPService(H, "127.0.0.1", 0, "ping-target")
        svc.serve()
        try:
            rtt = tcp_ping("127.0.0.1", svc.port)
            assert rtt is not None and rtt > 0
            from dragonfly2_tpu.scheduler.resource import Host

            host = Host(id="h", hostname="h", ip="127.0.0.1", download_port=svc.port)
            assert make_host_pinger()(host) > 0
        finally:
            svc.stop()
        assert tcp_ping("127.0.0.1", 1, timeout=0.2) is None  # closed port

    def test_dferrors_codes(self):
        from dragonfly2_tpu.utils.dferrors import (
            Code, NotFoundError, UnavailableError, is_retryable,
        )

        assert NotFoundError("x").code is Code.NOT_FOUND
        assert is_retryable(UnavailableError("y"))
        assert not is_retryable(NotFoundError("x"))
        assert not is_retryable(ValueError("z"))

    def test_version_metadata(self):
        from dragonfly2_tpu.version import build_info

        info = build_info()
        assert info.version and info.python_version
        assert "/" in info.platform
        assert set(info.to_dict()) == {"version", "git_commit", "python_version", "platform"}

    def test_scheduler_resolver_follows_dynconfig(self):
        from dragonfly2_tpu.manager import Dynconfig, DynconfigServer
        from dragonfly2_tpu.rpc.resolver import SchedulerResolver

        server = DynconfigServer()
        server.set("daemon", {"schedulers": [
            {"id": "s1", "url": "http://s1:80"}, {"id": "s2", "url": "http://s2:80"}
        ]})
        resolver = SchedulerResolver()
        dc = Dynconfig(lambda: server.get("daemon")[0])
        dc.register(resolver.on_config)
        dc.refresh()
        assert resolver.all_urls() == ["http://s1:80", "http://s2:80"]
        picked = {resolver.pick(f"task-{i}") for i in range(50)}
        assert picked == {"http://s1:80", "http://s2:80"}
        # Task affinity is stable.
        assert resolver.pick("task-7") == resolver.pick("task-7")
        server.set("daemon", {"schedulers": [{"id": "s1", "url": "http://s1:80"}]})
        dc.refresh()
        assert resolver.all_urls() == ["http://s1:80"]
        assert resolver.pick("task-7") == "http://s1:80"


class TestServiceMetrics:
    def test_scheduler_metrics_increment(self, tmp_path):
        from dragonfly2_tpu.scheduler import metrics as sm
        from tests.test_daemon import _Swarm

        before = sm.PEER_RESULT_TOTAL.value(result="succeeded")
        before_rec = sm.DOWNLOAD_RECORDS_TOTAL.value()
        from dragonfly2_tpu.records.storage import Storage

        store = Storage(str(tmp_path / "r"), buffer_size=1)
        swarm = _Swarm(tmp_path, n_hosts=2, record_storage=store)
        swarm.daemons[0].download(
            "https://origin/m", piece_size=65536, content_length=2 * 65536
        )
        assert sm.PEER_RESULT_TOTAL.value(result="succeeded") == before + 1
        assert sm.DOWNLOAD_RECORDS_TOTAL.value() == before_rec + 1
        assert sm.PIECE_RESULT_TOTAL.value(result="finished") >= 2
        from dragonfly2_tpu.utils.metrics import default_registry

        text = default_registry.expose_text()
        assert "scheduler_peer_result_total" in text

    def test_trainer_metrics_increment(self, tmp_path, cluster):
        from dragonfly2_tpu.manager import ModelRegistry
        from dragonfly2_tpu.records.columnar import ColumnarWriter
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
        from dragonfly2_tpu.trainer import metrics as tm
        from dragonfly2_tpu.trainer.service import TrainerService
        from dragonfly2_tpu.trainer.train import TrainConfig

        before = tm.MODELS_PUBLISHED.value(model="mlp")
        shard = tmp_path / "download-0.dfc"
        with ColumnarWriter(str(shard), DOWNLOAD_COLUMNS) as w:
            w.append(cluster.generate_feature_rows(1500, seed=9))
        svc = TrainerService(
            ModelRegistry(), train_config=TrainConfig(epochs=2, warmup_steps=5)
        )
        session = svc.open_train_stream(ip="1.1.1.1", hostname="t", scheduler_id="s")
        session.send_download_shard(str(shard))
        key = session.close_and_train()
        assert svc.runs[key].error is None
        assert tm.MODELS_PUBLISHED.value(model="mlp") == before + 1
        assert tm.TRAINING_TOTAL.value(model="all", result="success") >= 1


class TestStressAndRecursive:
    def test_stress_tool_over_swarm(self, tmp_path):
        from dragonfly2_tpu.tools.stress import run_stress
        from tests.test_daemon import PIECE, _Swarm

        swarm = _Swarm(tmp_path, n_hosts=3)
        urls = [f"https://origin/stress-{t}" for t in range(3)]
        for u in urls:
            swarm.daemons[0].download(u, piece_size=PIECE, content_length=2 * PIECE)

        def dl(url):
            return swarm.daemons[1].download(url, piece_size=PIECE)

        report = run_stress(dl, urls, concurrency=4, total=20)
        s = report.summary()
        assert s["succeeded"] == 20 and s["failed"] == 0
        assert s["throughput_MBps"] > 0 and s["latency_p95_ms"] > 0

    def test_dfget_recursive(self, tmp_path, capsys):
        from dragonfly2_tpu.cli.dfget import run as dfget

        src = tmp_path / "tree"
        (src / "sub").mkdir(parents=True)
        (src / "a.bin").write_bytes(os.urandom(70_000))
        (src / "sub" / "b.bin").write_bytes(os.urandom(130_000))
        out = tmp_path / "restored"
        rc = dfget([
            f"file://{src}", "-O", str(out), "--recursive",
            "--piece-size", "65536", "--work-dir", str(tmp_path / "w"),
        ])
        assert rc == 0
        assert (out / "a.bin").read_bytes() == (src / "a.bin").read_bytes()
        assert (out / "sub" / "b.bin").read_bytes() == (src / "sub" / "b.bin").read_bytes()

    def test_dfget_recursive_odd_names_and_empty_dirs(self, tmp_path, capsys):
        from dragonfly2_tpu.cli.dfget import run as dfget

        src = tmp_path / "tree2"
        (src / "empty_sub").mkdir(parents=True)
        (src / "a#1.bin").write_bytes(os.urandom(40_000))
        (src / "dangling").symlink_to("/nonexistent-target")
        out = tmp_path / "restored2"
        rc = dfget([
            f"file://{src}", "-O", str(out), "--recursive",
            "--piece-size", "65536", "--work-dir", str(tmp_path / "w2"),
        ])
        assert rc == 0
        assert (out / "a#1.bin").read_bytes() == (src / "a#1.bin").read_bytes()
        assert (out / "empty_sub").is_dir()
        err = capsys.readouterr().err
        assert "skipped dangling" in err

    def test_stress_percentile_and_empty_urls(self):
        from dragonfly2_tpu.tools.stress import StressReport, run_stress

        r = StressReport()
        r.latencies_s = [i / 1000 for i in range(1, 101)]  # 1..100 ms
        assert r.percentile(99) == pytest.approx(0.099)  # 99th, not max
        assert r.percentile(50) == pytest.approx(0.050)
        with pytest.raises(ValueError):
            run_stress(lambda u: None, [], total=5)


class TestSteeringClient:
    """Multi-replica steering (rpc/steering.py): ring routing and
    per-replica fault isolation (the deployed behavior lives in
    deploy/e2e_loop.py stage 6; these are its unit contracts)."""

    class _Fake:
        def __init__(self, url, fail=False):
            self.url = url
            self.fail = fail
            self.announced = []
            self.registered = []

        def announce_host(self, host):
            if self.fail:
                raise ConnectionError(f"{self.url} down")
            self.announced.append(host.id)

        def register_peer(self, *, host, url, task_id=None, **kw):
            if self.fail:
                raise ConnectionError(f"{self.url} down")
            self.registered.append(task_id)
            return ("reg", self.url, task_id)

        def sync_probes_start(self, host):
            return [self.url]

    def _mk(self, fail_first=False):
        from dragonfly2_tpu.rpc.steering import SteeringSchedulerClient

        fakes = {}

        def factory(u):
            fakes[u] = self._Fake(u, fail=(fail_first and u == "http://a"))
            return fakes[u]

        client = SteeringSchedulerClient(
            ["http://a", "http://b"], factory=factory
        )
        return client, fakes

    def test_task_routing_is_stable_and_splits(self):
        client, fakes = self._mk()

        class H:
            id = "h-1"

        owners = set()
        for i in range(40):
            tid = f"task-{i}"
            out = client.register_peer(host=H(), url="u", task_id=tid)
            owners.add(out[1])
            # Re-registering the SAME task always lands on the same replica.
            assert client.for_task(tid).url == out[1]
        assert owners == {"http://a", "http://b"}  # the ring actually splits

    def test_announce_survives_one_replica_down(self):
        client, fakes = self._mk(fail_first=True)

        class H:
            id = "h-2"

        client.announce_host(H())  # must NOT raise
        assert fakes["http://b"].announced == ["h-2"]

        # With EVERY replica down, the failure surfaces.
        fakes["http://b"].fail = True
        import pytest as _pytest

        with _pytest.raises(ConnectionError):
            client.announce_host(H())

    def test_probes_pin_per_host(self):
        client, fakes = self._mk()

        class H:
            def __init__(self, hid):
                self.id = hid

        picks = {client.sync_probes_start(H(f"host-{i}"))[0] for i in range(40)}
        assert picks == {"http://a", "http://b"}
        # Same host always probes through the same replica.
        assert (
            client.sync_probes_start(H("host-0"))
            == client.sync_probes_start(H("host-0"))
        )
