"""Kill-the-manager-mid-preheat recovery drill (VERDICT r4 #5).

The manager concentrates durable state behind ONE backend
(manager/state.py): model registry rows, CRUD rows, the job broker, the
shared topology cache, users.  Reference: the manager spreads this over
MySQL/Postgres + Redis and survives restarts by construction
(manager/database/database.go:50-59).  This drill proves the embedded
backend gives the same story: a REAL manager process is SIGKILLed with
a preheat group in flight, restarted on the same state directory, and
every surface resumes —

- the preheat group survives and a late-attaching scheduler worker
  polls + completes it (jobs re-poll);
- pushed topology re-merges into replica pulls (topology re-merges);
- the cluster CA and its trust root are the SAME, so peer identities
  issued before the crash keep verifying and renewal retries succeed
  against the restarted manager (renewals retry);
- registry models and CRUD rows are intact.

DESIGN.md's failure-mode table cites this file in its "verified by"
column.
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    import cryptography  # noqa: F401

    _HAS_CRYPTO = True
except ImportError:
    _HAS_CRYPTO = False


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url: str, path: str, body: dict) -> dict:
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read() or b"{}")


def _get(url: str, path: str) -> dict:
    with urllib.request.urlopen(url + path, timeout=10) as r:
        return json.loads(r.read())


class _Manager:
    """A real cli.manager process on a FIXED port + state dir, so a
    restart is address-stable (clients retry the same endpoint).
    ``ha_yaml``/``extra_args`` configure the replication role (the
    leader+standby failover drill)."""

    def __init__(self, tmp: str, port: int, *, name: str = "manager",
                 ha_yaml: str = "", extra_args=()):
        self.tmp, self.port = tmp, port
        cfg_path = os.path.join(tmp, f"{name}.yaml")
        with open(cfg_path, "w") as f:
            f.write(
                f"server: {{host: 127.0.0.1, port: {port}, grpc_port: -1}}\n"
                f"registry: {{blob_dir: {tmp}/{name}}}\n"
                f"ca_dir: {tmp}/ca-{name}\n"
                "jobs_min_requeue_s: 0.01\n"
                + ha_yaml
            )
        self.cfg_path = cfg_path
        self.extra_args = list(extra_args)
        self.proc = None
        self.url = f"http://127.0.0.1:{port}"
        self.lines = []

    def start(self) -> None:
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "dragonfly2_tpu.cli.manager",
             "--config", self.cfg_path, *self.extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"},
        )
        ready = threading.Event()
        lines = self.lines = []

        def pump():
            for line in self.proc.stdout:
                lines.append(line)
                if line.startswith("manager: serving"):
                    ready.set()

        threading.Thread(target=pump, daemon=True).start()
        if not ready.wait(60):
            raise AssertionError(f"manager never ready: {lines[-10:]}")

    def sigkill(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def stop(self) -> None:
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            self.proc.wait(timeout=30)


@pytest.mark.skipif(
    not _HAS_CRYPTO,
    reason="drill verifies CA trust-root survival; needs `cryptography`",
)
def test_kill_manager_mid_preheat_recovers(tmp_path):
    from dragonfly2_tpu.jobs.remote import RemoteJobClient, RemoteJobWorker
    from dragonfly2_tpu.security.ca import PeerIdentity

    mgr = _Manager(str(tmp_path), _free_port())
    mgr.start()
    try:
        client = RemoteJobClient(mgr.url)

        # --- stage the in-flight world ---------------------------------
        # 1. A preheat fanned to a scheduler queue whose worker has NOT
        #    attached yet — exactly the mid-preheat window.
        group = client.create_group(
            "preheat", {"urls": ["https://origin/blob"]}, ["q-sched-a"]
        )
        gid = group["group_id"]
        # 2. A scheduler's topology push (the shared probe graph).
        _post(mgr.url, "/api/v1/topology", {
            "scheduler_id": "sched-a",
            "edges": [{"src": "h1", "dst": "h2", "average_rtt_ns": 12345}],
        })
        # 3. A registered model (the registry surface).
        import base64

        _post(mgr.url, "/api/v1/models", {
            "name": "parent-bandwidth-mlp", "type": "mlp",
            "scheduler_id": "sched-a",
            "artifact_b64": base64.b64encode(b"npzbytes").decode(),
        })
        # 4. A CRUD row (cluster config override).
        _post(mgr.url, "/api/v1/clusters", {
            "id": "c1", "name": "c1",
            "scheduler_cluster_config": {"candidate_parent_limit": 7},
        })
        # 5. A peer identity issued by the cluster CA.
        ident = PeerIdentity.request_from_manager(
            mgr.url, common_name="daemon-a"
        )
        ca_pem_before = ident.ca_pem

        # --- the crash --------------------------------------------------
        mgr.sigkill()
        with pytest.raises(urllib.error.URLError):
            _get(mgr.url, "/api/v1/jobs")  # provably down

        # --- restart on the same state dir ------------------------------
        mgr.start()

        # Jobs re-poll: the group survived, and the late-attaching worker
        # completes it now.
        st = client.group_state(gid)
        assert st["state"] == "PENDING", st
        worker = RemoteJobWorker(mgr.url, "q-sched-a", poll_timeout_s=0.5)
        done = {}
        worker.register(
            "preheat", lambda args: done.setdefault("urls", args["urls"])
        )
        assert worker.poll_once() is True
        assert done["urls"] == ["https://origin/blob"]
        assert client.group_state(gid)["state"] == "SUCCESS"

        # Topology re-merges: a replica's pull still sees sched-a's edge.
        edges = _get(mgr.url, "/api/v1/topology?exclude=sched-b")["edges"]
        assert any(
            e["src"] == "h1" and e["average_rtt_ns"] == 12345 for e in edges
        ), edges

        # Registry + CRUD intact.
        models = _get(mgr.url, "/api/v1/models")
        assert any(m["name"] == "parent-bandwidth-mlp" for m in models), models
        cluster_cfg = _get(mgr.url, "/api/v1/clusters/c1:config")
        assert cluster_cfg["scheduler_cluster_config"] == {
            "candidate_parent_limit": 7
        }

        # Renewals retry: the SAME trust root signs after restart, so the
        # pre-crash identity still verifies and a renewal succeeds.
        renewed = PeerIdentity.request_from_manager(
            mgr.url, common_name="daemon-a"
        )
        assert renewed.ca_pem == ca_pem_before
    finally:
        mgr.stop()


def test_started_job_redelivers_after_restart(tmp_path):
    """The at-least-once contract across a crash: a job a worker POPPED
    (STARTED) before the manager died re-delivers after restart through
    the stale-visibility requeue — the worker's completion was lost with
    the broker, so the job must run again, not vanish."""
    from dragonfly2_tpu.jobs.remote import RemoteJobClient, RemoteJobWorker

    mgr = _Manager(str(tmp_path), _free_port())
    mgr.start()
    try:
        client = RemoteJobClient(mgr.url)
        group = client.create_group("preheat", {"urls": ["u"]}, ["q-s"])
        gid = group["group_id"]
        # Pop WITHOUT reporting: the broker marks it STARTED durably.
        job = _post(mgr.url, "/api/v1/jobs:poll", {"queue": "q-s",
                                                   "timeout_s": 2})
        assert job["id"]
        mgr.sigkill()
        mgr.start()
        st = client.group_state(gid)
        assert st["jobs"][0]["state"] == "STARTED"  # reloaded as popped
        # A fresh poll inside the visibility window yields nothing...
        worker = RemoteJobWorker(mgr.url, "q-s", poll_timeout_s=0.3)
        worker.register("preheat", lambda args: "done")
        assert worker.poll_once() is False
        # ...and the broker's stale-started requeue re-delivers it once
        # the window passes (shrunk via the poll parameter).
        job2 = _post(mgr.url, "/api/v1/jobs:poll", {
            "queue": "q-s", "timeout_s": 2, "requeue_started_after_s": 0.01,
        })
        assert job2["id"] == job["id"]
    finally:
        mgr.stop()


def test_leader_sigkill_with_standby_fails_over_zero_pinning(tmp_path):
    """The Manager-HA acceptance drill (ISSUE 9 / DESIGN.md §20): the
    leader is SIGKILLed mid-preheat with a hot standby attached and is
    NEVER restarted —

    - the standby promotes itself on lease expiry (term 2);
    - the in-flight preheat completes through the promoted follower
      (job rows replicated, worker polls the endpoint pair);
    - the dynconfig payload and the model registry (row + digest-checked
      artifact) keep serving through the standby;
    - the ModelSubscriber's poll NEVER engages the PR-4 pin-to-last-
      ACTIVE degraded mode (``pinned`` stays False throughout).
    """
    import numpy as np

    from dragonfly2_tpu.jobs.remote import RemoteJobClient, RemoteJobWorker
    from dragonfly2_tpu.records.features import DOWNLOAD_FEATURE_DIM
    from dragonfly2_tpu.rpc.registry_client import RemoteRegistry
    from dragonfly2_tpu.scheduler import MLEvaluator, ModelSubscriber
    from dragonfly2_tpu.trainer.export import MLPScorer, scorer_to_bytes

    ha_yaml = (
        "ha: {enable: true, lease_ttl_s: 2.0, poll_interval_s: 0.25, "
        "lease_secret: drill-secret-0123456789abcdef}\n"
    )
    leader = _Manager(str(tmp_path), _free_port(), name="leader",
                      ha_yaml=ha_yaml)
    leader.start()
    standby = _Manager(
        str(tmp_path), _free_port(), name="standby", ha_yaml=ha_yaml,
        extra_args=["--replicate-from", leader.url],
    )
    standby.start()
    pair = f"{leader.url},{standby.url}"
    try:
        client = RemoteJobClient(pair)

        # --- stage the in-flight world on the LEADER --------------------
        group = client.create_group(
            "preheat", {"urls": ["https://origin/blob"]}, ["q-sched-a"]
        )
        gid = group["group_id"]
        rng = np.random.default_rng(0)
        weights = [(
            rng.standard_normal((DOWNLOAD_FEATURE_DIM, 1)).astype(np.float32),
            np.zeros(1, dtype=np.float32),
        )]
        artifact = scorer_to_bytes(MLPScorer(weights=weights))
        import base64

        created = _post(leader.url, "/api/v1/models", {
            "name": "parent-bandwidth-mlp", "type": "mlp",
            "scheduler_id": "sched-a",
            "artifact_b64": base64.b64encode(artifact).decode(),
        })
        _post(leader.url, f"/api/v1/models/{created['id']}:activate", {})

        # A subscriber polling through the endpoint pair, synced once
        # while the leader is alive.
        remote = RemoteRegistry(pair, timeout=5.0)
        subscriber = ModelSubscriber(
            remote, MLEvaluator(), scheduler_id="sched-a",
        )
        assert subscriber.refresh() is True
        assert subscriber.pinned is False

        # Give the follower a beat to tail the staged rows.
        deadline = time.time() + 15
        while time.time() < deadline:
            health = _get(standby.url, "/api/v1/replication:status")
            if health["applied_seq"] >= 1 and health["role"] == "standby":
                break
            time.sleep(0.2)

        # --- the crash: SIGKILL the leader, never restart it ------------
        leader.sigkill()

        # Reads fail over immediately (standby answers them pre-
        # promotion); the poll must NOT pin.
        assert subscriber.refresh() is False  # unchanged version
        assert subscriber.pinned is False, (
            "subscriber pinned with a live standby attached"
        )

        # The standby promotes on lease expiry and the in-flight preheat
        # completes THROUGH it.
        worker = RemoteJobWorker(pair, "q-sched-a", poll_timeout_s=0.5)
        done = {}
        worker.register(
            "preheat", lambda args: done.setdefault("urls", args["urls"])
        )
        deadline = time.time() + 30
        completed = False
        while time.time() < deadline and not completed:
            try:
                completed = worker.poll_once()
            except ConnectionError:
                time.sleep(0.3)
        assert completed, (
            "preheat never drained through the promoted follower",
            standby.lines[-10:],
        )
        assert done["urls"] == ["https://origin/blob"]
        assert client.group_state(gid)["state"] == "SUCCESS"

        # Promotion is observable: role leader, term advanced.
        status = _get(standby.url, "/api/v1/replication:status")
        assert status["role"] == "leader" and status["term"] >= 2

        # Registry row + digest-verified artifact through the survivor.
        model = remote.active_model("sched-a", "parent-bandwidth-mlp")
        assert model is not None
        assert remote.load_artifact(model) == artifact

        # Dynconfig payload (cluster config) still serving.
        cfg = _get(standby.url, "/api/v1/clusters/default:config")
        assert "scheduler_cluster_config" in cfg

        # And the subscriber STILL never pinned.
        subscriber.refresh()
        assert subscriber.pinned is False
    finally:
        leader.stop()
        standby.stop()


def test_legacy_sqlite_layouts_migrate_once(tmp_path):
    """Pre-seam deployments kept per-store files with typed tables; an
    upgraded manager imports them into the kv backend instead of
    silently booting empty — and never re-imports over newer rows."""
    import sqlite3

    from dragonfly2_tpu.manager.crud import CrudStore
    from dragonfly2_tpu.manager.registry import ModelRegistry
    from dragonfly2_tpu.manager.state import SQLiteBackend, migrate_legacy_sqlite
    from dragonfly2_tpu.manager.users import UserStore

    models_db = str(tmp_path / "manager.db")
    conn = sqlite3.connect(models_db)
    conn.execute(
        "CREATE TABLE models (id TEXT PRIMARY KEY, name TEXT, type TEXT, "
        "version INTEGER, scheduler_id TEXT, state TEXT, evaluation TEXT, "
        "blob_key TEXT, created_at REAL, updated_at REAL)"
    )
    conn.execute(
        "INSERT INTO models VALUES ('m1-v1','ranker','gnn',1,'s1',"
        "'active','{\"mae\": 0.5}','b1',1.0,2.0)"
    )
    conn.commit(); conn.close()

    crud_db = str(tmp_path / "crud.db")
    conn = sqlite3.connect(crud_db)
    conn.execute(
        "CREATE TABLE crud_rows (kind TEXT, id TEXT, value TEXT, "
        "PRIMARY KEY (kind, id))"
    )
    conn.execute(
        "INSERT INTO crud_rows VALUES ('application','a1',"
        "'{\"id\": \"a1\", \"name\": \"app\", \"url\": \"\", "
        "\"bio\": \"\", \"priority\": 1}')"
    )
    conn.commit(); conn.close()

    users_db = str(tmp_path / "users.db")
    legacy_users = UserStore(db_path=None)  # build hashes via the real path
    conn = sqlite3.connect(users_db)
    conn.execute(
        "CREATE TABLE users (id TEXT PRIMARY KEY, name TEXT, email TEXT, "
        "role INTEGER, state TEXT, password_hash BLOB, salt BLOB, "
        "created_at REAL)"
    )
    conn.execute(
        "INSERT INTO users VALUES ('user-1','root','', 2,'enabled',?,?,1.0)",
        (b"\x01\x02", b"\x03\x04"),
    )
    conn.execute(
        "CREATE TABLE pats (id TEXT PRIMARY KEY, user_id TEXT, name TEXT, "
        "role INTEGER, token_hash TEXT, expires_at REAL, revoked INTEGER, "
        "created_at REAL)"
    )
    conn.commit(); conn.close()

    backend = SQLiteBackend(str(tmp_path / "manager-state.db"))
    counts = migrate_legacy_sqlite(
        backend, models_db=models_db, crud_db=crud_db, users_db=users_db
    )
    assert counts == {"models": 1, "crud": 1, "users": 1}

    reg = ModelRegistry(backend=backend)
    m = reg.get("m1-v1")
    assert m and m.name == "ranker" and m.evaluation == {"mae": 0.5}
    crud = CrudStore(backend=backend)
    assert crud.get("application", "a1").priority == 1
    users = UserStore(backend=backend)
    assert users.by_name("root") is not None
    assert users._creds["user-1"] == (b"\x01\x02", b"\x03\x04")

    # Idempotent: a second boot (rows now present) imports nothing.
    assert migrate_legacy_sqlite(
        backend, models_db=models_db, crud_db=crud_db, users_db=users_db
    ) == {}


def test_crash_between_registry_flip_and_rollout_row(tmp_path):
    """DF014 crash-between-rows drill for the ``rollouts`` table: the
    registry flip (models table, transactional) and the rollout row
    (rollouts table) cannot share a transaction, so ``begin`` can crash
    AFTER the candidate went SHADOW but BEFORE its rollout row
    committed.  Without repair, every evaluation report would KeyError
    forever against a candidate the scheduler can see.  The reloaded
    controller must reconcile: adopt the orphan candidate so the
    rollout is judgeable again (declared invariant
    'no_dangling_rollout')."""
    from dragonfly2_tpu.manager.registry import ModelRegistry, ModelState
    from dragonfly2_tpu.manager.state import SQLiteBackend
    from dragonfly2_tpu.rollout.controller import RolloutController
    from dragonfly2_tpu.utils import faultinject

    db = str(tmp_path / "state.db")
    backend = SQLiteBackend(db)
    registry = ModelRegistry(backend=backend)
    active = registry.create_model(
        name="ranker", type="mlp", scheduler_id="s1", artifact=b"\x01" * 4,
    )
    registry.activate(active.id)
    candidate = registry.create_model(
        name="ranker", type="mlp", scheduler_id="s1", artifact=b"\x02" * 4,
    )
    controller = RolloutController(registry, backend=backend)
    inj = faultinject.FaultInjector([
        faultinject.FaultSpec(site="state.put.rollouts", kind="drop", at=(0,)),
    ])
    with faultinject.installed(inj):
        with pytest.raises(ConnectionError):
            controller.begin(candidate.id)
    # The tear is real: the registry committed the SHADOW flip, the
    # rollouts table has no row.
    assert registry.get(candidate.id).state is ModelState.SHADOW
    assert backend.table("rollouts").load_all() == {}
    backend.close()

    # Restart: reload BOTH consumers from the same file.
    backend = SQLiteBackend(db)
    registry2 = ModelRegistry(backend=backend)
    controller2 = RolloutController(registry2, backend=backend)
    rollout = controller2.get("s1", "ranker")
    assert rollout is not None, "orphan SHADOW candidate was not adopted"
    assert rollout.model_id == candidate.id
    assert rollout.phase == "shadow"
    assert rollout.previous_active_id == active.id
    # The adopted row is durable AND judgeable: a report flows.
    decision = controller2.report("s1", "ranker", {"joined_edges": 1})
    assert decision["decision"] == "hold"
    backend.close()

    # And the row survives the NEXT restart as a plain reload (no
    # re-adoption path needed).
    backend = SQLiteBackend(db)
    registry3 = ModelRegistry(backend=backend)
    controller3 = RolloutController(registry3, backend=backend)
    r3 = controller3.get("s1", "ranker")
    assert r3 is not None and r3.model_id == candidate.id
    assert r3.reason == "adopted during crash recovery"
    backend.close()


def test_crash_between_promote_and_rollout_row(tmp_path):
    """The other tear direction: ``_advance`` to ACTIVE commits the
    registry's single-active flip, then crashes before the rollout row
    records the phase.  On reload the row must follow the registry
    (phase 'active'), not replay the canary judgement."""
    from dragonfly2_tpu.manager.registry import ModelRegistry, ModelState
    from dragonfly2_tpu.manager.state import SQLiteBackend
    from dragonfly2_tpu.rollout.controller import (
        RolloutController, RolloutGuardrails,
    )
    from dragonfly2_tpu.utils import faultinject

    db = str(tmp_path / "state.db")
    backend = SQLiteBackend(db)
    registry = ModelRegistry(backend=backend)
    candidate = registry.create_model(
        name="ranker", type="mlp", scheduler_id="s1", artifact=b"\x02" * 4,
    )
    rails = RolloutGuardrails(min_shadow_samples=1, min_canary_samples=1)
    controller = RolloutController(registry, guardrails=rails, backend=backend)
    controller.begin(candidate.id)
    clean = {
        "joined_edges": 10,
        "regret_at_k": {"candidate": 0.0, "active": 0.0, "k": 3},
        "inversion_rate": {"candidate": 0.0, "active": 0.0},
        "psi_max": 0.0,
    }
    assert controller.report("s1", "ranker", clean)["decision"] == "advance"
    # Promote: the registry flip (put_many on models) commits, the
    # rollout-row put is dropped.
    clean2 = dict(clean, joined_edges=20)
    inj = faultinject.FaultInjector([
        faultinject.FaultSpec(site="state.put.rollouts", kind="drop", at=(0,)),
    ])
    with faultinject.installed(inj):
        with pytest.raises(ConnectionError):
            controller.report("s1", "ranker", clean2)
    assert registry.get(candidate.id).state is ModelState.ACTIVE
    backend.close()

    backend = SQLiteBackend(db)
    registry2 = ModelRegistry(backend=backend)
    controller2 = RolloutController(registry2, backend=backend)
    rollout = controller2.get("s1", "ranker")
    assert rollout is not None
    assert rollout.phase == "active", (
        "rollout row must follow the committed registry promote",
        rollout.phase,
    )
    assert "reconciled" in rollout.reason
    backend.close()
