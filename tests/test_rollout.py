"""Model rollout & quality plane (ISSUE 4, DESIGN.md §15).

Covers the new subsystem end to end:

- ShadowScorer: deterministic sampling, zero-copy reuse of the serving
  feature matrix, replay-log contents, bounded-queue drops, seq resume,
  PSI drift against blob-stamped training snapshots;
- replay evaluation: outcome join, regret@k, pairwise inversions (all
  vs naive references);
- canary serving: deterministic bucketing, per-arm batcher dispatch,
  atomic pin-to-active when the candidate vanishes mid-queue;
- ModelSubscriber satellites: seeded ±jitter poll spread, digest-refused
  corrupted artifacts, candidate install/promote/drop, manager-loss pin;
- RolloutController: guardrail holds/advances/rollbacks, post-promotion
  auto-rollback to last-good, StateBackend persistence;
- registry lifecycle durability: activation crash atomicity, dangling
  active pointer on delete, artifact digest verification;
- the two acceptance drills: injected-regression auto-rollback and
  manager-kill-mid-canary pinning, both read out of rollout_state
  metrics;
- tools/bench_shadow.py --smoke JSON schema gate (tier-1).
"""

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]

from dragonfly2_tpu.manager import ArtifactDigestError, ModelRegistry, ModelState
from dragonfly2_tpu.manager.registry import BlobStore
from dragonfly2_tpu.records.columnar import ColumnarWriter
from dragonfly2_tpu.records.features import (
    DOWNLOAD_COLUMNS,
    DOWNLOAD_FEATURE_DIM,
)
from dragonfly2_tpu.rollout import (
    LocalRolloutClient,
    RolloutController,
    RolloutGuardrails,
    RolloutPhase,
    RolloutReporter,
    ShadowScorer,
    evaluate_shadow,
    join_outcomes,
    pairwise_inversion_rate,
    population_stability_index,
    regret_at_k,
)
from dragonfly2_tpu.rollout import metrics as rollout_metrics
from dragonfly2_tpu.rollout.shadow import SHADOW_COLUMNS, sampled
from dragonfly2_tpu.scheduler import (
    CanaryRoute,
    HostFeatureCache,
    MLEvaluator,
    ModelSubscriber,
    ScorerBatcher,
)
from dragonfly2_tpu.scheduler import metrics as sched_metrics
from dragonfly2_tpu.sim.swarm import build_announce_swarm
from dragonfly2_tpu.trainer.export import (
    MLPScorer,
    feature_snapshot_stats,
    load_scorer,
    scorer_to_bytes,
)

MODEL_NAME = "parent-bandwidth-mlp"

_COL = {name: i for i, name in enumerate(SHADOW_COLUMNS)}


def _mk_weights(seed, invert=False):
    rng = np.random.default_rng(seed)
    dims = (DOWNLOAD_FEATURE_DIM, 16, 1)
    ws = [
        (
            rng.standard_normal((dims[i], dims[i + 1])).astype(np.float32) * 0.3,
            rng.standard_normal(dims[i + 1]).astype(np.float32) * 0.05,
        )
        for i in range(len(dims) - 1)
    ]
    if invert:
        ws[-1] = (-ws[-1][0], -ws[-1][1])
    return ws


def _mk_scorer(seed, invert=False, **kw):
    return MLPScorer(weights=_mk_weights(seed, invert), **kw)


class _ConstScorer:
    """Scores row i as base + step*i — rankings are predictable."""

    def __init__(self, base=0.0, step=1.0):
        self.base, self.step = base, step
        self.calls = 0

    def score(self, features, **_buckets):
        self.calls += 1
        n = features.shape[0]
        return self.base + self.step * np.arange(n, dtype=np.float64)


def _drive_announces(ml, task, peers, count=30, parents=8, start=0):
    for i in range(start, start + count):
        child = peers[i % len(peers)]
        cands = [peers[(i + j + 1) % len(peers)] for j in range(parents)]
        ml.evaluate_parents(cands, child, task.total_piece_count)


def _write_download_rows(path, src, dst, target_log_bw):
    rows = np.zeros((len(src), len(DOWNLOAD_COLUMNS)), np.float32)
    rows[:, 0] = src
    rows[:, 1] = dst
    rows[:, -1] = target_log_bw
    with ColumnarWriter(path, DOWNLOAD_COLUMNS) as w:
        w.append(rows)


class _StorageStub:
    """Just enough of records.storage.Storage for RolloutReporter."""

    def __init__(self, paths):
        self._paths = list(paths)

    def download_columnar_paths(self):
        return list(self._paths)


# ---------------------------------------------------------------------------
# ShadowScorer
# ---------------------------------------------------------------------------


class TestShadowScorer:
    def test_sampling_is_deterministic_and_respects_rate(self):
        picks = [sampled("child-7", seq, 0.1) for seq in range(5000)]
        assert picks == [sampled("child-7", seq, 0.1) for seq in range(5000)]
        frac = sum(picks) / len(picks)
        assert 0.07 < frac < 0.13
        assert not any(sampled("c", s, 0.0) for s in range(100))
        assert all(sampled("c", s, 1.0) for s in range(100))

    def test_candidate_scores_the_exact_serving_matrix(self):
        seen = []

        class Recorder:
            def score(self, features, **_b):
                seen.append(features)
                return np.zeros(features.shape[0])

        sh = ShadowScorer(Recorder(), candidate_version=2, sample_rate=1.0)
        feats = np.random.default_rng(0).standard_normal((6, 4)).astype(np.float32)
        assert sh.offer("c", feats, np.arange(6), np.zeros(6, np.int64),
                        np.arange(6, dtype=float))
        assert sh.drain()
        sh.close()
        # Zero extra featurization: the worker scored the VERY array the
        # announce path built, not a copy.
        assert len(seen) == 1 and seen[0] is feats

    def test_replay_log_rows_and_ranks(self):
        sh = ShadowScorer(
            _ConstScorer(step=1.0),  # candidate prefers HIGH index
            candidate_version=3, active_version=1, sample_rate=1.0,
        )
        active_scores = np.array([5.0, 1.0, 3.0])  # active rank: 0,2,1
        sh.offer("c", np.zeros((3, 2), np.float32), np.array([11, 12, 13]),
                 np.array([7, 7, 7]), active_scores)
        sh.drain()
        sh.close()
        rows = sh.replay_rows()
        assert rows.shape == (3, len(SHADOW_COLUMNS))
        assert rows[0, _COL["candidate_version"]] == 3.0
        assert rows[0, _COL["active_version"]] == 1.0
        assert list(rows[:, _COL["src_bucket"]]) == [11.0, 12.0, 13.0]
        assert list(rows[:, _COL["active_rank"]]) == [0.0, 2.0, 1.0]
        # candidate = ascending scores → best is the LAST row.
        assert list(rows[:, _COL["candidate_rank"]]) == [2.0, 1.0, 0.0]

    def test_batched_drain_matches_per_sample_ranks(self):
        # A long worker linger forces ONE drain over many announces: the
        # vectorized lexsort rank path must agree with per-sample
        # stable argsort ranks.
        rng = np.random.default_rng(3)
        cand = _mk_scorer(4)
        sh = ShadowScorer(cand, candidate_version=2, sample_rate=1.0,
                          batch_linger_s=0.25)
        per_announce = []
        for a in range(6):
            n = 4 + a  # varying group sizes
            feats = rng.standard_normal((n, DOWNLOAD_FEATURE_DIM)).astype(np.float32)
            active = rng.standard_normal(n)
            sh.offer(f"c{a}", feats, np.arange(n, dtype=np.int64) + 100 * a,
                     np.full(n, a, np.int64), active)
            per_announce.append((feats, active))
        sh.drain(timeout=10.0)
        rows = sh.replay_rows()
        sh.close()
        assert rows.shape[0] == sum(4 + a for a in range(6))
        for a, (feats, active) in enumerate(per_announce):
            grp = rows[rows[:, _COL["dst_bucket"]] == a]
            cand_scores = cand.score(feats)
            n = len(active)
            exp_a = np.empty(n, np.int64)
            exp_a[np.argsort(-active, kind="stable")] = np.arange(n)
            exp_c = np.empty(n, np.int64)
            exp_c[np.argsort(-cand_scores, kind="stable")] = np.arange(n)
            assert list(grp[:, _COL["active_rank"]]) == list(exp_a.astype(float))
            assert list(grp[:, _COL["candidate_rank"]]) == list(exp_c.astype(float))
            assert np.allclose(grp[:, _COL["candidate_score"]], cand_scores,
                               rtol=1e-5)

    def test_bounded_queue_drops_instead_of_blocking(self):
        release = threading.Event()

        class Slow:
            def score(self, features, **_b):
                release.wait(5.0)
                return np.zeros(features.shape[0])

        sh = ShadowScorer(Slow(), candidate_version=2, sample_rate=1.0,
                          max_queue=1)
        feats = np.zeros((2, 2), np.float32)
        args = (np.zeros(2, np.int64), np.zeros(2, np.int64), np.zeros(2))
        for _ in range(6):
            sh.offer("c", feats, *args)
        release.set()
        sh.drain()
        stats = sh.stats()
        sh.close()
        assert stats["dropped"] > 0
        assert stats["offered"] == 6
        assert stats["scored_announces"] + stats["dropped"] == 6

    def test_seq_resumes_past_existing_log(self, tmp_path):
        log = str(tmp_path / "shadow.dfc")
        sh = ShadowScorer(_ConstScorer(), candidate_version=2,
                          sample_rate=1.0, log_path=log)
        sh.offer("c", np.zeros((2, 2), np.float32), np.zeros(2, np.int64),
                 np.zeros(2, np.int64), np.zeros(2))
        sh.drain()
        sh.close()
        sh2 = ShadowScorer(_ConstScorer(), candidate_version=2,
                           sample_rate=1.0, log_path=log)
        assert sh2.offered == 1  # continues past logged announce_seq 0
        sh2.offer("c", np.zeros((2, 2), np.float32), np.zeros(2, np.int64),
                  np.zeros(2, np.int64), np.zeros(2))
        sh2.drain()
        sh2.close()
        rows = sh2.replay_rows()
        assert set(rows[:, _COL["announce_seq"]]) == {0.0, 1.0}

    def test_psi_flags_shifted_serving_distribution(self):
        rng = np.random.default_rng(0)
        train = rng.standard_normal((4000, 5)).astype(np.float32)
        edges, fracs = feature_snapshot_stats(train)
        cand = _ConstScorer()
        cand.train_bin_edges, cand.train_bin_fracs = edges, fracs
        cand.post_hoc_masked = False

        def feed(sh, rows):
            sh.offer("c", rows, np.zeros(len(rows), np.int64),
                     np.zeros(len(rows), np.int64), np.zeros(len(rows)))
            sh.drain()

        same = ShadowScorer(cand, candidate_version=2, sample_rate=1.0)
        feed(same, rng.standard_normal((2000, 5)).astype(np.float32))
        psi_same = same.psi()
        same.close()
        assert psi_same is not None and psi_same.max() < 0.05

        shifted = ShadowScorer(cand, candidate_version=2, sample_rate=1.0)
        feed(shifted, (rng.standard_normal((2000, 5)) + 2.0).astype(np.float32))
        psi_shift = shifted.psi()
        shifted.close()
        assert psi_shift.max() > 1.0

    def test_psi_none_without_snapshot(self):
        sh = ShadowScorer(_ConstScorer(), candidate_version=2, sample_rate=1.0)
        assert sh.psi() is None
        assert sh.stats()["psi_max"] is None
        sh.close()


# ---------------------------------------------------------------------------
# Replay evaluation
# ---------------------------------------------------------------------------


def _shadow_rows(per_announce, announces, cand_rank_fn, active_rank_fn,
                 version=2):
    """Synthesize a replay log: one group per announce."""
    rows = []
    for a in range(announces):
        n = per_announce
        r = np.zeros((n, len(SHADOW_COLUMNS)), np.float32)
        r[:, _COL["announce_seq"]] = a
        r[:, _COL["candidate_version"]] = version
        r[:, _COL["src_bucket"]] = np.arange(n) + a * n
        r[:, _COL["dst_bucket"]] = 99_000 + a
        r[:, _COL["active_rank"]] = active_rank_fn(n)
        r[:, _COL["candidate_rank"]] = cand_rank_fn(n)
        rows.append(r)
    return np.concatenate(rows, axis=0)


class TestReplayEvaluation:
    def test_join_outcomes_matches_and_averages(self, tmp_path):
        sh = np.zeros((3, len(SHADOW_COLUMNS)), np.float32)
        sh[:, _COL["src_bucket"]] = [1, 2, 3]
        sh[:, _COL["dst_bucket"]] = [9, 9, 9]
        dl = np.zeros((3, len(DOWNLOAD_COLUMNS)), np.float32)
        dl[:, 0] = [1, 1, 2]   # src
        dl[:, 1] = [9, 9, 9]   # dst
        dl[:, -1] = [10.0, 20.0, 7.0]
        realized = join_outcomes(sh, dl)
        assert realized[0] == pytest.approx(15.0)  # duplicate pair averaged
        assert realized[1] == pytest.approx(7.0)
        assert np.isnan(realized[2])               # no record for (3, 9)

    def test_regret_perfect_vs_inverted(self):
        n, announces, k = 8, 10, 4
        rows = _shadow_rows(
            n, announces,
            cand_rank_fn=lambda n: np.arange(n)[::-1],  # candidate inverted
            active_rank_fn=lambda n: np.arange(n),      # active = ideal
        )
        # Realized bandwidth decreasing with index → active rank order is
        # exactly the realized order.
        realized = np.log1p(
            np.tile(np.linspace(100.0, 10.0, n), announces)
        )
        out = regret_at_k(rows, realized, k=k)
        assert out["announces"] == announces
        assert out["active"] == pytest.approx(0.0, abs=1e-9)
        bw = np.linspace(100.0, 10.0, n)
        expected = 1.0 - bw[-k:].mean() / bw[:k].mean()
        assert out["candidate"] == pytest.approx(expected, rel=1e-6)

    def test_regret_ignores_unjoined_and_tiny_groups(self):
        rows = _shadow_rows(2, 3, lambda n: np.arange(n), lambda n: np.arange(n))
        realized = np.full(rows.shape[0], np.nan)
        realized[0] = 5.0  # one joined edge → group too small to score
        out = regret_at_k(rows, realized, k=2)
        assert out["announces"] == 0

    def test_inversion_rate_hand_example(self):
        rows = _shadow_rows(
            3, 1,
            cand_rank_fn=lambda n: np.array([2, 1, 0]),  # prefers worst
            active_rank_fn=lambda n: np.array([0, 1, 2]),
        )
        realized = np.log1p(np.array([30.0, 20.0, 10.0]))
        out = pairwise_inversion_rate(rows, realized)
        assert out["pairs"] == 3
        assert out["active"] == 0.0
        assert out["candidate"] == 1.0

    def test_psi_formula_sanity(self):
        expected = np.array([[0.25, 0.25, 0.25, 0.25]])
        same = population_stability_index(expected, np.array([[25, 25, 25, 25]]))
        skew = population_stability_index(expected, np.array([[97, 1, 1, 1]]))
        assert same[0] == pytest.approx(0.0, abs=1e-9)
        assert skew[0] > 1.0

    def test_evaluate_shadow_report_shape(self):
        rows = _shadow_rows(4, 5, lambda n: np.arange(n), lambda n: np.arange(n))
        dl = np.zeros((rows.shape[0], len(DOWNLOAD_COLUMNS)), np.float32)
        dl[:, 0] = rows[:, _COL["src_bucket"]]
        dl[:, 1] = rows[:, _COL["dst_bucket"]]
        dl[:, -1] = 5.0
        report = evaluate_shadow(rows, dl, k=2, psi_max=0.03)
        assert report["joined_edges"] == rows.shape[0]
        assert report["announces"] == 5
        assert report["psi_max"] == 0.03
        assert report["regret_at_k"]["k"] == 2
        assert report["candidate_version"] == 2


# ---------------------------------------------------------------------------
# Canary serving
# ---------------------------------------------------------------------------


class TestCanaryServing:
    def test_bucketing_deterministic_and_proportional(self):
        route = CanaryRoute(_ConstScorer(), percent=10, version=2)
        ids = [f"host-{i}" for i in range(4000)]
        picks = [route.routes_to_candidate(h) for h in ids]
        assert picks == [route.routes_to_candidate(h) for h in ids]
        frac = sum(picks) / len(picks)
        assert 0.07 < frac < 0.13
        assert not any(
            CanaryRoute(None, 0, 2).routes_to_candidate(h) for h in ids[:200]
        )

    def test_evaluator_routes_arms_and_counts(self):
        task, peers = build_announce_swarm(40, seed=5)
        active = _ConstScorer(step=1.0)      # prefers LAST candidate
        candidate = _ConstScorer(step=-1.0)  # prefers FIRST candidate
        ml = MLEvaluator(active)
        ml.set_canary(CanaryRoute(candidate, percent=50, version=2))
        before = {
            arm: sched_metrics.CANARY_ANNOUNCES_TOTAL.value(arm=arm)
            for arm in ("candidate", "active")
        }
        routed = unrouted = 0
        for i in range(20):
            child, cands = peers[i], [peers[(i + j + 1) % 40] for j in range(5)]
            ranked = ml.evaluate_parents(cands, child, task.total_piece_count)
            if ml.canary.routes_to_candidate(child.host.id):
                routed += 1
                assert [p.id for p in ranked] == [p.id for p in cands]
            else:
                unrouted += 1
                assert [p.id for p in ranked] == [p.id for p in cands[::-1]]
        assert routed and unrouted  # both arms exercised
        assert (
            sched_metrics.CANARY_ANNOUNCES_TOTAL.value(arm="candidate")
            - before["candidate"]
        ) == routed
        assert (
            sched_metrics.CANARY_ANNOUNCES_TOTAL.value(arm="active")
            - before["active"]
        ) == unrouted

    def test_batcher_splits_arms_one_flush(self):
        active = _ConstScorer(step=1.0)
        candidate = _ConstScorer(step=-1.0)
        b = ScorerBatcher(active, linger_s=0.05)
        b.set_candidate(candidate)
        results = {}

        def call(arm, flag):
            feats = np.zeros((4, 3), np.float32)
            results[arm] = np.asarray(b.score(feats, candidate=flag))

        threads = [
            threading.Thread(target=call, args=("active", False), daemon=True),
            threading.Thread(target=call, args=("candidate", True), daemon=True),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert list(np.argsort(-results["active"])) == [3, 2, 1, 0]
        assert list(np.argsort(-results["candidate"])) == [0, 1, 2, 3]
        # Each arm's scorer was called exactly once: per-arm coalescing,
        # never a mixed-version call.
        assert active.calls == 1 and candidate.calls == 1

    def test_batcher_pins_candidate_requests_when_candidate_gone(self):
        active = _ConstScorer(step=1.0)
        b = ScorerBatcher(active, linger_s=0.0)
        # No candidate installed but a canary-tagged request arrives (the
        # canary was uninstalled mid-flight): pin to active, no error.
        scores = np.asarray(b.score(np.zeros((3, 2), np.float32), candidate=True))
        assert list(np.argsort(-scores)) == [2, 1, 0]
        assert active.calls == 1


# ---------------------------------------------------------------------------
# ModelSubscriber satellites: jitter, digest refusal, pinning
# ---------------------------------------------------------------------------


class TestSubscriberJitter:
    def test_intervals_spread_within_bounds_and_across_instances(self):
        ml = MLEvaluator(None)
        a = ModelSubscriber(ModelRegistry(), ml, scheduler_id="sched-a",
                            refresh_interval=300.0, jitter=0.1)
        b = ModelSubscriber(ModelRegistry(), ml, scheduler_id="sched-b",
                            refresh_interval=300.0, jitter=0.1)
        seq_a = [a._next_interval() for _ in range(64)]
        seq_b = [b._next_interval() for _ in range(64)]
        for v in seq_a + seq_b:
            assert 270.0 <= v <= 330.0  # ±10 %
        # Decorrelated across the fleet and non-constant per instance —
        # the herd actually spreads.
        assert seq_a != seq_b
        assert len(set(round(v, 6) for v in seq_a)) > 32
        # Reproducible for one identity (seeded RNG).
        a2 = ModelSubscriber(ModelRegistry(), ml, scheduler_id="sched-a",
                             refresh_interval=300.0, jitter=0.1)
        assert [a2._next_interval() for _ in range(64)] == seq_a

    def test_zero_jitter_keeps_fixed_cadence(self):
        sub = ModelSubscriber(ModelRegistry(), MLEvaluator(None),
                              scheduler_id="s", jitter=0.0)
        assert sub._next_interval() == sub.refresh_interval


class TestArtifactDigest:
    def _registry_with_model(self, tmp_path):
        blobs = BlobStore(str(tmp_path / "blobs"))
        reg = ModelRegistry(blobs)
        m = reg.create_model(name=MODEL_NAME, type="mlp", scheduler_id="s1",
                             artifact=scorer_to_bytes(_mk_scorer(1)))
        return reg, blobs, m

    def test_digest_recorded_and_verified(self, tmp_path):
        reg, blobs, m = self._registry_with_model(tmp_path)
        assert len(m.artifact_digest) == 64
        assert load_scorer(reg.load_artifact(m)) is not None
        blobs.put(m.blob_key, b"corrupted bytes")
        with pytest.raises(ArtifactDigestError):
            reg.load_artifact(m)

    def test_subscriber_refuses_corrupted_blob_keeps_current(self, tmp_path):
        reg, blobs, m1 = self._registry_with_model(tmp_path)
        reg.activate(m1.id)
        ml = MLEvaluator(None)
        sub = ModelSubscriber(reg, ml, scheduler_id="s1")
        assert sub.refresh() is True
        serving = ml._scorer
        assert serving is not None
        # v2 lands corrupted: the swap must be REFUSED and v1 kept.
        m2 = reg.create_model(name=MODEL_NAME, type="mlp", scheduler_id="s1",
                              artifact=scorer_to_bytes(_mk_scorer(2)))
        blobs.put(m2.blob_key, b"\x00" * 64)
        reg.activate(m2.id)
        assert sub.refresh() is False
        assert ml._scorer is serving
        assert sub._loaded_version == m1.version

    def test_legacy_rows_without_digest_still_load(self, tmp_path):
        reg, blobs, m = self._registry_with_model(tmp_path)
        m.artifact_digest = ""  # a pre-digest row
        blobs.put(m.blob_key, b"whatever")  # cannot be verified
        assert reg.load_artifact(m) == b"whatever"


# ---------------------------------------------------------------------------
# Registry lifecycle durability (satellite)
# ---------------------------------------------------------------------------


class TestRegistryDurability:
    def test_activate_crash_between_writes_never_splits_active(self, tmp_path):
        from dragonfly2_tpu.utils import faultinject
        from dragonfly2_tpu.utils.faultinject import FaultInjector, FaultSpec

        db = str(tmp_path / "m.db")
        blobs = str(tmp_path / "blobs")
        reg = ModelRegistry(BlobStore(blobs), db_path=db)
        m1 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"1")
        m2 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"2")
        reg.activate(m1.id)
        # The very next models write dies (the crash-between-rows probe:
        # put_many is one transaction, so the flip either fully lands or
        # never does — there is no "between" to tear).
        inj = FaultInjector([FaultSpec(site="state.put.models", kind="drop",
                                       at=(0,))])
        with faultinject.installed(inj):
            with pytest.raises(ConnectionError):
                reg.activate(m2.id)
        # Reload from the backend, as a restarted manager would.
        reg2 = ModelRegistry(BlobStore(blobs), db_path=db)
        active = [m for m in reg2.list(scheduler_id="s", name="m")
                  if m.state is ModelState.ACTIVE]
        assert [m.id for m in active] == [m1.id]

    def test_delete_active_leaves_no_dangling_pointer(self, tmp_path):
        db = str(tmp_path / "m.db")
        blobs = str(tmp_path / "blobs")
        reg = ModelRegistry(BlobStore(blobs), db_path=db)
        m1 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"1")
        m2 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"2")
        reg.activate(m2.id)
        reg.delete(m2.id)
        reg3 = ModelRegistry(BlobStore(blobs), db_path=db)
        assert reg3.active_model("s", "m") is None
        assert [m.id for m in reg3.list(scheduler_id="s", name="m")] == [m1.id]
        # The survivor can be activated cleanly after the reload.
        reg3.activate(m1.id)
        assert reg3.active_model("s", "m").id == m1.id

    def test_candidate_states_exclusive_and_persisted(self, tmp_path):
        db = str(tmp_path / "m.db")
        blobs = str(tmp_path / "blobs")
        reg = ModelRegistry(BlobStore(blobs), db_path=db)
        m1 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"1")
        m2 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"2")
        reg.set_state(m1.id, ModelState.SHADOW)
        reg.set_state(m2.id, ModelState.CANARY)  # demotes m1
        reg2 = ModelRegistry(BlobStore(blobs), db_path=db)
        assert reg2.get(m1.id).state is ModelState.INACTIVE
        assert reg2.get(m2.id).state is ModelState.CANARY
        assert reg2.candidate_model("s", "m").id == m2.id


# ---------------------------------------------------------------------------
# Rollout controller
# ---------------------------------------------------------------------------


def _registry_v1_active_v2(reg=None, invert_v2=True, sched="s1", v2_seed=2):
    reg = reg or ModelRegistry()
    m1 = reg.create_model(name=MODEL_NAME, type="mlp", scheduler_id=sched,
                          artifact=scorer_to_bytes(_mk_scorer(1)))
    reg.activate(m1.id)
    m2 = reg.create_model(
        name=MODEL_NAME, type="mlp", scheduler_id=sched,
        artifact=scorer_to_bytes(_mk_scorer(v2_seed, invert=invert_v2)),
    )
    return reg, m1, m2


def _report(joined=500, cand_regret=0.1, active_regret=0.1,
            cand_inv=0.2, active_inv=0.2, psi=0.01):
    return {
        "joined_edges": joined,
        "announces": joined // 4,
        "regret_at_k": {"k": 4, "candidate": cand_regret, "active": active_regret},
        "inversion_rate": {"pairs": joined, "candidate": cand_inv,
                           "active": active_inv},
        "psi_max": psi,
    }


class TestRolloutController:
    def test_begin_flips_to_shadow_and_records_last_good(self):
        reg, m1, m2 = _registry_v1_active_v2()
        ctrl = RolloutController(reg)
        r = ctrl.begin(m2.id)
        assert reg.get(m2.id).state is ModelState.SHADOW
        assert r.previous_active_id == m1.id
        assert r.phase == RolloutPhase.SHADOW.value
        with pytest.raises(ValueError):
            ctrl.begin(m1.id)  # already active

    def test_hold_below_sample_floor(self):
        reg, m1, m2 = _registry_v1_active_v2()
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=100))
        ctrl.begin(m2.id)
        out = ctrl.report("s1", MODEL_NAME, _report(joined=10))
        assert out["decision"] == "hold"
        assert reg.get(m2.id).state is ModelState.SHADOW

    def test_clean_reports_walk_shadow_canary_active(self):
        reg, m1, m2 = _registry_v1_active_v2()
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=50, min_canary_samples=50, canary_percent=25))
        ctrl.begin(m2.id)
        out = ctrl.report("s1", MODEL_NAME, _report(joined=60))
        assert out["decision"] == "advance" and out["canary_percent"] == 25
        assert reg.get(m2.id).state is ModelState.CANARY
        # Canary needs NEW samples past the phase baseline.
        out = ctrl.report("s1", MODEL_NAME, _report(joined=80))
        assert out["decision"] == "hold"
        out = ctrl.report("s1", MODEL_NAME, _report(joined=130))
        assert out["decision"] == "promote"
        assert reg.get(m2.id).state is ModelState.ACTIVE
        assert reg.get(m1.id).state is ModelState.INACTIVE
        assert ctrl.get("s1", MODEL_NAME).phase == RolloutPhase.ACTIVE.value

    def test_regret_breach_rolls_back_candidate(self):
        reg, m1, m2 = _registry_v1_active_v2()
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=50))
        ctrl.begin(m2.id)
        out = ctrl.report("s1", MODEL_NAME,
                          _report(joined=100, cand_regret=0.5, active_regret=0.1))
        assert out["decision"] == "rollback"
        assert "regret" in out["reason"]
        assert reg.get(m2.id).state is ModelState.INACTIVE
        assert reg.active_model("s1", MODEL_NAME).id == m1.id
        assert rollout_metrics.ROLLOUT_STATE.value(
            scheduler_id="s1", name=MODEL_NAME) == 5.0
        # Further reports answer rolled_back without judging again.
        out = ctrl.report("s1", MODEL_NAME, _report(joined=200))
        assert out["decision"] == "rolled_back"

    def test_psi_breach_rolls_back(self):
        reg, m1, m2 = _registry_v1_active_v2()
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=10, max_psi=0.25))
        ctrl.begin(m2.id)
        out = ctrl.report("s1", MODEL_NAME, _report(joined=50, psi=0.9))
        assert out["decision"] == "rollback" and "drift" in out["reason"]

    def test_post_promotion_regression_reactivates_last_good(self):
        reg, m1, m2 = _registry_v1_active_v2()
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=10, min_canary_samples=10))
        ctrl.begin(m2.id)
        ctrl.report("s1", MODEL_NAME, _report(joined=20))
        ctrl.report("s1", MODEL_NAME, _report(joined=40))
        assert reg.active_model("s1", MODEL_NAME).id == m2.id
        out = ctrl.report("s1", MODEL_NAME,
                          _report(joined=60, cand_regret=0.9, active_regret=0.1))
        assert out["decision"] == "rollback"
        assert reg.active_model("s1", MODEL_NAME).id == m1.id
        assert reg.get(m2.id).state is ModelState.INACTIVE

    def test_rollouts_persist_across_controller_restart(self, tmp_path):
        from dragonfly2_tpu.manager.state import SQLiteBackend

        backend = SQLiteBackend(str(tmp_path / "state.db"))
        reg, m1, m2 = _registry_v1_active_v2()
        ctrl = RolloutController(reg, backend=backend,
                                 guardrails=RolloutGuardrails(min_shadow_samples=10))
        ctrl.begin(m2.id)
        ctrl.report("s1", MODEL_NAME, _report(joined=20))
        ctrl2 = RolloutController(reg, backend=backend,
                                  guardrails=RolloutGuardrails(min_canary_samples=10))
        r = ctrl2.get("s1", MODEL_NAME)
        assert r is not None and r.phase == RolloutPhase.CANARY.value
        assert r.previous_active_id == m1.id
        out = ctrl2.report("s1", MODEL_NAME, _report(joined=40))
        assert out["decision"] == "promote"


# ---------------------------------------------------------------------------
# Subscriber ↔ rollout integration + reporter
# ---------------------------------------------------------------------------


def _serving_stack(reg, ctrl, shadow_rate=1.0, linger=0.0):
    ml = MLEvaluator(
        None,
        feature_cache=HostFeatureCache(max_hosts=1024),
        batcher=ScorerBatcher(linger_s=linger),
    )
    sub = ModelSubscriber(
        reg, ml, scheduler_id="s1",
        rollout_client=LocalRolloutClient(ctrl),
        shadow_sample_rate=shadow_rate,
    )
    return ml, sub


class TestSubscriberRolloutIntegration:
    def test_candidate_installs_shadow_then_canary_then_promotes(self):
        reg, m1, m2 = _registry_v1_active_v2(invert_v2=False)
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=1, min_canary_samples=1, canary_percent=30))
        ml, sub = _serving_stack(reg, ctrl)
        sub.refresh()
        assert ml.shadow is None  # no rollout yet
        ctrl.begin(m2.id)
        sub.refresh()
        assert ml.shadow is not None and ml.canary is None
        assert sched_metrics.ROLLOUT_SERVING_STATE.value(name=MODEL_NAME) == 2.0
        ctrl.report("s1", MODEL_NAME, _report(joined=5))
        sub.refresh()
        assert ml.canary is not None and ml.canary.percent == 30
        assert sched_metrics.ROLLOUT_SERVING_STATE.value(name=MODEL_NAME) == 3.0
        ctrl.report("s1", MODEL_NAME, _report(joined=10))
        sub.refresh()
        # Promoted: candidate became the active scorer, rollout state clear.
        assert ml.canary is None and ml.shadow is None
        assert sub._loaded_version == m2.version
        assert sched_metrics.ROLLOUT_SERVING_STATE.value(name=MODEL_NAME) == 0.0
        sub.stop()

    def test_reporter_cycle_reports_and_applies(self, tmp_path):
        # v2 = same weights as v1 (a clean retrain): rankings agree, so
        # outcome-joined quality cannot show a regression.
        reg, m1, m2 = _registry_v1_active_v2(invert_v2=False, v2_seed=1)
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=1, min_canary_samples=10**9))
        ctrl.begin(m2.id)
        ml, sub = _serving_stack(reg, ctrl)
        sub.refresh()
        task, peers = build_announce_swarm(40, seed=7)
        _drive_announces(ml, task, peers, count=25, parents=6)
        ml.shadow.drain()
        rows = ml.shadow.replay_rows()
        dl_path = str(tmp_path / "download.dfc")
        # Outcomes that agree with the ACTIVE scores → no regression.
        _write_download_rows(
            dl_path, rows[:, _COL["src_bucket"]], rows[:, _COL["dst_bucket"]],
            np.log1p(1000.0 - rows[:, _COL["active_rank"]] * 10.0),
        )
        reporter = RolloutReporter(
            sub, _StorageStub([dl_path]), LocalRolloutClient(ctrl))
        out = reporter.run_once()
        assert out is not None
        assert out["decision"]["decision"] == "advance"
        assert out["report"]["joined_edges"] > 0
        assert ml.canary is not None  # refresh applied the canary
        sub.stop()

    def test_reporter_none_without_shadow(self):
        reg, m1, m2 = _registry_v1_active_v2()
        ctrl = RolloutController(reg)
        ml, sub = _serving_stack(reg, ctrl)
        sub.refresh()
        reporter = RolloutReporter(sub, _StorageStub([]), LocalRolloutClient(ctrl))
        assert reporter.run_once() is None
        sub.stop()


# ---------------------------------------------------------------------------
# Acceptance drill 1: injected regression → automatic rollback
# ---------------------------------------------------------------------------


class TestRegressionAutoRollbackDrill:
    def test_injected_regression_candidate_rolls_back(self, tmp_path):
        # v1 active; v2 is v1 with the output layer INVERTED — a maximal
        # ranking regression that shadow evaluation must catch before it
        # ever serves an announce.
        reg, m1, m2 = _registry_v1_active_v2(invert_v2=True)
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=50))
        ctrl.begin(m2.id)
        ml, sub = _serving_stack(reg, ctrl)
        sub.refresh()
        assert ml.shadow is not None

        task, peers = build_announce_swarm(60, seed=11)
        _drive_announces(ml, task, peers, count=40, parents=8)
        ml.shadow.drain()
        rows = ml.shadow.replay_rows()
        assert rows.shape[0] >= 50
        # Ground truth sides with the ACTIVE model: realized bandwidth
        # decreases with active rank (the model in production is good).
        dl_path = str(tmp_path / "download.dfc")
        _write_download_rows(
            dl_path, rows[:, _COL["src_bucket"]], rows[:, _COL["dst_bucket"]],
            np.log1p(1000.0 - rows[:, _COL["active_rank"]] * 50.0),
        )
        reporter = RolloutReporter(
            sub, _StorageStub([dl_path]), LocalRolloutClient(ctrl))
        out = reporter.run_once()
        assert out is not None and out["decision"]["decision"] == "rollback"
        # The candidate is out, the last-good version still serves, and
        # the decision is visible in rollout_state.
        assert reg.get(m2.id).state is ModelState.INACTIVE
        assert reg.active_model("s1", MODEL_NAME).id == m1.id
        assert ctrl.get("s1", MODEL_NAME).phase == RolloutPhase.ROLLED_BACK.value
        assert "regret" in ctrl.get("s1", MODEL_NAME).reason
        assert rollout_metrics.ROLLOUT_STATE.value(
            scheduler_id="s1", name=MODEL_NAME) == 5.0
        # The scheduler side dropped its rollout state too.
        assert ml.shadow is None and ml.canary is None
        assert sub._loaded_version == m1.version
        sub.stop()


# ---------------------------------------------------------------------------
# Acceptance drill 2: manager kill mid-canary → pinned to last ACTIVE
# ---------------------------------------------------------------------------


class TestManagerKillMidCanaryDrill:
    def test_kill_pins_scheduler_to_last_active_no_flapping(self, tmp_path):
        from dragonfly2_tpu.manager import ClusterManager
        from dragonfly2_tpu.manager.rest import ManagerRESTServer
        from dragonfly2_tpu.rollout import RolloutRESTClient
        from dragonfly2_tpu.rpc.registry_client import RemoteRegistry

        reg, m1, m2 = _registry_v1_active_v2(invert_v2=False, sched="s-kill")
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=1, canary_percent=20))
        server = ManagerRESTServer(reg, ClusterManager(), rollout=ctrl)
        server.serve()
        try:
            remote = RemoteRegistry(server.url, timeout=3.0)
            rollout_client = RolloutRESTClient(server.url, timeout=3.0)
            ml = MLEvaluator(
                None, feature_cache=HostFeatureCache(max_hosts=512),
                batcher=ScorerBatcher(linger_s=0.0),
            )
            sub = ModelSubscriber(
                remote, ml, scheduler_id="s-kill",
                rollout_client=rollout_client, shadow_sample_rate=0.5,
            )
            sub.refresh()
            assert sub._loaded_version == m1.version
            # Walk the candidate to CANARY over the REAL wire.
            ctrl.begin(m2.id)
            decision = rollout_client.report(
                "s-kill", MODEL_NAME, _report(joined=5))
            assert decision["decision"] == "advance"
            sub.refresh()
            assert ml.canary is not None and ml.canary.percent == 20
            assert sched_metrics.ROLLOUT_SERVING_STATE.value(
                name=MODEL_NAME) == 3.0
            serving = ml._scorer
        finally:
            server.stop()  # the KILL: manager gone mid-canary

        # Next poll fails → the scheduler pins to the last ACTIVE version.
        assert sub.refresh() is False
        assert ml.canary is None and ml.shadow is None
        assert ml._scorer is serving and sub._loaded_version == m1.version
        assert sched_metrics.ROLLOUT_SERVING_STATE.value(name=MODEL_NAME) == 0.0
        # No flapping: repeated failed polls keep the exact same state.
        for _ in range(3):
            assert sub.refresh() is False
            assert ml.canary is None and ml._scorer is serving
        # Announces keep ranking with the pinned active scorer.
        task, peers = build_announce_swarm(30, seed=13)
        ranked = ml.evaluate_parents(
            [peers[i] for i in range(1, 9)], peers[0], task.total_piece_count
        )
        assert len(ranked) == 8
        sub.stop()


# ---------------------------------------------------------------------------
# REST surface
# ---------------------------------------------------------------------------


class TestRolloutREST:
    def _server(self):
        from dragonfly2_tpu.manager import ClusterManager
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        reg, m1, m2 = _registry_v1_active_v2(sched="s-rest")
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=1))
        server = ManagerRESTServer(reg, ClusterManager(), rollout=ctrl)
        server.serve()
        return server, reg, ctrl, m1, m2

    def _call(self, base, method, path, body=None):
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            base + path, data=data,
            headers={"Content-Type": "application/json"}, method=method,
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read() or b"{}")

    def test_rollout_routes_roundtrip(self):
        import urllib.error

        server, reg, ctrl, m1, m2 = self._server()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                self._call(server.url, "GET",
                           "/api/v1/models:candidate?scheduler_id=s-rest"
                           f"&name={MODEL_NAME}")
            assert exc.value.code == 404
            r = self._call(server.url, "POST", f"/api/v1/models/{m2.id}:rollout",
                           {"canary_percent": 15})
            assert r["phase"] == "shadow" and r["canary_percent"] == 15
            cand = self._call(server.url, "GET",
                              "/api/v1/models:candidate?scheduler_id=s-rest"
                              f"&name={MODEL_NAME}")
            assert cand["model"]["id"] == m2.id
            assert cand["model"]["artifact_digest"]
            assert cand["phase"] == "shadow" and cand["canary_percent"] == 15
            out = self._call(server.url, "POST", "/api/v1/rollouts:report",
                             {"scheduler_id": "s-rest", "name": MODEL_NAME,
                              "report": _report(joined=5)})
            assert out["decision"] == "advance"
            listing = self._call(server.url, "GET", "/api/v1/rollouts")
            assert [r["model_id"] for r in listing] == [m2.id]
            one = self._call(server.url, "GET",
                             "/api/v1/rollouts:get?scheduler_id=s-rest"
                             f"&name={MODEL_NAME}")
            assert one["phase"] == "canary"
            with pytest.raises(urllib.error.HTTPError) as exc:
                self._call(server.url, "POST", "/api/v1/rollouts:report",
                           {"scheduler_id": "ghost", "name": MODEL_NAME,
                            "report": {}})
            assert exc.value.code == 404
        finally:
            server.stop()

    def test_remote_registry_verifies_digest_over_the_wire(self):
        import dataclasses

        from dragonfly2_tpu.rpc.registry_client import RemoteRegistry

        server, reg, ctrl, m1, m2 = self._server()
        try:
            remote = RemoteRegistry(server.url, timeout=3.0)
            model = remote.active_model("s-rest", MODEL_NAME)
            assert model.artifact_digest == m1.artifact_digest
            assert load_scorer(remote.load_artifact(model)) is not None
            # CLIENT-side verification: the server serves good bytes, but
            # the row the client holds pins a different digest → refused
            # at the client boundary.
            tampered = dataclasses.replace(model, artifact_digest="0" * 64)
            with pytest.raises(ArtifactDigestError):
                remote.load_artifact(tampered)
            # SERVER-side verification: a corrupted blob is refused by the
            # manager itself (clean 404, surfaced as KeyError here) — no
            # unverifiable bytes ever leave the registry.
            reg.blobs.put(m1.blob_key, b"tampered")
            with pytest.raises(KeyError):
                remote.load_artifact(model)
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# bench_shadow smoke: the tier-1 JSON schema gate
# ---------------------------------------------------------------------------


class TestBenchShadowSmoke:
    def test_smoke_emits_schema_json(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "bench_shadow.py"), "--smoke"],
            capture_output=True,
            text=True,
            timeout=300,
            cwd=str(REPO),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = proc.stdout.strip().splitlines()[-1]
        out = json.loads(line)
        sys.path.insert(0, str(REPO / "tools"))
        try:
            from bench_shadow import SCHEMA_KEYS
        finally:
            sys.path.pop(0)
        for key in SCHEMA_KEYS:
            assert key in out, key
        assert out["ok"] is True
        for path in ("shadow_off", "shadow_on"):
            stats = out["paths"][path]
            assert stats["announces"] > 0
            assert stats["announces_per_sec"] > 0
            assert stats["p50_ms"] <= stats["p99_ms"]
        shadow = out["shadow"]
        assert shadow["offered"] > 0
        accounted = (shadow["scored_announces"] + shadow["dropped"]
                     + shadow["sampled_out"] + shadow["errors"])
        # offered/sampled_out are lock-free racy counters (shadow.py):
        # allow a couple of lost increments under announcer contention.
        assert abs(accounted - shadow["offered"]) <= 4
        assert isinstance(out["overhead_pct"], float)


# ---------------------------------------------------------------------------
# Scorer-snapshot pinning (ISSUE 7): arm split atomic with the route decision
# ---------------------------------------------------------------------------


class TestScorerSnapshotPinning:
    def test_no_mixed_snapshot_flush_when_candidate_swaps_mid_linger(self):
        """A rollout transition mid-linger (float candidate → quantized
        candidate) must never re-route an already-enqueued request onto
        the newer snapshot: each request is scored by the scorer captured
        ATOMICALLY with its CanaryRoute decision, and requests pinned to
        different snapshots never share one coalesced call."""
        active = _ConstScorer(step=1.0)
        float_cand = _ConstScorer(step=-1.0)   # "float" candidate arm
        quant_cand = _ConstScorer(step=-2.0)   # "quantized" candidate arm
        b = ScorerBatcher(active, linger_s=0.10)
        b.set_candidate(float_cand)
        results = {}
        errs = []

        def call(key, snapshot, delay):
            try:
                time.sleep(delay)
                results[key] = np.asarray(
                    b.score(np.zeros((4, 3), np.float32), candidate=True,
                            scorer=snapshot)
                )
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        def transition():
            time.sleep(0.03)
            b.set_candidate(quant_cand)  # the rollout flips mid-linger

        threads = [
            threading.Thread(target=call, args=("float", float_cand, 0.0),
                             daemon=True),
            threading.Thread(target=transition, daemon=True),
            threading.Thread(target=call, args=("quant", quant_cand, 0.06),
                             daemon=True),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(15)
        assert errs == []
        # Both requests coalesced into the SAME flush window, yet each
        # was scored by ITS snapshot — one call per snapshot, never a
        # merged mixed-precision call, and the active arm untouched.
        assert list(np.argsort(-results["float"])) == [0, 1, 2, 3]
        assert list(np.argsort(-results["quant"])) == [0, 1, 2, 3]
        assert float_cand.calls == 1
        assert quant_cand.calls == 1
        assert active.calls == 0

    def test_unpinned_requests_keep_flush_snapshot_semantics(self):
        # Legacy callers (no snapshot) still get the flush snapshot —
        # including the candidate-gone → pinned-to-active behavior.
        active = _ConstScorer(step=1.0)
        b = ScorerBatcher(active, linger_s=0.0)
        out = np.asarray(b.score(np.zeros((3, 2), np.float32), candidate=True))
        assert list(np.argsort(-out)) == [2, 1, 0]
        assert active.calls == 1

    def test_evaluator_pins_candidate_snapshot_through_batcher(self):
        """End to end: MLEvaluator resolves the candidate snapshot with
        the route decision and carries it into the flush — a set_canary
        swap between routing and flushing cannot change which scorer
        scores the announce."""
        task, peers = build_announce_swarm(30, seed=13)
        active = _ConstScorer(step=1.0)
        cand_v2 = _ConstScorer(step=-1.0)
        batcher = ScorerBatcher(active, linger_s=0.0)
        ml = MLEvaluator(active, feature_cache=HostFeatureCache(max_hosts=128),
                         batcher=batcher)
        ml.set_canary(CanaryRoute(cand_v2, percent=100, version=2))
        child, cands = peers[0], [peers[i + 1] for i in range(5)]
        ranked = ml.evaluate_parents(cands, child, task.total_piece_count)
        # percent=100 → candidate arm; scored by cand_v2 (ascending step
        # -1 → candidate prefers FIRST row).
        assert [p.id for p in ranked] == [p.id for p in cands]
        assert cand_v2.calls == 1


# ---------------------------------------------------------------------------
# Quantized serving scorer gated through the rollout plane (ISSUE 7)
# ---------------------------------------------------------------------------


def _measured_inversion(scores: np.ndarray, realized: np.ndarray, group: int) -> float:
    """Fraction of within-announce pairs an arm ranks against the
    realized order — the replay evaluator's inversion semantics on
    plainly visible arrays."""
    flips = pairs = 0
    for g in range(0, len(scores), group):
        s, r = scores[g:g + group], realized[g:g + group]
        for i in range(len(s)):
            for j in range(i + 1, len(s)):
                if r[i] == r[j]:
                    continue
                pairs += 1
                if (s[i] - s[j]) * (r[i] - r[j]) < 0:
                    flips += 1
    return flips / max(pairs, 1)


class TestQuantizedScorerRollout:
    GROUP = 8

    def _arms(self, mode):
        from dragonfly2_tpu.trainer.export import quantize_scorer

        active = _mk_scorer(21)
        quant = quantize_scorer(active, mode)
        return active, quant

    def _measured_report(self, active, candidate, joined=400, seed=5):
        rng = np.random.default_rng(seed)
        rows = rng.standard_normal((joined, DOWNLOAD_FEATURE_DIM)).astype(
            np.float32
        )
        act = active.score(rows)
        cand = candidate.score(rows)
        # Realized bandwidth = the float model's signal + outcome noise:
        # the active arm is imperfect against it, and the guardrail asks
        # whether the candidate is MATERIALLY worse than active.
        realized = act + rng.normal(0.0, 0.05 * np.std(act), size=act.shape)
        a_inv = _measured_inversion(act, realized, self.GROUP)
        c_inv = _measured_inversion(cand, realized, self.GROUP)
        return {
            "joined_edges": joined,
            "announces": joined // self.GROUP,
            "regret_at_k": {"k": 4, "candidate": c_inv, "active": a_inv},
            "inversion_rate": {"pairs": joined, "candidate": c_inv,
                               "active": a_inv},
            "psi_max": 0.01,
        }

    @pytest.mark.parametrize("mode", ["int8", "bf16"])
    def test_quantized_candidate_passes_gates_and_promotes(self, mode, tmp_path):
        from dragonfly2_tpu.trainer.export import QuantizedMLPScorer

        active, quant = self._arms(mode)
        blobs = BlobStore(str(tmp_path / "blobs"))
        reg = ModelRegistry(blobs)
        m1 = reg.create_model(name=MODEL_NAME, type="mlp", scheduler_id="s1",
                              artifact=scorer_to_bytes(active))
        reg.activate(m1.id)
        m2 = reg.create_model(name=MODEL_NAME, type=f"mlp_{mode}",
                              scheduler_id="s1",
                              artifact=scorer_to_bytes(quant))
        # The artifact round-trips through the registry digest check and
        # loads as the quantized class.
        loaded = load_scorer(reg.load_artifact(m2))
        assert isinstance(loaded, QuantizedMLPScorer)
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=100, min_canary_samples=100))
        ctrl.begin(m2.id)
        assert reg.get(m2.id).state is ModelState.SHADOW
        report = self._measured_report(active, quant)
        # Quantization barely moves the rankings: the measured inversion
        # delta sits inside the candidate ≤ active·1.10 + 0.02 guardrail.
        out = ctrl.report("s1", MODEL_NAME, report)
        assert out["decision"] == "advance", out
        report2 = self._measured_report(active, quant, joined=800, seed=6)
        out = ctrl.report("s1", MODEL_NAME, report2)
        assert out["decision"] == "promote", out
        assert reg.get(m2.id).state is ModelState.ACTIVE
        assert reg.get(m1.id).state is ModelState.INACTIVE

    def test_destroyed_quantization_rolls_back(self, tmp_path):
        # A quantizer gone wrong (weights crushed to sign * amax — a
        # 1-bit disaster) produces measurably inverted rankings: the
        # replay gate must refuse it, never score-equivalence assumptions.
        active = _mk_scorer(21)
        bad_weights = [
            (np.sign(w) * np.max(np.abs(w)), b) for w, b in active.weights
        ]
        bad = MLPScorer(weights=[(w.astype(np.float32), b) for w, b in bad_weights])
        blobs = BlobStore(str(tmp_path / "blobs"))
        reg = ModelRegistry(blobs)
        m1 = reg.create_model(name=MODEL_NAME, type="mlp", scheduler_id="s1",
                              artifact=scorer_to_bytes(active))
        reg.activate(m1.id)
        m2 = reg.create_model(name=MODEL_NAME, type="mlp_int8",
                              scheduler_id="s1", artifact=scorer_to_bytes(bad))
        ctrl = RolloutController(reg, guardrails=RolloutGuardrails(
            min_shadow_samples=100))
        ctrl.begin(m2.id)
        report = self._measured_report(active, bad)
        assert report["inversion_rate"]["candidate"] > (
            report["inversion_rate"]["active"] * 1.10 + 0.02
        )
        out = ctrl.report("s1", MODEL_NAME, report)
        assert out["decision"] == "rollback"
        assert reg.get(m2.id).state is ModelState.INACTIVE
        assert reg.active_model("s1", MODEL_NAME).id == m1.id
