"""S3/OSS/OBS object-storage backends (VERDICT r2 next-#6, r3 next-#8):
signed HTTP backends against signature-VERIFYING fakes, config dispatch,
and the gateway e2e over the S3 and OBS backends."""

import pytest

from dragonfly2_tpu.objectstorage import (
    OBSBackend,
    OSSBackend,
    S3Backend,
    make_backend,
)
from tests.fake_s3 import ACCESS_KEY, REGION, SECRET_KEY, FakeS3


@pytest.fixture()
def fake_s3():
    srv = FakeS3()
    yield srv
    srv.stop()


@pytest.fixture()
def s3(fake_s3):
    return S3Backend(
        fake_s3.endpoint, access_key=ACCESS_KEY, secret_key=SECRET_KEY,
        region=REGION,
    )


@pytest.fixture()
def fake_obs():
    srv = FakeS3(auth="obs")
    yield srv
    srv.stop()


@pytest.fixture()
def obs(fake_obs):
    return make_backend(
        "obs", endpoint=fake_obs.endpoint,
        access_key=ACCESS_KEY, secret_key=SECRET_KEY,
    )


class TestS3Backend:
    def test_bucket_and_object_crud(self, fake_s3, s3):
        assert not s3.bucket_exists("bkt")
        s3.create_bucket("bkt")
        assert s3.bucket_exists("bkt")
        s3.create_bucket("bkt")  # idempotent

        meta = s3.put_object("bkt", "a/b/model.npz", b"\x00\x01payload")
        assert meta.content_length == 9
        assert s3.get_object("bkt", "a/b/model.npz") == b"\x00\x01payload"
        head = s3.head_object("bkt", "a/b/model.npz")
        assert head.content_length == 9 and head.etag == meta.etag
        assert s3.object_exists("bkt", "a/b/model.npz")
        assert not s3.object_exists("bkt", "ghost")
        with pytest.raises(KeyError):
            s3.get_object("bkt", "ghost")
        # Every request above carried a signature the server RECOMPUTED.
        assert fake_s3.auth_failures == 0

    def test_copy_list_delete(self, fake_s3, s3):
        s3.create_bucket("bkt")
        s3.put_object("bkt", "x/one", b"1" * 10)
        s3.put_object("bkt", "x/two", b"2" * 20)
        s3.put_object("bkt", "y/three", b"3" * 30)
        copied = s3.copy_object("bkt", "x/one", "x/copied")
        assert copied.content_length == 10
        keys = [m.key for m in s3.list_objects("bkt", prefix="x/")]
        assert keys == ["x/copied", "x/one", "x/two"]
        sizes = {m.key: m.content_length for m in s3.list_objects("bkt")}
        assert sizes["y/three"] == 30
        s3.delete_object("bkt", "x/one")
        assert not s3.object_exists("bkt", "x/one")
        s3.delete_object("bkt", "x/one")  # idempotent
        assert fake_s3.auth_failures == 0

    def test_bad_credentials_rejected(self, fake_s3):
        bad = S3Backend(
            fake_s3.endpoint, access_key=ACCESS_KEY, secret_key="wrong",
            region=REGION,
        )
        from dragonfly2_tpu.objectstorage import ObjectStorageError

        with pytest.raises((ObjectStorageError, OSError)):
            bad.create_bucket("nope")
        assert fake_s3.auth_failures > 0

    def test_make_backend_dispatch(self, tmp_path, fake_s3):
        fs = make_backend("fs", root=str(tmp_path))
        fs.create_bucket("b")
        assert fs.bucket_exists("b")
        s3 = make_backend("s3", endpoint=fake_s3.endpoint,
                          access_key=ACCESS_KEY, secret_key=SECRET_KEY,
                          region=REGION)
        assert isinstance(s3, S3Backend)
        assert isinstance(
            make_backend("oss", endpoint="http://x", access_key="a",
                         secret_key="b"),
            OSSBackend,
        )
        with pytest.raises(ValueError):
            make_backend("gcs", endpoint="http://x")


class TestGatewayOverS3:
    def test_gateway_e2e_on_fake_s3(self, tmp_path, fake_s3, s3):
        """VERDICT r2 next-#6 done-condition: the daemon gateway runs its
        put→seed→P2P-read loop against the S3 backend."""
        from dragonfly2_tpu.daemon.gateway import (
            GatewayConfig,
            GatewaySourceFetcher,
            ObjectGateway,
        )
        from tests.test_daemon import PIECE, _Swarm

        swarm = _Swarm(tmp_path, n_hosts=2)
        for d in swarm.daemons:
            d.conductor.source_fetcher = GatewaySourceFetcher(s3)
        gws = [
            ObjectGateway(d, s3, GatewayConfig(piece_size=PIECE))
            for d in swarm.daemons
        ]
        payload = bytes(i % 251 for i in range(2 * PIECE + 77))
        gws[0].put_object("models/ranker.npz", payload)
        # The object landed in the (fake) S3 bucket...
        assert s3.get_object("dragonfly", "models/ranker.npz") == payload
        # ...and the second daemon reads it P2P-first from daemon 0.
        got = gws[1].get_object("models/ranker.npz")
        assert got == payload
        assert swarm.daemons[0].upload.upload_count > 0
        # Metadata surface.
        assert gws[1].head_object("models/ranker.npz").content_length == len(payload)
        assert [m.key for m in gws[1].list_objects("models/")] == ["models/ranker.npz"]
        gws[0].delete_object("models/ranker.npz")
        assert not gws[1].object_exists("models/ranker.npz")
        assert fake_s3.auth_failures == 0


class TestOBSBackend:
    """OBS backend selected by config (make_backend("obs")) against the
    header-signature-verifying fake — the r3 next-#8 done-condition."""

    def test_crud_copy_list_against_verifying_fake(self, fake_obs, obs):
        assert isinstance(obs, OBSBackend)
        obs.create_bucket("bkt")
        assert obs.bucket_exists("bkt")
        obs.put_object("bkt", "m/a.npz", b"obs-payload")
        assert obs.get_object("bkt", "m/a.npz") == b"obs-payload"
        assert obs.head_object("bkt", "m/a.npz").content_length == 11
        copied = obs.copy_object("bkt", "m/a.npz", "m/b.npz")
        assert copied.content_length == 11
        assert [m.key for m in obs.list_objects("bkt", prefix="m/")] == [
            "m/a.npz", "m/b.npz",
        ]
        obs.delete_object("bkt", "m/a.npz")
        assert not obs.object_exists("bkt", "m/a.npz")
        # Every request carried an OBS signature the server RECOMPUTED.
        assert fake_obs.auth_failures == 0

    def test_bad_credentials_rejected(self, fake_obs):
        from dragonfly2_tpu.objectstorage import ObjectStorageError

        bad = make_backend(
            "obs", endpoint=fake_obs.endpoint,
            access_key=ACCESS_KEY, secret_key="wrong",
        )
        with pytest.raises((ObjectStorageError, OSError)):
            bad.create_bucket("nope")
        assert fake_obs.auth_failures > 0

    def test_gateway_e2e_on_fake_obs(self, tmp_path, fake_obs, obs):
        """The daemon gateway's put→seed→P2P-read loop over the OBS
        backend — same suite the S3 backend passes."""
        from dragonfly2_tpu.daemon.gateway import (
            GatewayConfig,
            GatewaySourceFetcher,
            ObjectGateway,
        )
        from tests.test_daemon import PIECE, _Swarm

        swarm = _Swarm(tmp_path, n_hosts=2)
        for d in swarm.daemons:
            d.conductor.source_fetcher = GatewaySourceFetcher(obs)
        gws = [
            ObjectGateway(d, obs, GatewayConfig(piece_size=PIECE))
            for d in swarm.daemons
        ]
        payload = bytes(i % 249 for i in range(2 * PIECE + 13))
        gws[0].put_object("models/r.npz", payload)
        assert obs.get_object("dragonfly", "models/r.npz") == payload
        assert gws[1].get_object("models/r.npz") == payload
        assert fake_obs.auth_failures == 0


class TestOSSSigning:
    def test_header_signature_shape(self):
        """Independent recomputation of the OSS HMAC-SHA1 scheme over the
        canonicalized request the backend signs."""
        import base64
        import hashlib
        import hmac

        b = OSSBackend("http://oss.local", access_key="AK", secret_key="SK")
        headers = b._sign(
            "PUT", "http://oss.local/bkt/key.bin",
            {"x-oss-meta-tag": "v", "Content-Type": "application/json"},
            b"payload", "bkt", "key.bin",
        )
        auth = headers["Authorization"]
        assert auth.startswith("OSS AK:")
        date = headers["Date"]
        to_sign = (
            "PUT\n\napplication/json\n" + date
            + "\nx-oss-meta-tag:v\n/bkt/key.bin"
        )
        want = base64.b64encode(
            hmac.new(b"SK", to_sign.encode(), hashlib.sha1).digest()
        ).decode()
        assert auth == f"OSS AK:{want}"


class TestBucketSurface:
    def test_backend_bucket_lifecycle(self, fake_s3, s3, tmp_path):
        from dragonfly2_tpu.objectstorage import FilesystemBackend

        for backend in (s3, FilesystemBackend(str(tmp_path / "fs"))):
            backend.create_bucket("alpha")
            backend.create_bucket("beta")
            assert backend.list_buckets() == ["alpha", "beta"]
            backend.delete_bucket("alpha")
            assert backend.list_buckets() == ["beta"]
            backend.delete_bucket("ghost")  # idempotent

    def test_manager_bucket_routes_proxy_backend(self, fake_s3, s3):
        """handlers/bucket.go parity: the manager's bucket routes drive
        the configured object-storage backend."""
        import json
        import urllib.error
        import urllib.request

        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        def call(base, method, path, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                base + path, data=data,
                headers={"Content-Type": "application/json"}, method=method,
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                return json.loads(resp.read() or b"{}")

        server = ManagerRESTServer(
            ModelRegistry(), ClusterManager(), objectstorage=s3
        )
        server.serve()
        try:
            call(server.url, "POST", "/api/v1/buckets", {"name": "blobs"})
            assert s3.bucket_exists("blobs")
            got = call(server.url, "GET", "/api/v1/buckets")
            assert {"name": "blobs"} in got
            call(server.url, "POST", "/api/v1/buckets/blobs:delete", {})
            assert not s3.bucket_exists("blobs")
        finally:
            server.stop()
        # Unconfigured manager: the surface 404s cleanly.
        bare = ManagerRESTServer(ModelRegistry(), ClusterManager())
        bare.serve()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                call(bare.url, "GET", "/api/v1/buckets")
            assert exc.value.code == 404
        finally:
            bare.stop()
