"""Serving-engine property tests (ISSUE 3 + ISSUE 7): the vectorized
scheduler paths must be byte-identical to the scalar reference
implementations, and the columnar host store (DESIGN.md §18) must keep
object views and slot columns byte-identical under churn.

Covers:
- rule ``Evaluator.evaluate_all`` vs scalar ``evaluate`` — bit-equal
  scores, identical orderings (incl. argsort(kind="stable") tie-breaks),
  on BOTH the storeless fromiter path and the columnar store path
  (including the lock-free ``rule_scores`` steady state);
- ``MLEvaluator._featurize`` (cache gather) vs ``_featurize_reference``
  — byte-identical feature matrices, identical orderings;
- ``is_bad_nodes`` vs per-peer ``is_bad_node`` across randomized cost
  populations (both the <30-sample 20× rule and the ≥30-sample 3σ rule);
- columnar ownership (ISSUE 7): bind/write-through/detach keep object
  accessor reads and slot columns byte-identical across announce /
  leave_host / eviction / slot-recycle interleavings, sequential and
  concurrent (``validate_consistency`` = the torn-row detector);
- ``ScorerBatcher`` coalescing, singleton bypass, scorer hot-swap
  atomicity under load (no mixed-version batch), degrade-to-per-request;
- ``ModelSubscriber.refresh`` concurrent refresh-under-load;
- ``tools/bench_sched.py --smoke`` JSON schema incl. the per-shape
  ``sweep`` entries (tier-1 gate).

The randomized sweeps are hypothesis-style seed sweeps: every case is a
fixed list of seeds driving ``np.random.default_rng``, so a failure
reproduces exactly.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from dragonfly2_tpu.records.features import host_bucket
from dragonfly2_tpu.scheduler import (
    Evaluator,
    HostFeatureCache,
    MLEvaluator,
    ModelSubscriber,
    ScorerBatcher,
)
from dragonfly2_tpu.scheduler.resource import Host, Peer, Task
from dragonfly2_tpu.sim.swarm import build_announce_swarm
from dragonfly2_tpu.utils.types import HostType

REPO = Path(__file__).resolve().parents[1]

SEEDS = [0, 1, 7, 1234]


def _draw_announces(n_hosts, rng, *, count=12, parents=17):
    """(child index, candidate index list) pairs, no self-candidacy."""
    out = []
    for _ in range(count):
        child_i = int(rng.integers(0, n_hosts))
        cand = rng.choice(n_hosts - 1, size=min(parents, n_hosts - 1),
                         replace=False)
        out.append((child_i, [c if c < child_i else c + 1 for c in cand]))
    return out


class _MLP:
    """Tiny deterministic scorer honouring the batched-score contract."""

    def __init__(self, seed=0, dim=32):
        rng = np.random.default_rng(seed)
        self.w = rng.standard_normal((dim, 1)).astype(np.float32)

    def score(self, features, **_buckets):
        return (np.asarray(features, np.float32) @ self.w)[..., 0]


# ---------------------------------------------------------------------------
# Ordering equivalence: vectorized vs scalar reference
# ---------------------------------------------------------------------------


class TestRuleEvaluatorEquivalence:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_scores_bit_equal_and_ordering_identical(self, seed):
        task, peers = build_announce_swarm(160, seed=seed)
        rule = Evaluator()
        rng = np.random.default_rng(seed + 100)
        for child_i, cand in _draw_announces(len(peers), rng):
            child = peers[child_i]
            parents = [peers[c] for c in cand]
            vec = rule.evaluate_all(parents, child, task.total_piece_count)
            ref = np.array(
                [rule.evaluate(p, child, task.total_piece_count) for p in parents]
            )
            assert np.array_equal(vec, ref)  # bit-equal, not just close
            assert [p.id for p in rule.evaluate_parents(
                parents, child, task.total_piece_count)] == \
                [p.id for p in rule.evaluate_parents_reference(
                    parents, child, task.total_piece_count)]

    def test_tie_break_keeps_candidate_order(self):
        # Identical hosts ⇒ identical scores for every parent: the stable
        # descending argsort must preserve the candidate sample order,
        # exactly like sorted(reverse=True).
        task = Task("t-tie", "https://example.com/blob")
        task.total_piece_count = 8
        parents = []
        for i in range(9):
            h = Host(id=f"tie-{i}", hostname=f"tie-{i}", ip="10.0.0.9",
                     concurrent_upload_limit=10)
            h.stats.network.idc = "idc-x"
            h.stats.network.location = "r|z"
            p = Peer(f"tiepeer-{i}", task, h)
            p.fsm.event("RegisterNormal")
            p.fsm.event("Download")
            parents.append(p)
        ch = Host(id="tie-child", hostname="tie-child", ip="10.0.0.10")
        child = Peer("tie-child-peer", task, ch)
        rule = Evaluator()
        ranked = rule.evaluate_parents(list(parents), child, 8)
        assert [p.id for p in ranked] == [p.id for p in parents]
        assert [p.id for p in rule.evaluate_parents_reference(
            list(parents), child, 8)] == [p.id for p in parents]

    def test_empty_and_singleton_passthrough(self):
        task, peers = build_announce_swarm(4, seed=0)
        rule = Evaluator()
        assert rule.evaluate_parents([], peers[0], 16) == []
        assert rule.evaluate_parents([peers[1]], peers[0], 16) == [peers[1]]


class TestMLEvaluatorEquivalence:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_featurize_byte_identical(self, seed):
        task, peers = build_announce_swarm(160, seed=seed)
        ml = MLEvaluator(_MLP(), feature_cache=HostFeatureCache(max_hosts=512))
        rng = np.random.default_rng(seed + 200)
        for child_i, cand in _draw_announces(len(peers), rng):
            child = peers[child_i]
            parents = [peers[c] for c in cand]
            vec = ml._featurize(parents, child)
            ref = ml._featurize_reference(parents, child)
            assert vec.dtype == ref.dtype == np.float32
            assert np.array_equal(vec, ref)

    @pytest.mark.parametrize("seed", SEEDS)
    def test_ordering_identical_with_and_without_batcher(self, seed):
        task, peers = build_announce_swarm(120, seed=seed)
        scorer = _MLP(seed)
        plain = MLEvaluator(scorer)
        batched = MLEvaluator(
            scorer,
            feature_cache=HostFeatureCache(max_hosts=512),
            batcher=ScorerBatcher(linger_s=0.0),
        )
        rng = np.random.default_rng(seed + 300)
        for child_i, cand in _draw_announces(len(peers), rng):
            child = peers[child_i]
            parents = [peers[c] for c in cand]
            ref = [p.id for p in plain._evaluate_parents_reference(
                parents, child, task.total_piece_count)]
            assert [p.id for p in plain.evaluate_parents(
                parents, child, task.total_piece_count)] == ref
            assert [p.id for p in batched.evaluate_parents(
                parents, child, task.total_piece_count)] == ref

    def test_cache_stays_byte_identical_after_host_mutation(self):
        # Stamp movement (announce/host-update) must recompute in place:
        # the cache path may never serve a stale row.
        task, peers = build_announce_swarm(40, seed=3)
        ml = MLEvaluator(_MLP(), feature_cache=HostFeatureCache(max_hosts=128))
        child, parents = peers[0], peers[1:20]
        before = ml._featurize(parents, child)
        assert np.array_equal(before, ml._featurize_reference(parents, child))
        for p in parents[:7]:  # mutate feature inputs mid-stream
            p.host.upload_count += 3
            p.host.concurrent_upload_count += 1
        after = ml._featurize(parents, child)
        assert np.array_equal(after, ml._featurize_reference(parents, child))
        assert not np.array_equal(before, after)


class TestIsBadNodesEquivalence:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_matches_scalar_over_random_populations(self, seed):
        rng = np.random.default_rng(seed)
        task = Task("t-bad", "https://example.com/blob")
        task.total_piece_count = 64
        ev = Evaluator()
        peers = []
        for i in range(60):
            h = Host(id=f"bh-{i}", hostname=f"bh-{i}", ip="10.1.0.1")
            p = Peer(f"bp-{i}", task, h)
            p.fsm.event("RegisterNormal")
            p.fsm.event("Download")
            # Mixed regimes: no samples / below MIN / short (20× rule) /
            # long (3σ rule), with occasional outlier last costs.
            n_costs = int(rng.choice([0, 1, 2, 5, 29, 30, 31, 45]))
            for n in range(n_costs):
                cost = int(rng.integers(1_000_000, 50_000_000))
                if n == n_costs - 1 and rng.random() < 0.4:
                    cost *= int(rng.integers(10, 60))  # probe outlier
                p.finish_piece(n, cost)
            peers.append(p)
        vec = ev.is_bad_nodes(peers)
        ref = np.array([ev.is_bad_node(p) for p in peers])
        assert np.array_equal(vec, ref)

    def test_bad_states_flagged_without_costs(self):
        task = Task("t-bad2", "https://example.com/blob")
        h = Host(id="bs-0", hostname="bs-0", ip="10.1.0.2")
        p = Peer("bsp-0", task, h)  # Pending is a bad state
        ev = Evaluator()
        assert ev.is_bad_nodes([p]).tolist() == [True]
        assert ev.is_bad_node(p) is True


# ---------------------------------------------------------------------------
# HostFeatureCache invalidation rules
# ---------------------------------------------------------------------------


class TestHostFeatureCache:
    def _host(self, i, idc="idc-a", loc="r1|z1"):
        h = Host(id=f"fc-{i}", hostname=f"fc-{i}", ip="10.2.0.1",
                 concurrent_upload_limit=8)
        h.stats.network.idc = idc
        h.stats.network.location = loc
        return h

    def test_hit_miss_and_write_through(self):
        # Columnar ownership (DESIGN.md §18): the first serve BINDS the
        # host (one miss); every mutation writes the slot columns in
        # place, so there is NO stamp-miss refresh on the steady state —
        # touch/counter writes keep the row current without a miss.
        cache = HostFeatureCache(max_hosts=16)
        h = self._host(0)
        r1 = cache.features(h)
        r2 = cache.features(h)
        assert cache.misses == 1 and cache.hits == 1
        assert np.array_equal(r1, r2)
        h.touch()  # announce path: full row refresh IN PLACE, no miss
        h.upload_count += 3  # write-through: derived cells updated
        r3 = cache.features(h)
        assert cache.misses == 1 and cache.hits == 2
        from dragonfly2_tpu.records.features import host_features

        assert np.array_equal(r3, host_features(h.to_record()))
        assert not np.array_equal(r2, r3)
        assert cache.validate_consistency() == []

    def test_explicit_invalidate_frees_slot(self):
        cache = HostFeatureCache(max_hosts=4)
        hosts = [self._host(i) for i in range(4)]
        cache.gather(hosts)
        assert len(cache) == 4
        cache.invalidate(hosts[0].id)
        assert len(cache) == 3
        # The freed slot is recycled without clobbering live entries.
        h_new = self._host(99)
        cache.features(h_new)
        rows, buckets = cache.gather_with_buckets(hosts[1:] + [h_new])
        for host, row, bucket in zip(hosts[1:] + [h_new], rows, buckets):
            assert np.array_equal(
                row, MLEvaluator(None).feature_cache.features(host)
            )
            assert bucket == host_bucket(host.id)

    def test_eviction_bounded_and_correct_after_reuse(self):
        cache = HostFeatureCache(max_hosts=8)
        hosts = [self._host(i) for i in range(30)]
        for h in hosts:
            cache.features(h)
        assert len(cache) == 8
        assert cache.evictions == 22
        # Every surviving or re-computed row is still byte-correct.
        fresh = HostFeatureCache(max_hosts=64)
        rows, _ = cache.gather_with_buckets(hosts)
        ref_rows, _ = fresh.gather_with_buckets(hosts)
        assert np.array_equal(rows, ref_rows)

    def test_serve_matches_uncached_and_interning(self):
        cache = HostFeatureCache(max_hosts=64)
        child = self._host(100, idc="idc-a", loc="r1|z1|rk1")
        hosts = (
            [self._host(i, idc="idc-a", loc="r1|z1|rk2") for i in range(5)]
            + [self._host(i + 5, idc="idc-b", loc="r2|z9") for i in range(5)]
            + [self._host(10, idc="", loc="")]
        )
        sv = cache.serve(child, hosts)
        ref = cache._serve_uncached(child, hosts)
        assert np.array_equal(sv.rows, ref.rows)
        assert np.array_equal(sv.child_row, ref.child_row)
        assert np.array_equal(sv.src_buckets, ref.src_buckets)
        assert sv.dst_bucket == ref.dst_bucket
        assert np.array_equal(sv.same_idc, ref.same_idc)
        assert np.array_equal(sv.location_affinity, ref.location_affinity)
        # Second serve is all hits and still identical.
        sv2 = cache.serve(child, hosts)
        assert sv2.n_misses == 0
        assert np.array_equal(sv2.same_idc, ref.same_idc)
        assert np.array_equal(sv2.location_affinity, ref.location_affinity)

    def test_empty_idc_never_matches(self):
        cache = HostFeatureCache(max_hosts=16)
        child = self._host(0, idc="")
        hosts = [self._host(1, idc=""), self._host(2, idc="idc-a")]
        sv = cache.serve(child, hosts)
        assert sv.same_idc.tolist() == [0.0, 0.0]

    def test_oversized_candidate_set_served_uncached(self):
        cache = HostFeatureCache(max_hosts=4)
        child = self._host(0)
        hosts = [self._host(i + 1) for i in range(8)]
        sv = cache.serve(child, hosts)
        assert sv.rows.shape[0] == 8 and sv.n_hits == 0
        fresh = HostFeatureCache(max_hosts=64)
        ref = fresh.serve(child, hosts)
        assert np.array_equal(sv.rows, ref.rows)


# ---------------------------------------------------------------------------
# Columnar ownership (ISSUE 7): views ↔ columns byte-identity under churn
# ---------------------------------------------------------------------------


class TestColumnarRuleEquivalence:
    """The columnar rule path (pre-scaled columns + lock-free
    ``rule_scores``) must stay bit-equal to the scalar oracle."""

    @pytest.mark.parametrize("seed", SEEDS)
    def test_columnar_scores_bit_equal_and_ordering_identical(self, seed):
        task, peers = build_announce_swarm(160, seed=seed)
        cache = HostFeatureCache(max_hosts=512)
        rule = Evaluator(feature_cache=cache)
        oracle = Evaluator()
        rng = np.random.default_rng(seed + 400)
        for child_i, cand in _draw_announces(len(peers), rng):
            child = peers[child_i]
            parents = [peers[c] for c in cand]
            vec = rule.evaluate_all(parents, child, task.total_piece_count)
            ref = np.array(
                [oracle.evaluate(p, child, task.total_piece_count) for p in parents]
            )
            assert np.array_equal(vec, ref)  # bit-equal, not just close
            assert [p.id for p in rule.evaluate_parents(
                parents, child, task.total_piece_count)] == \
                [p.id for p in oracle.evaluate_parents_reference(
                    parents, child, task.total_piece_count)]
        # Steady state exercises the lock-free fast path whenever this
        # store is the process primary (in production the composition
        # root's store always is; under pytest another test's store may
        # hold primacy, in which case the locked path — asserted
        # bit-equal above either way — serves).  One locked serve first:
        # the fast path requires the CHILD's affinity pair row built.
        rule.evaluate_all(
            [peers[1], peers[2]], peers[0], task.total_piece_count
        )
        fast = cache.rule_scores(
            peers[0], [peers[1], peers[2]], task.total_piece_count
        )
        if cache._is_primary:
            assert fast is not None
        else:
            assert fast is None

    @pytest.mark.parametrize("seed", SEEDS[:2])
    def test_columnar_scores_track_mutations(self, seed):
        # Counter churn between announces must be reflected bit-exactly
        # (write-through keeps the pre-scaled columns current).
        task, peers = build_announce_swarm(60, seed=seed)
        cache = HostFeatureCache(max_hosts=256)
        rule = Evaluator(feature_cache=cache)
        oracle = Evaluator()
        rng = np.random.default_rng(seed)
        child, parents = peers[0], peers[1:20]
        for _ in range(6):
            for p in parents:
                r = rng.random()
                if r < 0.3:
                    p.host.acquire_upload()
                elif r < 0.6:
                    p.host.release_upload(succeeded=rng.random() < 0.8)
                elif r < 0.8:
                    p.host.upload_count += int(rng.integers(1, 4))
                if rng.random() < 0.2:
                    p.finish_piece(int(rng.integers(100, 10_000)),
                                   int(rng.integers(10**6, 10**8)))
            vec = rule.evaluate_all(parents, child, task.total_piece_count)
            ref = np.array(
                [oracle.evaluate(p, child, task.total_piece_count) for p in parents]
            )
            assert np.array_equal(vec, ref)


class TestColumnarOwnership:
    def _host(self, i, idc="idc-a", loc="r1|z1"):
        h = Host(id=f"co-{i}", hostname=f"co-{i}", ip="10.9.0.1",
                 concurrent_upload_limit=8)
        h.stats.network.idc = idc
        h.stats.network.location = loc
        return h

    def test_bind_write_through_detach_roundtrip(self):
        from dragonfly2_tpu.records.features import host_features

        cache = HostFeatureCache(max_hosts=8)
        h = self._host(0)
        h.upload_count = 7
        cache.features(h)              # bind: columns become authoritative
        assert h._cols is not None and h._cols[0] is cache
        # Write-through: accessors and columns agree after every mutator.
        assert h.acquire_upload() is True
        h.release_upload(succeeded=False)
        h.upload_count += 2
        h.concurrent_upload_limit = 11
        h.touch()
        assert h.upload_count == 10 and h.upload_failed_count == 1
        assert h.concurrent_upload_limit == 11
        assert cache.validate_consistency() == []
        row = cache.features(h)
        assert np.array_equal(row, host_features(h.to_record()))
        # Detach (departure): state survives byte-for-byte in the object.
        cache.invalidate(h.id)
        assert h._cols is None and h._pslot == -1
        assert h.upload_count == 10 and h.upload_failed_count == 1
        assert h.concurrent_upload_limit == 11
        # Re-announce rebinds from the shadows, byte-identical.
        assert np.array_equal(cache.features(h), row)

    def test_eviction_slot_recycle_preserves_state(self):
        cache = HostFeatureCache(max_hosts=4)
        hosts = [self._host(i) for i in range(12)]
        for i, h in enumerate(hosts):
            h.upload_count = 100 + i
            h.concurrent_upload_count = i % 3
            cache.features(h)  # binds; evicts (detaches) earlier owners
        assert cache.evictions == 8
        # Every host — evicted or still bound — reads its own state.
        for i, h in enumerate(hosts):
            assert h.upload_count == 100 + i
            assert h.concurrent_upload_count == i % 3
        assert cache.validate_consistency() == []

    def test_peer_count_column_mirrors(self):
        cache = HostFeatureCache(max_hosts=8)
        task = Task("t-pc", "https://example.com/x")
        h = self._host(1)
        cache.features(h)
        slot = h._cols[1]
        peers = [Peer(f"pcp-{i}", task, h) for i in range(3)]
        for p in peers:
            h.store_peer(p)
        assert int(cache._peer_count_col[slot]) == 3 == h.peer_count()
        h.delete_peer(peers[0].id)
        assert int(cache._peer_count_col[slot]) == 2 == h.peer_count()

    def test_foreign_store_serves_value_identical_copies(self):
        task, peers = build_announce_swarm(40, seed=2)
        owner = HostFeatureCache(max_hosts=128)
        other = HostFeatureCache(max_hosts=128)
        hosts = [p.host for p in peers[:16]]
        owner.gather(hosts)            # owner binds
        rows_other = other.gather(hosts)   # stamped foreign copies
        rows_owner = owner.gather(hosts)
        assert np.array_equal(rows_other, rows_owner)
        # A mutation invalidates the foreign copy via the _mut stamp.
        hosts[0].upload_count += 5
        assert np.array_equal(other.gather(hosts), owner.gather(hosts))

    def test_concurrent_churn_converges_with_no_torn_rows(self):
        # announce / upload churn / leave_host / rebind from many
        # threads; at quiesce the columns must byte-match a recompute
        # off the accessors for every bound host.
        task, peers = build_announce_swarm(48, seed=7)
        cache = HostFeatureCache(max_hosts=32)  # forces slot recycling
        rule = Evaluator(feature_cache=cache)
        errors = []
        stop = threading.Event()

        def churn(tid):
            rng = np.random.default_rng(tid)
            try:
                while not stop.is_set():
                    p = peers[int(rng.integers(0, len(peers)))]
                    r = rng.random()
                    if r < 0.35:
                        cands = [
                            peers[int(c)]
                            for c in rng.integers(0, len(peers), size=9)
                        ]
                        rule.evaluate_parents(cands, p, task.total_piece_count)
                    elif r < 0.55:
                        p.host.touch()
                    elif r < 0.7:
                        if p.host.acquire_upload():
                            p.host.release_upload(succeeded=rng.random() < 0.9)
                    elif r < 0.85:
                        p.host.upload_count += 1
                    else:
                        cache.invalidate(p.host.id)  # leave_host
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [
            threading.Thread(target=churn, args=(i,), daemon=True)
            for i in range(8)
        ]
        for t in threads:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(10)
        assert errors == []
        assert cache.validate_consistency() == []
        # And the columnar scores still match the scalar oracle exactly.
        oracle = Evaluator()
        child, parents = peers[0], peers[1:17]
        vec = rule.evaluate_all(parents, child, task.total_piece_count)
        ref = np.array(
            [oracle.evaluate(p, child, task.total_piece_count) for p in parents]
        )
        assert np.array_equal(vec, ref)


# ---------------------------------------------------------------------------
# ScorerBatcher: coalescing, hot-swap atomicity, degrade modes
# ---------------------------------------------------------------------------


class _VersionScorer:
    """Returns a constant per-row value == its version: a mixed-version
    batch would show up as a non-constant result vector."""

    def __init__(self, version):
        self.version = float(version)

    def score(self, features, **_buckets):
        return np.full(np.asarray(features).shape[0], self.version)


class TestScorerBatcher:
    def test_coalesces_concurrent_requests(self):
        calls = []

        class Recording:
            def score(self, features, **_buckets):
                calls.append(np.asarray(features).shape[0])
                return np.zeros(np.asarray(features).shape[0])

        b = ScorerBatcher(Recording(), linger_s=0.05)
        results, errs = [], []

        def worker():
            try:
                results.append(b.score(np.ones((3, 4), np.float32)))
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs and len(results) == 8
        assert all(r.shape == (3,) for r in results)
        # 8 × 3 rows coalesced into far fewer scorer calls than requests.
        assert sum(calls) == 24 and len(calls) < 8
        assert b.mean_occupancy() > 1.0

    def test_singleton_bypass_unpadded(self):
        shapes = []

        class Recording:
            def score(self, features, **_buckets):
                shapes.append(np.asarray(features).shape)
                return np.zeros(np.asarray(features).shape[0])

        b = ScorerBatcher(Recording(), linger_s=0.0)
        out = b.score(np.ones((5, 4), np.float32))
        assert out.shape == (5,) and shapes == [(5, 4)]  # raw, no padding

    def test_pad_ladder_only_for_static_shape_scorers(self):
        shapes = []

        class StaticShapes:
            static_shapes = True

            def score(self, features, **_buckets):
                shapes.append(np.asarray(features).shape[0])
                return np.zeros(np.asarray(features).shape[0])

        b = ScorerBatcher(StaticShapes(), linger_s=0.05)

        def worker():
            b.score(np.ones((3, 4), np.float32))

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Any coalesced (non-singleton) call landed on the bucket ladder.
        assert shapes
        for n in shapes:
            assert n == 3 or n in b.pad_buckets

    def test_hot_swap_never_splits_a_batch(self):
        b = ScorerBatcher(_VersionScorer(1), linger_s=0.002)
        stop = threading.Event()
        bad, errs = [], []

        def swapper():
            v = 1
            while not stop.is_set():
                v += 1
                b.set_scorer(_VersionScorer(v))

        def worker():
            try:
                for _ in range(200):
                    out = b.score(np.ones((4, 2), np.float32))
                    u = np.unique(out)
                    if len(u) != 1:  # rows from two model versions
                        bad.append(out.tolist())
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        sw = threading.Thread(target=swapper, daemon=True)
        workers = [threading.Thread(target=worker, daemon=True) for _ in range(6)]
        sw.start()
        for t in workers:
            t.start()
        for t in workers:
            t.join()
        stop.set()
        sw.join()
        assert not errs and bad == []

    def test_failed_batch_degrades_to_per_request(self):
        class FlakyBatch:
            def __init__(self):
                self.calls = 0

            def score(self, features, **_buckets):
                self.calls += 1
                n = np.asarray(features).shape[0]
                if n > 4:  # the coalesced call dies; per-request succeeds
                    raise RuntimeError("batched backend exploded")
                return np.ones(n)

        b = ScorerBatcher(FlakyBatch(), linger_s=0.05)
        results, errs = [], []

        def worker():
            try:
                results.append(b.score(np.ones((4, 3), np.float32)))
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs and len(results) == 5
        assert all(np.array_equal(r, np.ones(4)) for r in results)
        assert b.fallbacks >= 1

    def test_no_scorer_raises_and_evaluator_falls_back_to_rules(self):
        b = ScorerBatcher(None, linger_s=0.0)
        from dragonfly2_tpu.scheduler import ScorerUnavailable

        with pytest.raises(ScorerUnavailable):
            b.score(np.ones((2, 2), np.float32))

        task, peers = build_announce_swarm(20, seed=5)

        class Dead:
            def score(self, features, **_buckets):
                raise RuntimeError("scorer gone")

        ml = MLEvaluator(Dead(), batcher=ScorerBatcher(linger_s=0.0))
        child, parents = peers[0], peers[1:9]
        ranked = ml.evaluate_parents(parents, child, task.total_piece_count)
        rule_ref = Evaluator().evaluate_parents_reference(
            parents, child, task.total_piece_count
        )
        assert [p.id for p in ranked] == [p.id for p in rule_ref]


# ---------------------------------------------------------------------------
# ModelSubscriber: concurrent refresh under announce load
# ---------------------------------------------------------------------------


class _FakeModel:
    def __init__(self, version):
        self.version = version
        self.id = f"m-{version}"
        self.name = "parent-bandwidth-mlp"


class _FlippingRegistry:
    """active_model cycles versions; load_artifact hands version bytes."""

    def __init__(self):
        self.version = 1

    def active_model(self, scheduler_id, name):
        return _FakeModel(self.version)

    def load_artifact(self, model):
        return b"v%d" % model.version


class TestModelSubscriberRefreshUnderLoad:
    def test_concurrent_refresh_and_scoring(self, monkeypatch):
        from dragonfly2_tpu.scheduler import model_loader

        monkeypatch.setattr(
            model_loader,
            "ModelRegistry",
            _FlippingRegistry,
            raising=False,
        )
        import dragonfly2_tpu.trainer.export as export

        monkeypatch.setattr(
            export,
            "load_scorer",
            lambda blob: _VersionScorer(int(bytes(blob)[1:])),
        )

        task, peers = build_announce_swarm(60, seed=9)
        batcher = ScorerBatcher(linger_s=0.001)
        ml = MLEvaluator(
            None, feature_cache=HostFeatureCache(max_hosts=256), batcher=batcher
        )
        registry = _FlippingRegistry()
        sub = ModelSubscriber(registry, ml, scheduler_id="sched-1")
        assert sub.refresh() is True  # v1 loaded

        stop = threading.Event()
        errs = []

        def refresher():
            while not stop.is_set():
                registry.version += 1
                try:
                    sub.refresh()
                except Exception as exc:  # noqa: BLE001
                    errs.append(exc)

        def announcer(tid):
            rng = np.random.default_rng(tid)
            try:
                for child_i, cand in _draw_announces(len(peers), rng, count=40,
                                                     parents=9):
                    ranked = ml.evaluate_parents(
                        [peers[c] for c in cand], peers[child_i],
                        task.total_piece_count,
                    )
                    assert len(ranked) == len(cand)
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        ref = threading.Thread(target=refresher, daemon=True)
        workers = [
            threading.Thread(target=announcer, args=(i,), daemon=True)
            for i in range(6)
        ]
        ref.start()
        for t in workers:
            t.start()
        for t in workers:
            t.join()
        stop.set()
        ref.join()
        assert errs == []
        # Quiesced: one final refresh converges on the registry's version.
        sub.refresh()
        assert sub._loaded_version == registry.version
        assert ml._scorer.version == float(registry.version)

    def test_refresh_serialized_against_itself(self, monkeypatch):
        import dragonfly2_tpu.trainer.export as export

        monkeypatch.setattr(
            export,
            "load_scorer",
            lambda blob: _VersionScorer(int(bytes(blob)[1:])),
        )
        registry = _FlippingRegistry()
        ml = MLEvaluator(None)
        sub = ModelSubscriber(registry, ml, scheduler_id="sched-2")
        errs = []

        def hammer():
            try:
                for _ in range(50):
                    registry.version += 1
                    sub.refresh()
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=hammer, daemon=True) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
        sub.refresh()
        assert sub._loaded_version == registry.version


# ---------------------------------------------------------------------------
# bench_sched smoke: the tier-1 JSON schema gate
# ---------------------------------------------------------------------------


class TestBenchSchedSmoke:
    def test_smoke_emits_schema_json(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "bench_sched.py"), "--smoke"],
            capture_output=True,
            text=True,
            timeout=300,
            cwd=str(REPO),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = proc.stdout.strip().splitlines()[-1]
        out = json.loads(line)
        sys.path.insert(0, str(REPO / "tools"))
        try:
            from bench_sched import SCHEMA_KEYS
        finally:
            sys.path.pop(0)
        for key in SCHEMA_KEYS:
            assert key in out, key
        assert out["ok"] is True
        for path in ("scalar_rule", "vector_rule", "scalar_ml", "vector_ml"):
            stats = out["paths"][path]
            assert stats["announces"] > 0
            assert stats["announces_per_sec"] > 0
            assert stats["p50_ms"] <= stats["p99_ms"]
        assert 0.0 <= out["cache_hit_rate"] <= 1.0
        assert out["mean_batch_occupancy"] >= 0.0
        # Per-shape sweep (ISSUE 7): every entry reports the rule-path
        # speedup for its candidate-set size in the JSON line.
        assert isinstance(out["sweep"], list) and len(out["sweep"]) >= 2
        parents_seen = set()
        for entry in out["sweep"]:
            parents_seen.add(entry["parents"])
            for key in (
                "hosts", "parents", "speedup_rule", "speedup_ml",
                "scalar_rule_announces_per_sec",
                "vector_rule_announces_per_sec",
                "vector_ml_announces_per_sec",
            ):
                assert key in entry, key
            assert entry["speedup_rule"] > 0 and entry["speedup_ml"] > 0
        assert len(parents_seen) >= 2  # genuinely distinct shapes
        # Vectorized serving must never retrace on the steady state.
        assert out["steady_state_recompiles"]["vector_ml"] == 0
        # Standalone bench process: no conftest, so the determinism
        # witness is not installed — the report must say so (§27).
        assert out["det_witness_disarmed"] is True
        # Flight-recorder overhead rounds (ISSUE 10): both arms measured,
        # the default sampling documented in the JSON.
        trace = out["tracing_overhead"]
        assert trace["on_announces_per_sec"] > 0
        assert trace["off_announces_per_sec"] > 0
        assert trace["sample_rate"] == 0.1
        assert "overhead_pct" in trace
