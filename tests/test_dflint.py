"""dflint self-tests: every DF rule fires on a minimal true-positive
fixture and stays quiet on the accepted shapes, pragmas, and baseline
entries (tools/dflint — the tier-1 invariant gate's own coverage)."""

from __future__ import annotations

import sys
import textwrap
from pathlib import Path

import pytest  # noqa: F401  (parity with the suite's import style)

_REPO = Path(__file__).resolve().parents[1]
if str(_REPO) not in sys.path:  # `python -m pytest` from elsewhere
    sys.path.insert(0, str(_REPO))

from tools.dflint.baseline import Baseline, parse_toml_subset, render  # noqa: E402
from tools.dflint.core import Module, run_checkers  # noqa: E402


def lint(source: str, relpath: str = "dragonfly2_tpu/daemon/fixture.py"):
    src = textwrap.dedent(source)
    module = Module(Path("/fixture.py"), relpath, src)
    return run_checkers(module)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# DF001 — exception swallowing
# ---------------------------------------------------------------------------


class TestDF001:
    def test_silent_pass_fires(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception:
                    pass
        """)
        assert rules_of(fs) == ["DF001"]

    def test_bare_except_fires(self):
        fs = lint("""
            def f():
                try:
                    work()
                except:
                    return None
        """)
        assert "DF001" in rules_of(fs)

    def test_logging_call_is_handled(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception as exc:
                    log.warning("failed: %s", exc)
        """)
        assert fs == []

    def test_reraise_is_handled(self):
        fs = lint("""
            def f():
                try:
                    work()
                except BaseException:
                    raise
        """)
        assert fs == []

    def test_bound_name_use_is_handled(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception as exc:
                    result = exc
                return result
        """)
        assert fs == []

    def test_narrow_except_is_exempt(self):
        fs = lint("""
            def f():
                try:
                    work()
                except KeyError:
                    pass
        """)
        assert fs == []

    def test_pragma_suppresses(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception:  # dflint: disable=DF001
                    pass
        """)
        assert fs == []

    def test_file_pragma_suppresses(self):
        fs = lint("""
            # dflint: disable-file=DF001
            def f():
                try:
                    work()
                except Exception:
                    pass
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# DF002 — thread hygiene
# ---------------------------------------------------------------------------


class TestDF002:
    def test_thread_without_daemon_fires(self):
        fs = lint("""
            import threading

            def start():
                t = threading.Thread(target=loop)
                t.start()
        """)
        assert rules_of(fs) == ["DF002"]

    def test_daemon_kwarg_ok(self):
        fs = lint("""
            import threading

            def start():
                threading.Thread(target=loop, daemon=True).start()
        """)
        assert fs == []

    def test_joined_thread_still_needs_explicit_daemon(self):
        fs = lint("""
            import threading

            def run_all():
                ts = [threading.Thread(target=loop) for _ in range(4)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
        """)
        assert rules_of(fs) == ["DF002"]
        assert any("implicit" in f.message for f in fs)

    def test_joined_thread_with_explicit_daemon_false_ok(self):
        fs = lint("""
            import threading

            def run_all():
                ts = [threading.Thread(target=loop, daemon=False) for _ in range(4)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
        """)
        assert fs == []

    def test_unlocked_shared_mutation_fires(self):
        fs = lint("""
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._loop, daemon=True).start()

                def _loop(self):
                    self.count += 1

                def reset(self):
                    self.count = 0
        """)
        assert "DF002" in rules_of(fs)
        assert any("reset" in f.message for f in fs)

    def test_locked_mutation_ok(self):
        fs = lint("""
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._loop, daemon=True).start()

                def _loop(self):
                    with self._mu:
                        self.count += 1

                def reset(self):
                    with self._mu:
                        self.count = 0
        """)
        assert fs == []

    def test_private_method_mutation_not_flagged(self):
        fs = lint("""
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._loop, daemon=True).start()

                def _loop(self):
                    self.count += 1

                def _internal(self):
                    self.count = 0
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# DF003 — JAX trace purity
# ---------------------------------------------------------------------------


class TestDF003:
    def test_time_in_jit_decorator_fires(self):
        fs = lint("""
            import time
            import jax

            @jax.jit
            def step(x):
                t0 = time.time()
                return x + t0
        """)
        assert rules_of(fs) == ["DF003"]

    def test_wrapped_method_resolution(self):
        fs = lint("""
            import jax

            class Trainer:
                def __init__(self):
                    self._fn = jax.jit(self._step)

                def _step(self, x):
                    print(x)
                    return x
        """)
        assert rules_of(fs) == ["DF003"]

    def test_partial_jit_decorator(self):
        fs = lint("""
            import random
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames="n")
            def step(x, n):
                return x * random.random()
        """)
        assert rules_of(fs) == ["DF003"]

    def test_item_escape_fires(self):
        fs = lint("""
            import jax

            @jax.jit
            def step(x):
                return float(x.sum().item())
        """)
        assert "DF003" in rules_of(fs)

    def test_np_asarray_fires(self):
        fs = lint("""
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return np.asarray(x)
        """)
        assert "DF003" in rules_of(fs)

    def test_jax_random_exempt(self):
        fs = lint("""
            import jax

            @jax.jit
            def step(key, x):
                noise = jax.random.normal(key, x.shape)
                return x + noise
        """)
        assert fs == []

    def test_untraced_function_free(self):
        fs = lint("""
            import time

            def host_loop(x):
                time.sleep(1)
                print(x)
        """)
        assert fs == []

    def test_pallas_kernel_resolution(self):
        fs = lint("""
            import time
            import jax
            from jax.experimental import pallas as pl

            def kernel(x_ref, o_ref):
                time.sleep(0.1)
                o_ref[...] = x_ref[...]

            def run(x):
                return pl.pallas_call(kernel, out_shape=x)(x)
        """)
        assert rules_of(fs) == ["DF003"]


# ---------------------------------------------------------------------------
# DF004 — fault-seam coverage
# ---------------------------------------------------------------------------


class TestDF004:
    def test_urlopen_without_fire_fires(self):
        fs = lint("""
            import urllib.request

            def fetch(url):
                with urllib.request.urlopen(url, timeout=5) as resp:
                    return resp.read()
        """)
        assert rules_of(fs) == ["DF004"]

    def test_urlopen_with_fire_ok(self):
        fs = lint("""
            import urllib.request
            from dragonfly2_tpu.utils import faultinject

            def fetch(url):
                faultinject.fire("fixture.fetch")
                with urllib.request.urlopen(url, timeout=5) as resp:
                    return resp.read()
        """)
        assert fs == []

    def test_socket_send_without_fire_fires(self):
        fs = lint("""
            def push(sock, data):
                sock.sendall(data)
        """)
        assert rules_of(fs) == ["DF004"]

    def test_allowlisted_module_exempt(self):
        fs = lint(
            """
            import urllib.request

            def export(url):
                urllib.request.urlopen(url, timeout=5).close()
            """,
            relpath="dragonfly2_tpu/utils/tracing.py",
        )
        assert fs == []

    def test_fire_in_other_function_does_not_cover(self):
        fs = lint("""
            from dragonfly2_tpu.utils import faultinject

            def seam():
                faultinject.fire("fixture.other")

            def push(sock, data):
                sock.sendall(data)
        """)
        assert rules_of(fs) == ["DF004"]

    def test_seam_inventory_missing_site_fires(self):
        # daemon/upload.py owns two required sites; a module with only
        # one of them must be flagged for the other.
        fs = lint(
            """
            from ..utils import faultinject

            def serve_piece(task_id, number):
                faultinject.fire("daemon.upload.serve_piece")
                return b""
            """,
            relpath="dragonfly2_tpu/daemon/upload.py",
        )
        # Three missing inventoried sites (body + sendfile + the PR-15
        # throttle gate), one finding each; PR 11's DF007 hotpath
        # inventory on this relpath also fires for the absent
        # UploadManager.serve_piece — filter to the seam rule under
        # test.
        df004 = [f for f in fs if f.rule == "DF004"]
        assert len(df004) == 3
        assert any("daemon.upload.body" in f.message for f in df004)
        assert any("daemon.upload.sendfile" in f.message for f in df004)

    def test_seam_inventory_fstring_prefix_matches(self):
        fs = lint(
            """
            from ..utils import faultinject

            def call(self, method):
                faultinject.fire(f"rpc.client.{method}")
            """,
            relpath="dragonfly2_tpu/rpc/scheduler_client.py",
        )
        assert [f for f in fs if f.rule == "DF004"] == []

    def test_real_seam_modules_satisfy_inventory(self):
        from tools.dflint.checkers.df004_fault_seams import (
            REQUIRED_SEAMS, fire_sites,
        )
        from tools.dflint.core import load_module

        repo = Path(__file__).resolve().parents[1]
        for relpath, required in REQUIRED_SEAMS.items():
            module = load_module(repo / relpath, repo)
            present = fire_sites(module)
            missing = [s for s in required if s not in present]
            assert not missing, f"{relpath}: missing seams {missing}"


# ---------------------------------------------------------------------------
# DF005 — resource hygiene
# ---------------------------------------------------------------------------


class TestDF005:
    def test_discarded_open_fires(self):
        fs = lint("""
            def touch(path):
                f = open(path, "w")
                f.write("x")
        """)
        assert rules_of(fs) == ["DF005"]

    def test_with_ok(self):
        fs = lint("""
            def touch(path):
                with open(path, "w") as f:
                    f.write("x")
        """)
        assert fs == []

    def test_immediate_close_ok(self):
        fs = lint("""
            def touch(path):
                open(path, "wb").close()
        """)
        assert fs == []

    def test_tracked_close_in_finally_ok(self):
        fs = lint("""
            import socket

            def probe():
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                try:
                    s.connect(("10.0.0.1", 1))
                    return s.getsockname()[0]
                finally:
                    s.close()
        """)
        assert fs == []

    def test_self_owned_ok(self):
        fs = lint("""
            class Store:
                def __init__(self, path):
                    self._f = open(path, "ab")

                def close(self):
                    self._f.close()
        """)
        assert fs == []

    def test_factory_return_ok(self):
        fs = lint("""
            import socket

            def connect(cid, port):
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.connect((cid, port))
                return s
        """)
        assert fs == []

    def test_expression_statement_open_fires(self):
        fs = lint("""
            def leak(path):
                open(path, "w").read()
        """)
        assert rules_of(fs) == ["DF005"]


# ---------------------------------------------------------------------------
# DF006 — deadline propagation in rpc/
# ---------------------------------------------------------------------------

RPC_PATH = "dragonfly2_tpu/rpc/fixture.py"


class TestDF006:
    def test_retry_without_deadline_fires(self):
        fs = lint(
            """
            from .retry import retry_call

            def call(fn):
                return retry_call(fn, attempts=3)
            """,
            relpath=RPC_PATH,
        )
        assert rules_of(fs) == ["DF006"]

    def test_deadline_passed_but_not_accepted_fires(self):
        fs = lint(
            """
            from .retry import retry_call

            def call(fn):
                return retry_call(fn, deadline_s=5.0)
            """,
            relpath=RPC_PATH,
        )
        assert rules_of(fs) == ["DF006"]

    def test_threaded_deadline_ok(self):
        fs = lint(
            """
            from .retry import retry_call

            def call(fn, *, deadline_s=None):
                return retry_call(fn, deadline_s=deadline_s)
            """,
            relpath=RPC_PATH,
        )
        assert fs == []

    def test_urlopen_without_timeout_fires(self):
        fs = lint(
            """
            import urllib.request
            from dragonfly2_tpu.utils import faultinject

            def get(url):
                faultinject.fire("rpc.fixture.get")
                with urllib.request.urlopen(url) as resp:
                    return resp.read()
            """,
            relpath=RPC_PATH,
        )
        assert rules_of(fs) == ["DF006"]

    def test_outside_rpc_exempt(self):
        fs = lint("""
            from dragonfly2_tpu.rpc.retry import retry_call

            def call(fn):
                return retry_call(fn, attempts=3)
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# DF007 — hot-path hygiene
# ---------------------------------------------------------------------------


class TestDF007:
    def test_loop_in_marked_function_fires(self):
        fs = lint("""
            import numpy as np

            def gather(rows):  # dflint: hotpath
                out = []
                for r in rows:
                    out.append(r * 2)
                return np.stack(out)
        """)
        assert "DF007" in rules_of(fs)

    def test_concatenate_in_marked_function_fires(self):
        fs = lint("""
            import numpy as np

            def featurize(a, b):  # dflint: hotpath
                return np.concatenate([a, b])
        """)
        assert "DF007" in rules_of(fs)

    def test_mark_on_line_above_def_applies(self):
        fs = lint("""
            import numpy as np

            # dflint: hotpath
            def featurize(a, b):
                return np.vstack([a, b])
        """)
        assert "DF007" in rules_of(fs)

    def test_comprehension_and_fromiter_are_accepted(self):
        fs = lint("""
            import numpy as np

            def score_all(parents):  # dflint: hotpath
                vals = np.fromiter((p.x for p in parents), np.float64)
                ids = [p.id for p in parents]
                return vals, ids
        """)
        assert fs == []

    def test_unmarked_function_is_free(self):
        fs = lint("""
            import numpy as np

            def build(rows):
                out = []
                for r in rows:
                    out.append(np.concatenate([r, r]))
                return out
        """)
        assert fs == []

    def test_pragma_suppresses_reviewed_constant_loop(self):
        fs = lint("""
            def mlp(x, weights):  # dflint: hotpath
                for w, b in weights:  # dflint: disable=DF007 — per-LAYER
                    x = x @ w + b
                return x
        """)
        assert fs == []

    def test_inventory_missing_function_fires_by_name(self):
        fs = lint(
            """
            def unrelated():
                return 1
            """,
            relpath="dragonfly2_tpu/scheduler/featcache.py",
        )
        assert any(
            f.rule == "DF007" and "HostFeatureCache.gather" in f.message
            for f in fs
        )

    def test_inventory_unmarked_function_fires(self):
        fs = lint(
            """
            class HostFeatureCache:
                def gather(self, hosts):
                    return hosts
            """,
            relpath="dragonfly2_tpu/scheduler/featcache.py",
        )
        assert any(
            f.rule == "DF007" and "lost its" in f.message for f in fs
        )


class TestBaseline:
    def _findings(self):
        return lint("""
            def f():
                try:
                    work()
                except Exception:
                    pass

            def g():
                try:
                    work()
                except Exception:
                    pass
        """)

    def test_split_budget(self):
        findings = self._findings()
        assert len(findings) == 2
        key_f = next(f for f in findings if f.qual == "f").key()
        bl = Baseline({key_f: 1})
        new, accepted = bl.split(findings)
        assert [f.qual for f in accepted] == ["f"]
        assert [f.qual for f in new] == ["g"]

    def test_budget_overflow_is_new(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception:
                    pass
                try:
                    more()
                except Exception:
                    pass
        """)
        assert len(fs) == 2
        bl = Baseline({fs[0].key(): 1})   # both share the key (same qual)
        new, accepted = bl.split(fs)
        assert len(accepted) == 1 and len(new) == 1

    def test_stale_keys_reported(self):
        bl = Baseline({"DF001:gone.py:f": 1})
        assert bl.stale_keys([]) == ["DF001:gone.py:f"]

    def test_round_trip_through_toml(self, tmp_path):
        findings = self._findings()
        path = tmp_path / "baseline.toml"
        path.write_text(render(findings), encoding="utf-8")
        bl = Baseline.load(path)
        new, accepted = bl.split(findings)
        assert new == [] and len(accepted) == 2

    def test_toml_subset_parser(self):
        data = parse_toml_subset(
            '# comment\n[accepted]\n"DF001:a.py:f" = 2  # trailing\nplain = "x"\n'
        )
        assert data["accepted"]["DF001:a.py:f"] == 2
        assert data["accepted"]["plain"] == "x"

    def test_checked_in_baseline_parses(self):
        from tools.dflint.baseline import DEFAULT_PATH

        bl = Baseline.load(DEFAULT_PATH)
        assert isinstance(bl.budgets, dict)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


class TestCLI:
    def test_exit_zero_on_clean_file(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        clean = tmp_path / "clean.py"
        clean.write_text("def f():\n    return 1\n")
        assert main([str(clean)]) == 0

    def test_exit_nonzero_on_finding(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(
            "def f():\n    try:\n        g()\n    except Exception:\n        pass\n"
        )
        assert main([str(dirty)]) == 1
        out = capsys.readouterr().out
        assert "DF001" in out

    def test_select_filters_rules(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(
            "def f():\n    try:\n        g()\n    except Exception:\n        pass\n"
        )
        assert main([str(dirty), "--select", "DF004"]) == 0

    def test_parse_error_exit_code(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        bad = tmp_path / "bad.py"
        bad.write_text("def f(:\n")
        assert main([str(bad)]) == 2

    def test_list_rules(self, capsys):
        from tools.dflint.__main__ import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ("DF001", "DF002", "DF003", "DF004", "DF005", "DF006"):
            assert rule in out


# ---------------------------------------------------------------------------
# Mutation sensitivity against the REAL tree (the acceptance contract:
# deleting a seam or a daemon= kwarg must fail the lint test by name)
# ---------------------------------------------------------------------------

REPO = Path(__file__).resolve().parents[1]


class TestMutationSensitivity:
    def _lint_source(self, relpath: str, source: str):
        module = Module(REPO / relpath, relpath, source)
        return run_checkers(module)

    def test_current_tree_is_clean(self):
        src_path = REPO / "dragonfly2_tpu/rpc/piece_transport.py"
        fs = self._lint_source(
            "dragonfly2_tpu/rpc/piece_transport.py",
            src_path.read_text(encoding="utf-8"),
        )
        assert fs == []

    def test_deleting_fire_seam_fails_df004(self):
        # download_via_daemon has exactly one seam guarding its urlopen;
        # removing it must re-expose the raw network call.
        relpath = "dragonfly2_tpu/rpc/daemon_control.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert 'faultinject.fire("daemon.control.download")' in source
        mutated = source.replace(
            'faultinject.fire("daemon.control.download")', "pass"
        )
        fs = self._lint_source(relpath, mutated)
        assert "DF004" in {f.rule for f in fs}

    def test_deleting_both_piece_fetch_seams_fails_df004(self):
        relpath = "dragonfly2_tpu/rpc/piece_transport.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        mutated = source.replace(
            'faultinject.fire("piece.fetch")', "pass"
        ).replace('faultinject.fire("piece.fetch.body", resp.read())',
                  "resp.read()")
        assert mutated != source
        fs = self._lint_source(relpath, mutated)
        assert "DF004" in {f.rule for f in fs}

    def test_deleting_daemon_kwarg_fails_df002(self):
        relpath = "dragonfly2_tpu/scheduler/push.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert "daemon=True" in source
        mutated = source.replace("daemon=True", "").replace(
            ", \n", "\n"
        )
        fs = self._lint_source(relpath, mutated)
        assert "DF002" in {f.rule for f in fs}

    def test_deleting_daemon_kwarg_on_joined_thread_fails_df002(self):
        # conductor's piece workers are join()ed, but the daemon flag must
        # still be explicit — deleting it is a lint regression, not a pass.
        relpath = "dragonfly2_tpu/daemon/conductor.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert ", daemon=True)" in source
        mutated = source.replace(", daemon=True)", ")")
        assert mutated != source
        fs = self._lint_source(relpath, mutated)
        assert "DF002" in {f.rule for f in fs}

    def test_unmarking_hotpath_inventory_fails_df007(self):
        # The serving-engine hygiene inventory pins evaluate_parents &co.;
        # stripping the hotpath marks must fail tier-1 by name.
        relpath = "dragonfly2_tpu/scheduler/evaluator.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert "# dflint: hotpath" in source
        mutated = source.replace("# dflint: hotpath", "")
        fs = self._lint_source(relpath, mutated)
        assert any(
            f.rule == "DF007" and "lost its" in f.message for f in fs
        )

    def test_looping_a_marked_hotpath_fails_df007(self):
        # Re-introducing the per-parent concatenate featurize (the exact
        # pre-PR shape) inside the marked function must be caught.
        relpath = "dragonfly2_tpu/scheduler/featcache.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = "return self.gather_with_buckets(hosts)[0]"
        assert needle in source
        mutated = source.replace(
            needle,
            "rows = []\n"
            "        for h in hosts:\n"
            "            rows.append(self.features(h))\n"
            "        return np.stack(rows)",
        )
        fs = self._lint_source(relpath, mutated)
        assert "DF007" in {f.rule for f in fs}


# ---------------------------------------------------------------------------
# Whole-program analysis (tools/dflint/program.py): DF008 / DF009
# ---------------------------------------------------------------------------

from tools.dflint.program import Program, witness_gaps  # noqa: E402

# Session caches for the real-tree batteries: the parse + link of the
# full dragonfly2_tpu/ tree dominates each whole-program view (~5s a
# build, dozens of builds across this file).  Program and the analyses
# treat Modules as read-only (same shareability argument as
# _real_tree_modules below), so the pristine tree is loaded and linked
# ONCE; mutation tests swap in a single re-parsed Module and relink.
_DF_TREE_MODULES = None
_DF_TREE_PROGRAM = None


def _df_tree_modules():
    global _DF_TREE_MODULES
    if _DF_TREE_MODULES is None:
        from tools.dflint.core import collect_files, load_module

        _DF_TREE_MODULES = [
            load_module(p, REPO)
            for p in collect_files([REPO / "dragonfly2_tpu"], REPO)
        ]
    return _DF_TREE_MODULES


def _df_tree_program() -> Program:
    """The pristine whole-tree Program, linked once and shared."""
    global _DF_TREE_PROGRAM
    if _DF_TREE_PROGRAM is None:
        _DF_TREE_PROGRAM = Program(list(_df_tree_modules()))
    return _DF_TREE_PROGRAM


def _df_tree_program_with(relpath: str, source: str) -> Program:
    """Whole-tree Program with ONE file's text replaced (mutation
    batteries): only the mutated file re-parses."""
    modules = [
        Module(m.path, m.relpath, source) if m.relpath == relpath else m
        for m in _df_tree_modules()
    ]
    return Program(modules)


def prog(files: dict) -> Program:
    """Build a whole-program view over an in-memory fixture tree."""
    modules = [
        Module(Path("/" + rp), rp, textwrap.dedent(src))
        for rp, src in files.items()
    ]
    return Program(modules)


def prog_rules(p: Program):
    return sorted({f.rule for f in p.findings()})


class TestDF008Fixtures:
    def test_direct_urlopen_under_lock_fires(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading
            import urllib.request

            class C:
                def __init__(self):
                    self._mu = threading.Lock()

                def f(self, url):
                    with self._mu:
                        urllib.request.urlopen(url, timeout=5).close()
        """})
        fs = p.findings()
        assert prog_rules(p) == ["DF008"]
        assert "C._mu" in fs[0].message

    def test_urlopen_outside_lock_is_clean(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading
            import urllib.request

            class C:
                def __init__(self):
                    self._mu = threading.Lock()

                def f(self, url):
                    with self._mu:
                        pending = True
                    urllib.request.urlopen(url, timeout=5).close()
        """})
        assert p.findings() == []

    def test_transitive_self_dispatch_fires(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading
            import urllib.request

            class C:
                def __init__(self):
                    self._mu = threading.Lock()

                def f(self, url):
                    with self._mu:
                        self._fetch(url)

                def _fetch(self, url):
                    return urllib.request.urlopen(url, timeout=5).read()
        """})
        fs = p.findings()
        assert prog_rules(p) == ["DF008"]
        assert "C._fetch" in fs[0].message and "urlopen" in fs[0].message

    def test_nonblocking_self_dispatch_is_clean(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self.n = 0

                def f(self):
                    with self._mu:
                        self._bump()

                def _bump(self):
                    self.n += 1
        """})
        assert p.findings() == []

    def test_condition_wait_releases_own_lock(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class W:
                def __init__(self):
                    self._cv = threading.Condition()

                def ok(self):
                    with self._cv:
                        self._cv.wait()
        """})
        assert p.findings() == []

    def test_condition_wait_blocks_other_held_locks(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class W:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._cv = threading.Condition()

                def bad(self):
                    with self._mu:
                        with self._cv:
                            self._cv.wait()
        """})
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert len(df8) == 1
        holding = df8[0].message.split("holding", 1)[1].split("(chain", 1)[0]
        assert "W._mu" in holding and "W._cv" not in holding

    def test_bounded_primitives_are_clean(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class C:
                def __init__(self, q, t, ev):
                    self._mu = threading.Lock()
                    self.q, self.t, self.ev = q, t, ev

                def f(self):
                    with self._mu:
                        self.q.get(timeout=1.0)
                        self.t.join(5)
                        self.ev.wait(2.0)
        """})
        assert p.findings() == []

    def test_bare_primitives_under_lock_fire(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class C:
                def __init__(self, q, t, ev):
                    self._mu = threading.Lock()
                    self.q, self.t, self.ev = q, t, ev

                def f(self):
                    with self._mu:
                        self.q.get()
                        self.t.join()
                        self.ev.wait()
        """})
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert len(df8) == 3

    def test_manual_acquire_release_region(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading
            import urllib.request

            class C:
                def __init__(self):
                    self._mu = threading.Lock()

                def f(self, url):
                    self._mu.acquire()
                    urllib.request.urlopen(url, timeout=5).close()
                    self._mu.release()
                    urllib.request.urlopen(url, timeout=5).close()
        """})
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert len(df8) == 1

    def test_pragma_suppresses_df008(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading
            import urllib.request

            class C:
                def __init__(self):
                    self._mu = threading.Lock()

                def f(self, url):
                    with self._mu:
                        urllib.request.urlopen(url, timeout=5).close()  # dflint: disable=DF008 — reviewed: startup-only config fetch
        """})
        assert p.findings() == []

    def test_retry_call_under_lock_fires_even_when_resolved(self):
        p = prog({
            "dragonfly2_tpu/rpc/fretry.py": """
                def retry_call(fn, attempts=3, deadline_s=None):
                    for _ in range(attempts):
                        return fn()
            """,
            "dragonfly2_tpu/rpc/fclient.py": """
                import threading

                from .fretry import retry_call

                class Client:
                    def __init__(self):
                        self._mu = threading.Lock()

                    def call(self, fn):
                        with self._mu:
                            return retry_call(fn, deadline_s=None)
            """,
        })
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert df8 and "retry_call" in df8[0].message


class TestDF009Fixtures:
    def test_inverted_nested_pair_fires_by_name(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
        """})
        df9 = [f for f in p.findings() if f.rule == "DF009"]
        assert len(df9) == 1
        assert "Pair._a" in df9[0].message and "Pair._b" in df9[0].message

    def test_consistent_order_is_clean(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
        """})
        assert p.findings() == []

    def test_inversion_via_call_chain_fires(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        self._grab_b()

                def _grab_b(self):
                    with self._b:
                        pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
        """})
        df9 = [f for f in p.findings() if f.rule == "DF009"]
        assert len(df9) == 1

    def test_pragma_removes_reviewed_edge(self):
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:  # dflint: disable=DF009 — reviewed: forward() only runs single-threaded at boot
                            pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
        """})
        assert [f for f in p.findings() if f.rule == "DF009"] == []

    def test_same_lock_class_nesting_not_reported(self):
        # Two INSTANCES of one class may nest (parent/child containers);
        # instances are statically indistinguishable, so self-edges stay
        # out of cycle reports.
        p = prog({"dragonfly2_tpu/daemon/fa.py": """
            import threading

            class Node:
                def __init__(self):
                    self._mu = threading.Lock()

                def link(self, other: "Node"):
                    with self._mu:
                        with other._mu:
                            pass
        """})
        # The self-edge IS in the graph (witness parity)...
        key = "dragonfly2_tpu/daemon/fa.py:Node._mu"
        assert (key, key) in p.edge_keys()
        # ...but never reported as a cycle.
        assert [f for f in p.findings() if f.rule == "DF009"] == []


class TestCallGraphResolver:
    """Satellite: each resolution feature with true-positive AND
    true-negative fixtures."""

    URLOPEN_UTIL = """
        import urllib.request

        def fetch(url):
            return urllib.request.urlopen(url, timeout=5).read()

        def local_math(x):
            return x * 2
    """

    def test_module_alias_import_positive(self):
        p = prog({
            "dragonfly2_tpu/daemon/futil.py": self.URLOPEN_UTIL,
            "dragonfly2_tpu/daemon/fsvc.py": """
                import threading

                from .futil import fetch as grab

                class S:
                    def __init__(self):
                        self._mu = threading.Lock()

                    def f(self, url):
                        with self._mu:
                            return grab(url)
            """,
        })
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert df8 and "fetch" in df8[0].message

    def test_module_alias_import_negative(self):
        p = prog({
            "dragonfly2_tpu/daemon/futil.py": self.URLOPEN_UTIL,
            "dragonfly2_tpu/daemon/fsvc.py": """
                import threading

                from .futil import local_math as compute

                class S:
                    def __init__(self):
                        self._mu = threading.Lock()

                    def f(self, x):
                        with self._mu:
                            return compute(x)
            """,
        })
        assert p.findings() == []

    def test_module_level_alias_assignment(self):
        p = prog({
            "dragonfly2_tpu/daemon/fsvc.py": """
                import threading
                import urllib.request

                def _fetch_impl(url):
                    return urllib.request.urlopen(url, timeout=5).read()

                fetch = _fetch_impl

                class S:
                    def __init__(self):
                        self._mu = threading.Lock()

                    def f(self, url):
                        with self._mu:
                            return fetch(url)
            """,
        })
        assert [f.rule for f in p.findings()] == ["DF008"]

    def test_cls_method_dispatch(self):
        p = prog({"dragonfly2_tpu/daemon/fsvc.py": """
            import threading
            import urllib.request

            _LOCK = threading.Lock()

            class S:
                @classmethod
                def f(cls, url):
                    with _LOCK:
                        return cls._fetch(url)

                @classmethod
                def _fetch(cls, url):
                    return urllib.request.urlopen(url, timeout=5).read()
        """})
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert df8 and "<module>._LOCK" in df8[0].message

    def test_lock_under_non_mu_name(self):
        p = prog({"dragonfly2_tpu/daemon/fsvc.py": """
            import threading
            import urllib.request

            class S:
                def __init__(self):
                    self.gate = threading.Lock()

                def f(self, url):
                    with self.gate:
                        return urllib.request.urlopen(url, timeout=5).read()
        """})
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert df8 and "S.gate" in df8[0].message

    def test_decorator_wrapped_function_positive(self):
        p = prog({"dragonfly2_tpu/daemon/fsvc.py": """
            import functools
            import threading
            import urllib.request

            def logged(fn):
                @functools.wraps(fn)
                def wrapper(*a, **kw):
                    return fn(*a, **kw)
                return wrapper

            @logged
            def fetch(url):
                return urllib.request.urlopen(url, timeout=5).read()

            class S:
                def __init__(self):
                    self._mu = threading.Lock()

                def f(self, url):
                    with self._mu:
                        return fetch(url)
        """})
        assert [f.rule for f in p.findings()] == ["DF008"]

    def test_decorator_wrapped_function_negative(self):
        p = prog({"dragonfly2_tpu/daemon/fsvc.py": """
            import functools
            import threading

            def logged(fn):
                @functools.wraps(fn)
                def wrapper(*a, **kw):
                    return fn(*a, **kw)
                return wrapper

            @logged
            def compute(x):
                return x + 1

            class S:
                def __init__(self):
                    self._mu = threading.Lock()

                def f(self, x):
                    with self._mu:
                        return compute(x)
        """})
        assert p.findings() == []

    def test_factory_return_annotation_types_attr(self):
        p = prog({
            "dragonfly2_tpu/daemon/fstore.py": """
                import threading

                class Table:
                    def __init__(self):
                        self._mu = threading.Lock()

                    def put(self, v):
                        with self._mu:
                            return v

                class Backend:
                    def table(self) -> Table:
                        return Table()
            """,
            "dragonfly2_tpu/daemon/fsvc.py": """
                import threading

                from .fstore import Backend

                class S:
                    def __init__(self):
                        self._mu = threading.Lock()
                        b = Backend()
                        self._t = b.table()

                    def write(self, v):
                        with self._mu:
                            self._t.put(v)
            """,
        })
        assert (
            "dragonfly2_tpu/daemon/fsvc.py:S._mu",
            "dragonfly2_tpu/daemon/fstore.py:Table._mu",
        ) in p.edge_keys()

    def test_virtual_dispatch_reaches_subclass_override(self):
        p = prog({"dragonfly2_tpu/daemon/fsvc.py": """
            import threading
            import urllib.request

            class Base:
                def put(self, v):
                    raise NotImplementedError

            class Remote(Base):
                def put(self, v):
                    return urllib.request.urlopen(v, timeout=5).read()

            class S:
                def __init__(self, backend: Base):
                    self._mu = threading.Lock()
                    self._b = backend

                def write(self, v):
                    with self._mu:
                        self._b.put(v)
        """})
        assert [f.rule for f in p.findings()] == ["DF008"]

    def test_union_annotation_covers_both_arms(self):
        p = prog({"dragonfly2_tpu/daemon/fsvc.py": """
            import threading
            import urllib.request
            from typing import Union

            class Local:
                def go(self):
                    return 1

            class Remote:
                def go(self):
                    return urllib.request.urlopen("u", timeout=5).read()

            class S:
                def __init__(self, client: "Union[Local, Remote]"):
                    self._mu = threading.Lock()
                    self.client = client

                def f(self):
                    with self._mu:
                        return self.client.go()
        """})
        assert [f.rule for f in p.findings()] == ["DF008"]

    def test_condition_wrapping_explicit_lock_aliases_it(self):
        p = prog({"dragonfly2_tpu/daemon/fsvc.py": """
            import threading

            class S:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._cv = threading.Condition(self._mu)

                def wake(self):
                    with self._cv:
                        self._cv.notify_all()
        """})
        cv = p.locks["dragonfly2_tpu/daemon/fsvc.py:S._cv"]
        mu = p.locks["dragonfly2_tpu/daemon/fsvc.py:S._mu"]
        assert cv.base() is mu

    def test_chained_attribute_lock_resolution(self):
        # `with self._b._mu:` — the _SQLiteTable idiom.
        p = prog({"dragonfly2_tpu/daemon/fsvc.py": """
            import threading
            import urllib.request

            class Backend:
                def __init__(self):
                    self._mu = threading.Lock()

            class Table:
                def __init__(self, backend: "Backend"):
                    self._b = backend

                def put(self, url):
                    with self._b._mu:
                        return urllib.request.urlopen(url, timeout=5).read()
        """})
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert df8 and "Backend._mu" in df8[0].message


class TestProgramMutationSensitivity:
    """Satellite: DF008/DF009 against (copies of) the REAL tree."""

    def _program_with_source(self, relpath: str, source: str) -> Program:
        return _df_tree_program_with(relpath, source)

    def test_real_tree_is_clean(self):
        p = _df_tree_program()
        assert p.findings() == [], "\n".join(f.render() for f in p.findings())

    def test_wrapping_retry_call_in_held_lock_fails_df008(self):
        # Reintroduce the exact pre-PR bug: ModelSubscriber's network
        # phase moved back under _refresh_mu.
        relpath = "dragonfly2_tpu/scheduler/model_loader.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = "            active = self._fetch_active(loaded)"
        assert needle in source
        mutated = source.replace(
            needle,
            "            with self._refresh_mu:\n"
            "                active = self._fetch_active(loaded)",
        )
        p = self._program_with_source(relpath, mutated)
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert any(
            "_refresh_mu" in f.message and "retry_call" in f.message
            for f in df8
        ), "\n".join(f.render() for f in df8)

    def test_reordering_conductor_report_under_lock_fails_df008(self):
        relpath = "dragonfly2_tpu/daemon/conductor.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = (
            "        self.scheduler.report_piece_finished(\n"
            "            peer, number, parent_id=\"\", length=len(data), cost_ns=cost_ns\n"
            "        )"
        )
        assert needle in source
        mutated = source.replace(
            needle,
            "        with self._report_lock:\n"
            "            self.scheduler.report_piece_finished(\n"
            "                peer, number, parent_id=\"\", length=len(data), cost_ns=cost_ns\n"
            "            )",
        )
        p = self._program_with_source(relpath, mutated)
        df8 = [f for f in p.findings() if f.rule == "DF008"]
        assert any("_report_lock" in f.message for f in df8)

    def test_introducing_inversion_in_real_module_fails_df009(self):
        # Give the registry a helper that acquires state-table then
        # registry locks — the reverse of every existing path.
        relpath = "dragonfly2_tpu/manager/registry.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        mutated = source + textwrap.dedent("""

            def _debug_reverse_probe(registry: ModelRegistry, table: "_MemTable"):
                from .state import _MemTable

                with table._mu:
                    with registry._mu:
                        return True
        """)
        p = self._program_with_source(relpath, mutated)
        df9 = [f for f in p.findings() if f.rule == "DF009"]
        assert df9 and any("ModelRegistry._mu" in f.message for f in df9)


# ---------------------------------------------------------------------------
# CLI output modes + lock-graph emission (satellites)
# ---------------------------------------------------------------------------

REPO = Path(__file__).resolve().parents[1]

_DIRTY = (
    "def f():\n"
    "    try:\n"
    "        g()\n"
    "    except Exception:\n"
    "        pass\n"
)


class TestCLIFormats:
    def test_json_format(self, tmp_path, capsys):
        import json as _json

        from tools.dflint.__main__ import main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(_DIRTY)
        assert main([str(dirty), "--format", "json"]) == 1
        out = _json.loads(capsys.readouterr().out)
        assert out["accepted"] == 0 and out["errors"] == []
        assert out["findings"][0]["rule"] == "DF001"
        assert out["findings"][0]["line"] == 4

    def test_github_format(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(_DIRTY)
        assert main([str(dirty), "--format", "github"]) == 1
        out = capsys.readouterr().out
        assert f"::error file={dirty}" .replace(str(tmp_path) + "/", "") or True
        assert "::error file=" in out and "title=DF001" in out

    def test_rule_filter_excludes_other_rules(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(_DIRTY)
        assert main([str(dirty), "--rule", "DF008"]) == 0
        assert main([str(dirty), "--rule", "DF001,DF008"]) == 1

    def test_rule_filter_unknown_rule_errors(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        assert main(["--rule", "DF999"]) == 2

    def test_list_rules_includes_program_rules(self, capsys):
        from tools.dflint.__main__ import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        assert "DF008" in out and "DF009" in out

    def test_emit_lock_graph_prints_markers_and_dot(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        src = tmp_path / "locked.py"
        src.write_text(
            "import threading\n\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n\n"
            "    def f(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
        )
        assert main([str(src), "--emit-lock-graph"]) == 0
        out = capsys.readouterr().out
        assert "dflint:lock-graph:begin" in out
        assert "digraph lock_order" in out
        assert "A._a" in out and "A._b" in out


class TestLockGraphStaleness:
    """DESIGN.md §16's committed lock-hierarchy block must match a fresh
    emission — the same discipline as baseline.toml staleness."""

    def test_design_md_lock_graph_is_current(self):
        from tools.dflint.__main__ import (
            LOCK_GRAPH_BEGIN, LOCK_GRAPH_END, render_lock_graph,
        )

        program = _df_tree_program()
        text = (REPO / "DESIGN.md").read_text(encoding="utf-8")
        begin = text.find(LOCK_GRAPH_BEGIN)
        end = text.find(LOCK_GRAPH_END)
        assert begin >= 0 and end > begin, "DESIGN.md §16 lock-graph markers missing"
        committed = text[begin : end + len(LOCK_GRAPH_END)]
        fresh = render_lock_graph(program)
        assert committed == fresh, (
            "DESIGN.md §16 lock graph is stale — regenerate with "
            "`python -m tools.dflint --update-lock-graph DESIGN.md dragonfly2_tpu`"
        )

    def test_update_lock_graph_rewrites_in_place(self, tmp_path):
        from tools.dflint.__main__ import main

        doc = tmp_path / "DESIGN.md"
        doc.write_text(
            "# doc\n\n<!-- dflint:lock-graph:begin -->\nstale\n"
            "<!-- dflint:lock-graph:end -->\ntail\n"
        )
        src = tmp_path / "locked.py"
        src.write_text("import threading\n_MU = threading.Lock()\n")
        assert main([str(src), "--update-lock-graph", str(doc)]) == 0
        body = doc.read_text()
        assert "stale" not in body and "| held lock |" in body and "tail" in body


# ---------------------------------------------------------------------------
# Trace-discipline analysis (tools/dflint/tracerules.py): DF010 / DF011 /
# DF012 fixtures, plus mutation sensitivity against the REAL tree
# ---------------------------------------------------------------------------

from tools.dflint.tracerules import (  # noqa: E402
    TraceAnalysis,
    budget_staleness,
    load_budget,
    render_budget,
)


def trace(files: dict) -> TraceAnalysis:
    return TraceAnalysis(prog(files))


def trace_rules(a: TraceAnalysis):
    return sorted({f.rule for f in a.findings()})


class TestDF010Fixtures:
    def test_immediate_invoke_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            def step(x):
                return x + 1

            def run(x):
                return jax.jit(step)(x)
        """})
        assert any(
            f.rule == "DF010" and "immediately invoked" in f.message
            for f in a.findings()
        )

    def test_construction_in_loop_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            def step(x):
                return x + 1

            def run(xs):
                out = []
                for x in xs:
                    f = jax.jit(step)
                    out.append(f(x))
                return out
        """})
        assert any(
            f.rule == "DF010" and "loop body" in f.message for f in a.findings()
        )

    def test_init_cached_and_module_level_ok(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            def top(x):
                return x

            _F = jax.jit(top)

            class T:
                def __init__(self):
                    self._f = jax.jit(self._step, donate_argnums=(0,))

                def _step(self, x):
                    return x
        """})
        assert "DF010" not in trace_rules(a)

    def test_module_array_closure_capture_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax
            import numpy as np

            TABLE = np.zeros((4, 4), dtype=np.float32)

            @jax.jit
            def step(x):
                return x @ TABLE
        """})
        fs = [f for f in a.findings() if f.rule == "DF010"]
        assert len(fs) == 1 and "TABLE" in fs[0].message
        assert "constant-folded" in fs[0].message

    def test_argument_passing_is_not_capture(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax
            import numpy as np

            @jax.jit
            def step(x, table):
                return x @ table
        """})
        assert "DF010" not in trace_rules(a)

    def test_list_arg_to_jitted_module_var_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            def step(x):
                return x

            _F = jax.jit(step)

            def call(v):
                return _F([v, v, v])
        """})
        assert any(
            f.rule == "DF010" and "pad-ladder" in f.message for f in a.findings()
        )

    def test_list_arg_to_jitted_self_attr_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            class T:
                def __init__(self):
                    self._f = jax.jit(self._step)

                def _step(self, x):
                    return x

                def call(self, v):
                    return self._f([v])
        """})
        assert any(
            f.rule == "DF010" and "Python container" in f.message
            for f in a.findings()
        )

    def test_nonstatic_branch_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            @jax.jit
            def step(x, n):
                if n > 2:
                    return x
                return -x
        """})
        fs = [f for f in a.findings() if f.rule == "DF010"]
        assert len(fs) == 1 and "'n'" in fs[0].message

    def test_range_over_nonstatic_param_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            @jax.jit
            def step(x, hops):
                for _ in range(hops):
                    x = x + 1
                return x
        """})
        assert any(
            f.rule == "DF010" and "'hops'" in f.message for f in a.findings()
        )

    def test_declared_static_and_partial_bound_ok(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("n",))
            def step(x, n):
                if n > 2:
                    return x
                return -x

            def kernel(x, exact):
                if exact:
                    return x
                return -x

            def launch(x):
                k = functools.partial(kernel, exact=True)
                return jax.jit(k)(x)  # dflint: disable=DF010 — fixture: bound-kwarg negative
        """})
        assert "DF010" not in trace_rules(a)

    def test_is_none_branch_is_exempt(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            @jax.jit
            def step(x, qef=None):
                if qef is None:
                    return x
                return x + qef
        """})
        assert "DF010" not in trace_rules(a)

    def test_construction_in_hotpath_fires(self):
        a = trace({"dragonfly2_tpu/scheduler/fx.py": """
            import jax

            def serve(x):  # dflint: hotpath
                f = jax.jit(lambda y: y + 1)
                return f(x)
        """})
        assert any(
            f.rule == "DF010" and "hotpath" in f.message for f in a.findings()
        )

    def test_pragma_suppresses(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            def step(x):
                return x

            def run(x):
                return jax.jit(step)(x)  # dflint: disable=DF010 — one-shot tool path, reviewed
        """})
        assert "DF010" not in trace_rules(a)


class TestDF011Fixtures:
    def test_reachable_helper_asarray_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax
            import numpy as np

            def helper(x):
                return np.asarray(x)

            @jax.jit
            def step(x):
                return helper(x)
        """})
        fs = [f for f in a.findings() if f.rule == "DF011"]
        assert len(fs) == 1 and "reachable from traced" in fs[0].message

    def test_traced_body_itself_is_df003s_beat(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return np.asarray(x)
        """})
        assert "DF011" not in trace_rules(a)

    def test_unreachable_helper_is_free(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax
            import numpy as np

            def helper(x):
                return np.asarray(x).item()

            @jax.jit
            def step(x):
                return x + 1
        """})
        assert "DF011" not in trace_rules(a)

    def test_block_until_ready_in_reachable_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax

            def sync(x):
                x.block_until_ready()
                return x

            @jax.jit
            def step(x):
                return sync(x)
        """})
        assert any(
            f.rule == "DF011" and "block_until_ready" in f.message
            for f in a.findings()
        )

    def test_item_in_hotpath_fires(self):
        a = trace({"dragonfly2_tpu/scheduler/fx.py": """
            def gather(rows):  # dflint: hotpath
                return rows.sum().item()
        """})
        fs = [f for f in a.findings() if f.rule == "DF011"]
        assert len(fs) == 1 and "hotpath" in fs[0].message

    def test_hotpath_numpy_asarray_is_allowed(self):
        # Host-side numpy marshalling is the hot path's JOB; only device
        # syncs (.item/.tolist/device_get/block_until_ready) are leaks.
        a = trace({"dragonfly2_tpu/scheduler/fx.py": """
            import numpy as np

            def gather(rows):  # dflint: hotpath
                return np.asarray(rows, dtype=np.float32)
        """})
        assert "DF011" not in trace_rules(a)

    def test_pragma_suppresses(self):
        a = trace({"dragonfly2_tpu/scheduler/fx.py": """
            def gather(rows):  # dflint: hotpath
                return rows.sum().item()  # dflint: disable=DF011 — fixture: reviewed sync
        """})
        assert "DF011" not in trace_rules(a)


_FX_CONTRACTS = """
CONTRACTS = {
    "fx.rows": {
        "file": "dragonfly2_tpu/records/fx.py",
        "dtype": "float32",
        "functions": ["make_rows"],
    },
    "fx.slots": {
        "file": "dragonfly2_tpu/records/fx.py",
        "attrs": {"Cache._m": "float32"},
    },
    "fx.defaults": {
        "file": "dragonfly2_tpu/records/fx.py",
        "defaults": {"Writer.__init__.dtype": "float32"},
    },
}
"""

# Indented to match the method-level fixture strings it concatenates with
# (one textwrap.dedent normalizes the whole file).
_FX_CLEAN_TAIL = """
            class Cache:
                def __init__(self):
                    self._m = np.empty((2, 2), dtype=np.float32)

            class Writer:
                def __init__(self, dtype="float32"):
                    self.dtype = dtype
"""


def _df012(fx_body: str) -> TraceAnalysis:
    # Dedent here: fixture bodies are written at method indent while
    # _FX_CLEAN_TAIL is at module indent — prog()'s single dedent cannot
    # normalize the concatenation.
    return trace({
        "dragonfly2_tpu/records/contracts.py": _FX_CONTRACTS,
        "dragonfly2_tpu/records/fx.py": textwrap.dedent(fx_body),
    })


class TestDF012Fixtures:
    def test_clean_contract_passes(self):
        a = _df012("""
            import numpy as np

            def make_rows(n):
                return np.zeros((n, 4), dtype=np.float32)
        """ + _FX_CLEAN_TAIL)
        assert "DF012" not in trace_rules(a)

    def test_widened_producer_fires_by_contract_name(self):
        a = _df012("""
            import numpy as np

            def make_rows(n):
                return np.zeros((n, 4), dtype=np.float64)
        """ + _FX_CLEAN_TAIL)
        fs = [f for f in a.findings() if f.rule == "DF012"]
        assert len(fs) == 1 and "'fx.rows'" in fs[0].message

    def test_implicit_float64_constructor_fires(self):
        a = _df012("""
            import numpy as np

            def make_rows(n):
                return np.zeros((n, 4))
        """ + _FX_CLEAN_TAIL)
        assert any(
            f.rule == "DF012" and "without an explicit dtype" in f.message
            for f in a.findings()
        )

    def test_widened_attr_pin_fires(self):
        a = _df012("""
            import numpy as np

            def make_rows(n):
                return np.zeros((n, 4), dtype=np.float32)

            class Cache:
                def __init__(self):
                    self._m = np.empty((2, 2), dtype=np.float64)

            class Writer:
                def __init__(self, dtype="float32"):
                    self.dtype = dtype
        """)
        assert any(
            f.rule == "DF012" and "'fx.slots'" in f.message
            and "Cache._m" in f.message
            for f in a.findings()
        )

    def test_missing_attr_pin_fires(self):
        a = _df012("""
            import numpy as np

            def make_rows(n):
                return np.zeros((n, 4), dtype=np.float32)

            class Cache:
                def __init__(self):
                    self._m = {}

            class Writer:
                def __init__(self, dtype="float32"):
                    self.dtype = dtype
        """)
        assert any(
            f.rule == "DF012" and "no array-constructor assignment" in f.message
            for f in a.findings()
        )

    def test_drifted_default_fires(self):
        a = _df012("""
            import numpy as np

            def make_rows(n):
                return np.zeros((n, 4), dtype=np.float32)

            class Cache:
                def __init__(self):
                    self._m = np.empty((2, 2), dtype=np.float32)

            class Writer:
                def __init__(self, dtype="float64"):
                    self.dtype = dtype
        """)
        assert any(
            f.rule == "DF012" and "'fx.defaults'" in f.message
            for f in a.findings()
        )

    def test_renamed_producer_fires(self):
        a = _df012("""
            import numpy as np

            def build_rows(n):
                return np.zeros((n, 4), dtype=np.float32)
        """ + _FX_CLEAN_TAIL)
        assert any(
            f.rule == "DF012" and "'make_rows' missing" in f.message
            for f in a.findings()
        )

    def test_float64_in_traced_def_fires(self):
        a = trace({"dragonfly2_tpu/trainer/fx.py": """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                return x.astype(jnp.float64)
        """})
        fs = [f for f in a.findings() if f.rule == "DF012"]
        assert len(fs) == 1 and "x64 is" in fs[0].message

    def test_pragma_suppresses(self):
        a = _df012("""
            import numpy as np

            def make_rows(n):
                return np.zeros((n, 4), dtype=np.float64)  # dflint: disable=DF012 — fixture: reviewed widening
        """ + _FX_CLEAN_TAIL)
        assert "DF012" not in trace_rules(a)


class TestTraceMutationSensitivity:
    """The acceptance contract against the REAL tree: un-caching a jitted
    step, adding an .item() to a hotpath, or widening a DFC1 column to
    float64 must each fail BY RULE NAME."""

    def _analyze_with(self, relpath: str, mutated: str) -> TraceAnalysis:
        return TraceAnalysis(_df_tree_program_with(relpath, mutated), REPO)

    @pytest.fixture(scope="class")
    def real_analysis(self):
        return TraceAnalysis(_df_tree_program(), REPO)

    def test_real_tree_is_clean(self, real_analysis):
        assert real_analysis.findings() == []

    def test_uncaching_streaming_step_fails_df010(self):
        relpath = "dragonfly2_tpu/trainer/streaming.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = "self.params, self.opt_state, loss = self._step_fn("
        assert needle in source
        mutated = source.replace(
            needle,
            "self.params, self.opt_state, loss = "
            "jax.jit(self._train_step, donate_argnums=(0, 1))(",
        )
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF010" and f.path == relpath for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_item_in_real_hotpath_fails_df011(self):
        relpath = "dragonfly2_tpu/scheduler/featcache.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = "return self.gather_with_buckets(hosts)[0]"
        assert needle in source
        mutated = source.replace(
            needle,
            "rows = self.gather_with_buckets(hosts)[0]\n"
            "        _ = rows.sum().item()\n"
            "        return rows",
        )
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF011" and f.path == relpath
            and "hotpath" in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_widening_dfc1_column_fails_df012(self):
        relpath = "dragonfly2_tpu/records/features.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = "out = np.zeros(HOST_FEATURE_DIM, dtype=np.float32)"
        assert needle in source
        mutated = source.replace(
            needle, "out = np.zeros(HOST_FEATURE_DIM, dtype=np.float64)"
        )
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF012" and "'dfc1.download'" in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_widening_columnar_writer_default_fails_df012(self):
        relpath = "dragonfly2_tpu/records/columnar.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        mutated = source.replace('dtype: str = "float32"', 'dtype: str = "float64"')
        assert mutated != source
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF012" and "'dfc1.file'" in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]


class TestCompileBudgetFile:
    def test_checked_in_budget_is_current(self):
        analysis = TraceAnalysis(_df_tree_program(), REPO)
        gaps = budget_staleness(analysis, load_budget())
        assert not gaps, "\n".join(gaps)

    def test_render_preserves_existing_bounds(self):
        text = render_budget(["a.py:f", "b.py:g"], {"a.py:f": 9})
        assert '"a.py:f" = 9' in text and '"b.py:g" = 4' in text

    def test_cli_rule_filter_covers_trace_rules(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        src = tmp_path / "fx.py"
        src.write_text(
            "import jax\n\n"
            "def step(x):\n    return x\n\n"
            "def run(x):\n    return jax.jit(step)(x)\n"
        )
        rc = main([str(src), "--rule", "DF010", "--no-baseline"])
        out = capsys.readouterr().out
        assert rc == 1 and "DF010" in out
        rc = main([str(src), "--rule", "DF012", "--no-baseline"])
        assert rc == 0


# ---------------------------------------------------------------------------
# State-machine / crash-consistency / RPC-parity analysis
# (tools/dflint/staterules.py): DF013 / DF014 / DF015 fixtures, contract
# staleness, plus mutation sensitivity against the REAL tree
# ---------------------------------------------------------------------------

from tools.dflint.staterules import (  # noqa: E402
    StateAnalysis,
    crash_witness_gaps,
)

_SC_PATH = "dragonfly2_tpu/records/state_contracts.py"


def state(files: dict) -> StateAnalysis:
    return StateAnalysis(prog(files))


def state_rules(a: StateAnalysis):
    return sorted({f.rule for f in a.findings()})


_FSM_CONTRACT = """
STATE_CONTRACTS = {
    "machines": {
        "widget": {
            "kind": "fsm",
            "file": "dragonfly2_tpu/daemon/w.py",
            "class": "Widget",
            "attr": "fsm",
            "events_var": "W_EVENTS",
            "initial": "Idle",
            "states": ["Idle", "Busy"],
            "events": {
                "Start": [["Idle", "Busy"]],
                "Stop": [["Busy", "Idle"]],
            },
            "mirrors": {"fsm_state": ["Widget.__init__", "Widget._mirror"]},
            "set_state_modules": ["dragonfly2_tpu/daemon/mirror.py"],
        },
    },
}
"""

_W_SRC = """
from ..utils.fsm import FSM, EventDesc

W_IDLE = "Idle"
W_BUSY = "Busy"
W_EVENTS = (
    EventDesc("Start", (W_IDLE,), W_BUSY),
    EventDesc("Stop", (W_BUSY,), W_IDLE),
)


class Widget:
    def __init__(self):
        self.fsm_state = W_IDLE
        self.fsm = FSM(W_IDLE, W_EVENTS,
                       callbacks={"enter_state": self._mirror})

    def _mirror(self, fsm, event, src, dst):
        self.fsm_state = dst

    def go(self):
        self.fsm.event("Start")
"""


class TestDF013FsmFixtures:
    def test_clean_machine_passes(self):
        a = state({_SC_PATH: _FSM_CONTRACT, "dragonfly2_tpu/daemon/w.py": _W_SRC})
        assert a.findings() == [], [f.render() for f in a.findings()]

    def test_undeclared_event_fires_by_machine_name(self):
        src = _W_SRC + """

    def explode(self):
        self.fsm.event("Explode")
"""
        a = state({_SC_PATH: _FSM_CONTRACT, "dragonfly2_tpu/daemon/w.py": src})
        assert any(
            f.rule == "DF013" and "'widget'" in f.message
            and "'Explode'" in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_code_event_missing_from_contract_fires(self):
        src = _W_SRC.replace(
            'EventDesc("Stop", (W_BUSY,), W_IDLE),',
            'EventDesc("Stop", (W_BUSY,), W_IDLE),\n'
            '    EventDesc("Kill", (W_BUSY,), W_IDLE),',
        )
        a = state({_SC_PATH: _FSM_CONTRACT, "dragonfly2_tpu/daemon/w.py": src})
        assert any(
            f.rule == "DF013" and "'Kill'" in f.message
            and "not declared" in f.message
            for f in a.findings()
        )

    def test_stale_contract_event_fires(self):
        contract = _FSM_CONTRACT.replace(
            '"Stop": [["Busy", "Idle"]],',
            '"Stop": [["Busy", "Idle"]],\n                "Pause": [["Busy", "Idle"]],',
        )
        a = state({_SC_PATH: contract, "dragonfly2_tpu/daemon/w.py": _W_SRC})
        assert any(
            f.rule == "DF013" and "'Pause'" in f.message
            and "stale" in f.message
            for f in a.findings()
        )

    def test_edge_drift_fires(self):
        contract = _FSM_CONTRACT.replace(
            '"Stop": [["Busy", "Idle"]],', '"Stop": [["Idle", "Idle"]],'
        )
        a = state({_SC_PATH: contract, "dragonfly2_tpu/daemon/w.py": _W_SRC})
        assert any(
            f.rule == "DF013" and "edges drifted" in f.message
            for f in a.findings()
        )

    def test_forwarder_literal_is_checked(self):
        src = _W_SRC + """

def try_event(fsm, name):
    fsm.event(name)


def drive(w: "Widget"):
    try_event(w.fsm, "Vanish")
"""
        a = state({_SC_PATH: _FSM_CONTRACT, "dragonfly2_tpu/daemon/w.py": src})
        assert any(
            f.rule == "DF013" and "'Vanish'" in f.message
            for f in a.findings()
        )

    def test_set_state_in_mirror_module_with_declared_state_ok(self):
        mirror = """
from .w import Widget


def force(w: "Widget"):
    w.fsm.set_state("Idle")
"""
        a = state({
            _SC_PATH: _FSM_CONTRACT,
            "dragonfly2_tpu/daemon/w.py": _W_SRC,
            "dragonfly2_tpu/daemon/mirror.py": mirror,
        })
        assert a.findings() == [], [f.render() for f in a.findings()]

    def test_set_state_outside_mirror_modules_fires(self):
        rogue = """
from .w import Widget


def force(w: "Widget"):
    w.fsm.set_state("Idle")
"""
        a = state({
            _SC_PATH: _FSM_CONTRACT,
            "dragonfly2_tpu/daemon/w.py": _W_SRC,
            "dragonfly2_tpu/daemon/rogue.py": rogue,
        })
        assert any(
            f.rule == "DF013" and "set_state" in f.message
            and f.path == "dragonfly2_tpu/daemon/rogue.py"
            for f in a.findings()
        )

    def test_set_state_to_undeclared_state_fires(self):
        mirror = """
from .w import Widget


def force(w: "Widget"):
    w.fsm.set_state("Haunted")
"""
        a = state({
            _SC_PATH: _FSM_CONTRACT,
            "dragonfly2_tpu/daemon/w.py": _W_SRC,
            "dragonfly2_tpu/daemon/mirror.py": mirror,
        })
        assert any(
            f.rule == "DF013" and "'Haunted'" in f.message
            for f in a.findings()
        )

    def test_mirror_write_outside_writers_fires(self):
        src = _W_SRC + """

def rogue(w):
    w.fsm_state = W_BUSY
"""
        a = state({_SC_PATH: _FSM_CONTRACT, "dragonfly2_tpu/daemon/w.py": src})
        assert any(
            f.rule == "DF013" and "mirror 'fsm_state'" in f.message
            for f in a.findings()
        )

    def test_pragma_suppresses(self):
        src = _W_SRC + """

def rogue(w):
    w.fsm_state = W_BUSY  # dflint: disable=DF013
"""
        a = state({_SC_PATH: _FSM_CONTRACT, "dragonfly2_tpu/daemon/w.py": src})
        assert a.findings() == []


_ENUM_CONTRACT = """
STATE_CONTRACTS = {
    "machines": {
        "light": {
            "kind": "enum",
            "file": "dragonfly2_tpu/daemon/light.py",
            "enum": "LightState",
            "owner_class": "Light",
            "state_attr": "state",
            "owner_modules": ["dragonfly2_tpu/daemon/light.py"],
            "states": ["on", "off"],
            "edges": [["off", "on"], ["on", "off"]],
            "gateway_attrs": ["lights"],
            "mutators": {
                "dragonfly2_tpu/daemon/light.py": ["on", "off"],
                "dragonfly2_tpu/daemon/ctrl.py": ["off"],
            },
        },
    },
}
"""

_LIGHT_SRC = """
import enum


class LightState(str, enum.Enum):
    ON = "on"
    OFF = "off"


class Light:
    def __init__(self):
        self.state = LightState.OFF


class LightRegistry:
    def activate(self, light):
        light.state = LightState.ON
"""


class TestDF013EnumFixtures:
    def test_clean_passes(self):
        a = state({
            _SC_PATH: _ENUM_CONTRACT,
            "dragonfly2_tpu/daemon/light.py": _LIGHT_SRC,
        })
        assert a.findings() == [], [f.render() for f in a.findings()]

    def test_direct_state_write_outside_owner_fires(self):
        ctrl = """
from .light import LightState


def rogue(light):
    light.state = LightState.ON
"""
        a = state({
            _SC_PATH: _ENUM_CONTRACT,
            "dragonfly2_tpu/daemon/light.py": _LIGHT_SRC,
            "dragonfly2_tpu/daemon/ctrl.py": ctrl,
        })
        assert any(
            f.rule == "DF013" and "outside the owning module" in f.message
            for f in a.findings()
        )

    def test_gateway_call_with_allowed_state_ok(self):
        ctrl = """
from .light import LightRegistry, LightState


def shutdown(lights: "LightRegistry", light):
    lights.set_state(light, LightState.OFF)
"""
        a = state({
            _SC_PATH: _ENUM_CONTRACT,
            "dragonfly2_tpu/daemon/light.py": _LIGHT_SRC
            + """
    def set_state(self, light, st):
        light.state = st
""",
            "dragonfly2_tpu/daemon/ctrl.py": ctrl,
        })
        assert a.findings() == [], [f.render() for f in a.findings()]

    def test_gateway_call_with_forbidden_state_fires(self):
        ctrl = """
from .light import LightRegistry, LightState


def rogue(lights: "LightRegistry", light):
    lights.set_state(light, LightState.ON)
"""
        a = state({
            _SC_PATH: _ENUM_CONTRACT,
            "dragonfly2_tpu/daemon/light.py": _LIGHT_SRC
            + """
    def set_state(self, light, st):
        light.state = st
""",
            "dragonfly2_tpu/daemon/ctrl.py": ctrl,
        })
        assert any(
            f.rule == "DF013" and "may not request state 'on'" in f.message
            for f in a.findings()
        )

    def test_gateway_call_from_undeclared_module_fires(self):
        rogue = """
from .light import LightState


def flip(registry, light):
    registry.set_state(light, LightState.OFF)
"""
        a = state({
            _SC_PATH: _ENUM_CONTRACT,
            "dragonfly2_tpu/daemon/light.py": _LIGHT_SRC,
            "dragonfly2_tpu/daemon/zzz.py": rogue,
        })
        assert any(
            f.rule == "DF013" and "not a declared mutator module" in f.message
            for f in a.findings()
        )

    def test_stale_declared_state_fires(self):
        contract = _ENUM_CONTRACT.replace(
            '"states": ["on", "off"],', '"states": ["on", "off", "dim"],'
        )
        a = state({
            _SC_PATH: contract,
            "dragonfly2_tpu/daemon/light.py": _LIGHT_SRC,
        })
        assert any(
            f.rule == "DF013" and "'dim'" in f.message
            and "no enum member" in f.message
            for f in a.findings()
        )

    def test_new_enum_member_not_declared_fires(self):
        src = _LIGHT_SRC.replace('OFF = "off"', 'OFF = "off"\n    DIM = "dim"')
        a = state({
            _SC_PATH: _ENUM_CONTRACT,
            "dragonfly2_tpu/daemon/light.py": src,
        })
        assert any(
            f.rule == "DF013" and "'dim'" in f.message
            and "not declared" in f.message
            for f in a.findings()
        )


_P_CONTRACT = """
STATE_CONTRACTS = {
    "machines": {},
    "persistence": {
        "namespaces": {
            "widgets": {
                "owner": "dragonfly2_tpu/daemon/store.py",
                "lock": ["dragonfly2_tpu/daemon/store.py", "WidgetStore", "_mu"],
                "loader": "WidgetStore.__init__",
                "multi_row": ["WidgetStore._flip"],
                "unlocked_ok": [],
                "invariant": "x",
            },
        },
        "write_order": [],
        "foreign_keys": [],
        "implementation": [],
    },
}
"""

_STORE_SRC = """
import threading


class WidgetStore:
    def __init__(self, backend):
        self._mu = threading.Lock()
        self._table = backend.table("widgets")
        self._rows = self._table.load_all()

    def flip_two(self, a, b):
        with self._mu:
            self._flip(a, b)

    def _flip(self, a, b):
        self._table.put_many({a: {}, b: {}})
"""


class TestDF014Fixtures:
    def test_clean_store_passes(self):
        a = state({
            _SC_PATH: _P_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": _STORE_SRC,
        })
        assert a.findings() == [], [f.render() for f in a.findings()]

    def test_split_put_in_multi_row_site_fires(self):
        src = _STORE_SRC.replace(
            "        self._table.put_many({a: {}, b: {}})",
            "        self._table.put(a, {})\n        self._table.put(b, {})",
        )
        a = state({
            _SC_PATH: _P_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert any(
            f.rule == "DF014" and "multi-row site WidgetStore._flip"
            in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_multi_row_site_without_put_many_fires(self):
        src = _STORE_SRC.replace(
            "        self._table.put_many({a: {}, b: {}})",
            "        pass",
        )
        a = state({
            _SC_PATH: _P_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert any(
            f.rule == "DF014" and "no put_many" in f.message
            for f in a.findings()
        )

    def test_unlocked_write_fires(self):
        src = _STORE_SRC + """

    def rogue(self, k):
        self._table.put(k, {})
"""
        a = state({
            _SC_PATH: _P_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert any(
            f.rule == "DF014" and "without the owning lock" in f.message
            for f in a.findings()
        )

    def test_lock_inherited_from_all_callers_is_clean(self):
        # _flip writes without a lexical lock; flip_two covers it.  The
        # clean fixture already proves this — assert the negative
        # explicitly: removing the caller's lock flips it to a finding.
        src = _STORE_SRC.replace(
            "        with self._mu:\n            self._flip(a, b)",
            "        self._flip(a, b)",
        )
        a = state({
            _SC_PATH: _P_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert any(f.rule == "DF014" for f in a.findings())

    def test_unlocked_read_in_writing_function_fires(self):
        src = _STORE_SRC + """

    def bump(self, k):
        row = self._table.get(k)
        with self._mu:
            self._table.put(k, row or {})
"""
        a = state({
            _SC_PATH: _P_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert any(
            f.rule == "DF014" and "read (in a writing function)" in f.message
            for f in a.findings()
        )

    def test_unlocked_ok_declaration_exempts(self):
        contract = _P_CONTRACT.replace(
            '"unlocked_ok": [],', '"unlocked_ok": ["WidgetStore.rogue"],'
        )
        src = _STORE_SRC + """

    def rogue(self, k):
        self._table.put(k, {})
"""
        a = state({
            _SC_PATH: contract,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert a.findings() == []

    def test_undeclared_namespace_fires(self):
        src = _STORE_SRC + """

    def scratch(self, backend, k):
        with self._mu:
            t = backend.table("scratch")
            t.put(k, {})
"""
        a = state({
            _SC_PATH: _P_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert any(
            f.rule == "DF014" and "'scratch'" in f.message
            and "not declared" in f.message
            for f in a.findings()
        )

    def test_stale_declared_namespace_fires(self):
        contract = _P_CONTRACT.replace(
            '"invariant": "x",\n            },',
            '"invariant": "x",\n            },\n'
            '            "ghosts": {\n'
            '                "owner": "dragonfly2_tpu/daemon/store.py",\n'
            '                "lock": ["dragonfly2_tpu/daemon/store.py",\n'
            '                         "WidgetStore", "_mu"],\n'
            '                "loader": "WidgetStore.__init__",\n'
            '                "multi_row": [],\n'
            '                "unlocked_ok": [],\n'
            '                "invariant": "x",\n'
            '            },',
        )
        a = state({
            _SC_PATH: contract,
            "dragonfly2_tpu/daemon/store.py": _STORE_SRC,
        })
        assert any(
            f.rule == "DF014" and "'ghosts'" in f.message
            and "never bound" in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_loader_without_load_all_fires(self):
        src = _STORE_SRC.replace(
            "        self._rows = self._table.load_all()",
            "        self._rows = {}",
        )
        a = state({
            _SC_PATH: _P_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert any(
            f.rule == "DF014" and "no longer calls load_all" in f.message
            for f in a.findings()
        )

    def test_loader_unreachable_from_constructor_fires(self):
        contract = _P_CONTRACT.replace(
            '"loader": "WidgetStore.__init__",',
            '"loader": "WidgetStore.reload",',
        )
        src = _STORE_SRC + """

    def reload(self):
        self._rows = self._table.load_all()
"""
        a = state({
            _SC_PATH: contract,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert any(
            f.rule == "DF014" and "not reachable from any constructor"
            in f.message
            for f in a.findings()
        )

    _ORDER_CONTRACT = '''
STATE_CONTRACTS = {
    "machines": {},
    "persistence": {
        "namespaces": {
            "widgets": {
                "owner": "dragonfly2_tpu/daemon/store.py",
                "lock": ["dragonfly2_tpu/daemon/store.py", "WidgetStore", "_mu"],
                "loader": "WidgetStore.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "x",
            },
            "refs": {
                "owner": "dragonfly2_tpu/daemon/store.py",
                "lock": ["dragonfly2_tpu/daemon/store.py", "WidgetStore", "_mu"],
                "loader": "WidgetStore.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "x",
            },
        },
        "write_order": [["widgets", "refs"]],
        "foreign_keys": [],
        "implementation": [],
    },
}
'''

    def test_write_order_violation_fires_and_fix_passes(self):
        bad = _STORE_SRC.replace(
            "        self._table = backend.table(\"widgets\")\n"
            "        self._rows = self._table.load_all()",
            "        self._table = backend.table(\"widgets\")\n"
            "        self._refs = backend.table(\"refs\")\n"
            "        self._rows = self._table.load_all()\n"
            "        self._refs.load_all()",
        ) + '''

    def add(self, k):
        with self._mu:
            self._refs.put(k, {})
            self._table.put(k, {})
'''
        a = state({
            _SC_PATH: self._ORDER_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": bad,
        })
        assert any(
            f.rule == "DF014" and "write-order violation" in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]
        good = bad.replace(
            "            self._refs.put(k, {})\n"
            "            self._table.put(k, {})",
            "            self._table.put(k, {})\n"
            "            self._refs.put(k, {})",
        )
        a2 = state({
            _SC_PATH: self._ORDER_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": good,
        })
        assert not any(
            "write-order" in f.message for f in a2.findings()
        ), [f.render() for f in a2.findings()]

    _FK_CONTRACT = '''
STATE_CONTRACTS = {
    "machines": {},
    "persistence": {
        "namespaces": {
            "widgets": {
                "owner": "dragonfly2_tpu/daemon/store.py",
                "lock": ["dragonfly2_tpu/daemon/store.py", "WidgetStore", "_mu"],
                "loader": "WidgetStore.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "x",
            },
            "refs": {
                "owner": "dragonfly2_tpu/daemon/refs.py",
                "lock": ["dragonfly2_tpu/daemon/refs.py", "RefStore", "_mu"],
                "loader": "RefStore.__init__",
                "multi_row": [],
                "unlocked_ok": [],
                "invariant": "x",
            },
        },
        "write_order": [],
        "foreign_keys": [
            {
                "parent": "widgets",
                "child": "refs",
                "primitive": "WidgetStore.drop",
                "cleanup": "RefStore.drop_widget",
                "cleanup_file": "dragonfly2_tpu/daemon/refs.py",
            },
        ],
        "implementation": [],
    },
}
'''

    def test_foreign_key_primitive_called_outside_cleanup_fires(self):
        store = _STORE_SRC + '''

    def drop(self, k):
        with self._mu:
            self._table.delete(k)
'''
        refs = '''
import threading

from .store import WidgetStore


class RefStore:
    def __init__(self, backend, store: "WidgetStore"):
        self._mu = threading.Lock()
        self._refs = backend.table("refs")
        self._rows = self._refs.load_all()
        self.store = store

    def drop_widget(self, k):
        with self._mu:
            self._refs.delete(k)
            self.store.drop(k)
'''
        rogue = refs + '''

def bypass(store: "WidgetStore", k):
    store.drop(k)
'''
        a = state({
            _SC_PATH: self._FK_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": store,
            "dragonfly2_tpu/daemon/refs.py": rogue,
        })
        assert any(
            f.rule == "DF014" and "outside the declared cleanup" in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]
        a2 = state({
            _SC_PATH: self._FK_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": store,
            "dragonfly2_tpu/daemon/refs.py": refs,
        })
        assert a2.findings() == [], [f.render() for f in a2.findings()]

    def test_pragma_suppresses(self):
        src = _STORE_SRC + """

    def rogue(self, k):
        self._table.put(k, {})  # dflint: disable=DF014
"""
        a = state({
            _SC_PATH: _P_CONTRACT,
            "dragonfly2_tpu/daemon/store.py": src,
        })
        assert a.findings() == []


_R_CONTRACT = """
STATE_CONTRACTS = {
    "machines": {},
    "persistence": {"namespaces": {}, "write_order": [],
                    "foreign_keys": [], "implementation": []},
    "rpc": {
        "svc": {
            "clients": {"dragonfly2_tpu/rpc/cl.py": ["Client"]},
            "server": ["dragonfly2_tpu/rpc/srv.py", "Adapter", "METHODS"],
            "grpc": ["dragonfly2_tpu/rpc/g.py", "G_METHODS"],
            "idempotent": ["ping"],
            "deduped": {"push": "dedup_push"},
            "seam_files": ["dragonfly2_tpu/rpc/srv.py"],
        },
    },
}
"""

_SRV_SRC = """
def dedup_push():
    pass


class Adapter:
    METHODS = frozenset({"ping", "push"})

    def ping(self, req):
        return {}

    def push(self, req):
        return {}
"""

_G_SRC = """
G_METHODS = {
    "ping": ("PingReq", "PingResp"),
    "push": ("PushReq", "PushResp"),
}
"""

_CL_SRC = """
class Client:
    def _call(self, method, req):
        return {}

    def ping(self):
        return self._call("ping", {})

    def push(self):
        return self._call("push", {})
"""


class TestDF015Fixtures:
    def _files(self, srv=_SRV_SRC, g=_G_SRC, cl=_CL_SRC, contract=_R_CONTRACT):
        return {
            _SC_PATH: contract,
            "dragonfly2_tpu/rpc/srv.py": srv,
            "dragonfly2_tpu/rpc/g.py": g,
            "dragonfly2_tpu/rpc/cl.py": cl,
        }

    def test_clean_parity_passes(self):
        a = state(self._files())
        assert a.findings() == [], [f.render() for f in a.findings()]

    def test_deleted_grpc_entry_fires_by_method_name(self):
        g = _G_SRC.replace('    "push": ("PushReq", "PushResp"),\n', "")
        a = state(self._files(g=g))
        assert any(
            f.rule == "DF015" and "'push'" in f.message
            and "gRPC transport table" in f.message
            for f in a.findings()
        )

    def test_deleted_dispatch_entry_fires(self):
        srv = _SRV_SRC.replace(
            'METHODS = frozenset({"ping", "push"})',
            'METHODS = frozenset({"ping"})',
        )
        a = state(self._files(srv=srv))
        assert any(
            f.rule == "DF015" and "'push'" in f.message
            and "no registered server dispatch handler" in f.message
            for f in a.findings()
        )

    def test_methods_entry_without_handler_def_fires(self):
        srv = _SRV_SRC.replace(
            'METHODS = frozenset({"ping", "push"})',
            'METHODS = frozenset({"ping", "push", "vanish"})',
        )
        a = state(self._files(srv=srv))
        assert any(
            f.rule == "DF015" and "'vanish'" in f.message
            and "no handler def" in f.message
            for f in a.findings()
        )

    def test_unclassified_retried_method_fires(self):
        srv = _SRV_SRC.replace(
            'METHODS = frozenset({"ping", "push"})',
            'METHODS = frozenset({"ping", "push", "zap"})',
        ) + """

    def zap(self, req):
        return {}
"""
        g = _G_SRC.replace(
            '    "push": ("PushReq", "PushResp"),',
            '    "push": ("PushReq", "PushResp"),\n'
            '    "zap": ("ZapReq", "ZapResp"),',
        )
        cl = _CL_SRC + """

    def zap(self):
        return self._call("zap", {})
"""
        a = state(self._files(srv=srv, g=g, cl=cl))
        assert any(
            f.rule == "DF015" and "'zap'" in f.message
            and "neither declared idempotent nor deduped" in f.message
            for f in a.findings()
        )

    def test_missing_dedup_seam_fires(self):
        srv = _SRV_SRC.replace("def dedup_push():\n    pass\n", "")
        a = state(self._files(srv=srv))
        assert any(
            f.rule == "DF015" and "'dedup_push'" in f.message
            and "not found" in f.message
            for f in a.findings()
        )

    def test_stale_classification_fires(self):
        contract = _R_CONTRACT.replace(
            '"idempotent": ["ping"],', '"idempotent": ["ping", "ghost"],'
        )
        a = state(self._files(contract=contract))
        assert any(
            f.rule == "DF015" and "'ghost'" in f.message
            and "stale" in f.message
            for f in a.findings()
        )

    def test_pragma_suppresses(self):
        cl = _CL_SRC + """

    def zap(self):
        return self._call("zap", {})  # dflint: disable=DF015
"""
        a = state(self._files(cl=cl))
        assert a.findings() == []


class TestStateMutationSensitivity:
    """The acceptance contract against the REAL tree: an illegal
    ModelState edge, the ACTIVE-flip put_many split into puts, and a
    deleted gRPC handler for a live client method must each fail BY
    RULE NAME."""

    def _analyze_with(self, relpath: str, mutated: str) -> StateAnalysis:
        return StateAnalysis(_df_tree_program_with(relpath, mutated), REPO)

    @pytest.fixture(scope="class")
    def real_state(self):
        return StateAnalysis(_df_tree_program(), REPO)

    def test_real_tree_is_clean(self, real_state):
        assert real_state.findings() == [], [
            f.render() for f in real_state.findings()
        ]

    def test_illegal_model_state_edge_fails_df013(self):
        # A scheduler-side module flipping model state: the scheduler
        # may POLL the registry, never mutate it.
        relpath = "dragonfly2_tpu/scheduler/model_loader.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        mutated = source + (
            "\n\ndef _rogue_promote(registry, model_id):\n"
            "    from ..manager.registry import ModelState\n"
            "    registry.set_state(model_id, ModelState.ACTIVE)\n"
        )
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF013" and "'model_state'" in f.message
            and f.path == relpath
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_put_many_split_fails_df014_by_site_name(self):
        relpath = "dragonfly2_tpu/manager/registry.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = (
            "            self._table.put_many("
            "{m.id: _model_to_doc(m) for m in models})"
        )
        assert needle in source
        mutated = source.replace(
            needle,
            "            for m in models:\n"
            "                self._table.put(m.id, _model_to_doc(m))",
        )
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF014"
            and "multi-row site ModelRegistry._persist" in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_deleted_grpc_handler_fails_df015_by_method_name(self):
        relpath = "dragonfly2_tpu/rpc/grpc_transport.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = '    "leave_peer": (pb.PeerRequest, pb.Empty),\n'
        assert needle in source
        a = self._analyze_with(relpath, source.replace(needle, ""))
        assert any(
            f.rule == "DF015" and "'leave_peer'" in f.message
            and "gRPC transport table" in f.message
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_appended_peer_event_fails_df013_staleness(self):
        relpath = "dragonfly2_tpu/scheduler/resource.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = "PEER_EVENTS = (\n"
        assert needle in source
        mutated = source.replace(
            needle,
            "PEER_EVENTS = (\n"
            '    EventDesc("Hijack", (PEER_SUCCEEDED,), PEER_RUNNING),\n',
        )
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF013" and "'Hijack'" in f.message
            for f in a.findings()
        )

    def test_fsm_mirror_write_outside_callback_fails_df013(self):
        relpath = "dragonfly2_tpu/scheduler/resource.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        mutated = source + (
            "\n\ndef _rogue_mirror(peer):\n"
            "    peer.fsm_state = PEER_RUNNING\n"
        )
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF013" and "mirror 'fsm_state'" in f.message
            for f in a.findings()
        )

    def test_witness_catches_pruned_inventory(self, real_state):
        """A write the static inventory cannot explain is a gap (the
        dynamic cross-check in tests/test_zz_crashwitness.py leans on
        this exact function)."""
        gaps = crash_witness_gaps(real_state, {
            ("dragonfly2_tpu/daemon/nowhere.py", 3): [
                {"namespace": "models", "method": "put",
                 "writes": 1, "max_rows": 1},
            ],
        })
        assert len(gaps) == 1 and "unknown to the static" in gaps[0]


class TestFsmGraphStaleness:
    """DESIGN.md §19's committed machine block must match a fresh
    emission — the same discipline as the §16 lock graph."""

    def test_design_md_fsm_graph_is_current(self):
        from tools.dflint.__main__ import (
            FSM_GRAPH_BEGIN, FSM_GRAPH_END, render_fsm_graph,
        )

        analysis = StateAnalysis(_df_tree_program(), REPO)
        text = (REPO / "DESIGN.md").read_text(encoding="utf-8")
        begin = text.find(FSM_GRAPH_BEGIN)
        end = text.find(FSM_GRAPH_END)
        assert begin >= 0 and end > begin, "DESIGN.md §19 fsm-graph markers missing"
        committed = text[begin : end + len(FSM_GRAPH_END)]
        fresh = render_fsm_graph(analysis)
        assert committed == fresh, (
            "DESIGN.md §19 fsm graph is stale — regenerate with "
            "`python -m tools.dflint dragonfly2_tpu --update-fsm-graph DESIGN.md`"
        )

    def test_update_fsm_graph_rewrites_in_place(self, tmp_path):
        from tools.dflint.__main__ import main

        doc = tmp_path / "DESIGN.md"
        doc.write_text(
            "# doc\n\n<!-- dflint:fsm-graph:begin -->\nstale\n"
            "<!-- dflint:fsm-graph:end -->\ntail\n"
        )
        src = tmp_path / "empty.py"
        src.write_text("X = 1\n")
        assert main([str(src), "--update-fsm-graph", str(doc)]) == 0
        body = doc.read_text()
        assert "stale" not in body and "tail" in body

    def test_graph_renders_every_declared_machine(self):
        analysis = StateAnalysis(_df_tree_program(), REPO)
        md = analysis.fsm_graph_markdown()
        dot = analysis.fsm_graph_dot()
        for key in ("peer", "task", "model_state", "rollout_phase"):
            assert f"machine `{key}`" in md
            assert f"digraph {key} {{" in dot


class TestCLIStateRules:
    def test_rule_filter_covers_state_rules(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        src = tmp_path / "clean.py"
        src.write_text("X = 1\n")
        assert main([str(src), "--rule", "DF013,DF014,DF015", "-q"]) == 0

    def test_jobs_parallel_matches_serial(self, tmp_path):
        from tools.dflint.core import run_paths, run_paths_parallel

        for i in range(4):
            (tmp_path / f"f{i}.py").write_text(
                "def f():\n"
                "    try:\n"
                "        work()\n"
                "    except Exception:\n"
                "        pass\n"
            )
        serial = run_paths([tmp_path], tmp_path)
        parallel = run_paths_parallel([tmp_path], tmp_path, jobs=3)
        key = lambda f: (f.path, f.line, f.col, f.rule)  # noqa: E731
        assert sorted(serial.findings, key=key) == sorted(
            parallel.findings, key=key
        )
        assert len(serial.findings) == 4

    def test_profile_prints_phase_timings(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        src = tmp_path / "clean.py"
        src.write_text("X = 1\n")
        assert main([str(src), "--profile", "-q"]) == 0
        err = capsys.readouterr().err
        assert "profile: per-file rules" in err
        assert "profile: state rules DF013-DF015" in err

    def test_emit_fsm_graph_prints_markers(self, capsys):
        from tools.dflint.__main__ import main

        assert main(["dragonfly2_tpu", "--emit-fsm-graph"]) == 0
        out = capsys.readouterr().out
        assert "<!-- dflint:fsm-graph:begin -->" in out
        assert "digraph peer {" in out


# ---------------------------------------------------------------------------
# DF016 fixtures — span coverage (flight recorder, DESIGN.md §21) — plus
# mutation sensitivity against the REAL tree
# ---------------------------------------------------------------------------


class TestDF016Fixtures:
    def test_adapter_dispatch_without_remote_span_fires(self):
        fs = lint(
            """
            def do_POST(self, adapter, method, req):
                resp = adapter.dispatch(method, req)
                return resp
            """,
            relpath="dragonfly2_tpu/rpc/fixture_server.py",
        )
        assert "DF016" in rules_of(fs)

    def test_adapter_dispatch_under_remote_span_ok(self):
        fs = lint(
            """
            from ..utils.tracing import default_tracer

            def do_POST(self, adapter, method, req, headers):
                with default_tracer.remote_span(
                    f"rpc/{method}", headers.get("traceparent")
                ):
                    resp = adapter.dispatch(method, req)
                return resp
            """,
            relpath="dragonfly2_tpu/rpc/fixture_server.py",
        )
        assert "DF016" not in rules_of(fs)

    def test_non_adapter_dispatch_not_flagged(self):
        # Dict/event dispatchers are not RPC server entries.
        fs = lint(
            """
            def route(self, table, method, req):
                return table.dispatch(method, req)
            """,
            relpath="dragonfly2_tpu/rpc/fixture_server.py",
        )
        assert "DF016" not in rules_of(fs)

    def test_inventory_missing_site_fires_by_name(self):
        fs = lint(
            """
            def quiet():
                return 1
            """,
            relpath="dragonfly2_tpu/scheduler/microbatch.py",
        )
        assert any(
            f.rule == "DF016" and "scheduler/eval.flush" in f.message
            for f in fs
        )

    def test_inventory_fstring_prefix_matches(self):
        fs = lint(
            """
            from ..utils.tracing import default_tracer

            def handle(self, adapter, method, req, tp):
                with default_tracer.remote_span(f"rpc/{method}", tp):
                    return adapter.dispatch(method, req)
            """,
            relpath="dragonfly2_tpu/rpc/scheduler_server.py",
        )
        assert "DF016" not in rules_of(fs)

    def test_pragma_suppresses(self):
        fs = lint(
            """
            def do_POST(self, adapter, method, req):
                return adapter.dispatch(method, req)  # dflint: disable=DF016
            """,
            relpath="dragonfly2_tpu/rpc/fixture_server.py",
        )
        assert "DF016" not in rules_of(fs)

    def test_dict_span_lookalike_not_coverage(self):
        # A non-tracer receiver's .span() must not satisfy the inventory.
        fs = lint(
            """
            def quiet(layout):
                layout.span("scheduler/eval.flush")
            """,
            relpath="dragonfly2_tpu/scheduler/microbatch.py",
        )
        assert any(f.rule == "DF016" for f in fs)

    def test_real_span_modules_satisfy_inventory(self):
        from tools.dflint.checkers.df016_spans import REQUIRED_SPANS, check
        from tools.dflint.core import load_module

        for rel in REQUIRED_SPANS:
            module = load_module(REPO / rel, REPO)
            findings = [f for f in check(module) if f.rule == "DF016"]
            assert findings == [], f"{rel}: {[f.message for f in findings]}"

    def test_inventory_not_stale(self):
        from tools.dflint.checkers.df016_spans import stale_inventory_entries

        assert stale_inventory_entries(REPO) == []


class TestDF016MutationSensitivity:
    def _lint_source(self, relpath: str, source: str):
        module = Module(REPO / relpath, relpath, source)
        return run_checkers(module)

    def test_deleting_http_remote_span_fails_df016(self):
        # The acceptance mutation: strip the HTTP transport's handler
        # span — BOTH sub-rules must fire (inventory: rpc/* gone;
        # adjacency: adapter.dispatch with no remote_span in scope).
        relpath = "dragonfly2_tpu/rpc/scheduler_server.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert "remote_span" in source
        mutated = source.replace(
            "                    with default_tracer.remote_span(\n"
            '                        f"rpc/{method}",\n'
            "                        self.headers.get(TRACEPARENT_HEADER),\n"
            '                        transport="http",\n'
            "                    ):\n"
            "                        resp = adapter.dispatch(method, req)",
            "                    resp = adapter.dispatch(method, req)",
        )
        assert mutated != source, "mutation target drifted"
        fs = [f for f in self._lint_source(relpath, mutated) if f.rule == "DF016"]
        assert any("rpc/*" in f.message for f in fs)
        assert any("remote_span in the same function" in f.message or
                   "without a remote_span" in f.message for f in fs)

    def test_deleting_piece_span_fails_df016(self):
        relpath = "dragonfly2_tpu/daemon/conductor.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert '"daemon/piece"' in source
        mutated = source.replace('"daemon/piece"', '"daemon/renamed"')
        fs = [f for f in self._lint_source(relpath, mutated) if f.rule == "DF016"]
        assert any("daemon/piece" in f.message for f in fs)

    def test_deleting_flush_span_fails_df016(self):
        relpath = "dragonfly2_tpu/scheduler/microbatch.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert '"scheduler/eval.flush"' in source
        mutated = source.replace('"scheduler/eval.flush"', '"renamed"')
        fs = [f for f in self._lint_source(relpath, mutated) if f.rule == "DF016"]
        assert any("scheduler/eval.flush" in f.message for f in fs)

    def test_cli_rule_filter_selects_df016(self, capsys):
        from tools.dflint.__main__ import main

        rc = main(["dragonfly2_tpu", "--rule", "DF016", "-q"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "0 new finding(s)" in out


# ---------------------------------------------------------------------------
# DF017 fixtures — metric hygiene (fleet telemetry plane, DESIGN.md §23) —
# plus mutation sensitivity against the REAL tree
# ---------------------------------------------------------------------------


class TestDF017Fixtures:
    def test_registration_inside_function_fires(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            def handler():
                c = _reg.counter("daemon_requests_total", "per-call!")
                c.inc()
            """,
        )
        assert any(
            f.rule == "DF017" and "inside a function" in f.message for f in fs
        )

    def test_module_scope_registration_ok(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            REQS = _reg.counter("daemon_requests_total", "requests", ["result"])
            LAT = _reg.sketch("daemon_request_seconds", "latency")
            DEPTH = _reg.gauge("daemon_queue_size", "depth")
            """,
        )
        assert "DF017" not in rules_of(fs)

    def test_direct_constructor_checked_too(self):
        fs = lint(
            """
            from ..utils.metrics import Counter

            def f():
                return Counter("daemon_x_total", "per-call")
            """,
        )
        assert any(f.rule == "DF017" for f in fs)

    def test_duplicate_registration_fires(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            A = _reg.counter("daemon_dup_total", "a")
            B = _reg.counter("daemon_dup_total", "b")
            """,
        )
        assert any(
            f.rule == "DF017" and "twice" in f.message for f in fs
        )

    def test_unbounded_label_fires(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            C = _reg.counter(
                "daemon_fetch_total", "fetches", ["result", "peer_id"]
            )
            """,
        )
        assert any(
            f.rule == "DF017" and "peer_id" in f.message for f in fs
        )

    def test_bounded_labels_ok(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            C = _reg.counter(
                "daemon_fetch_total", "fetches", ["result", "algorithm"]
            )
            """,
        )
        assert "DF017" not in rules_of(fs)

    def test_raw_tenant_id_label_fires_by_name(self):
        """ISSUE 15 satellite: a raw tenant id is one series per tenant
        on a million-user fleet — the fixture proves the ban fires BY
        NAME, and that the bounded tenant_class label passes."""
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            C = _reg.counter(
                "scheduler_qos_served_total", "per tenant!", ["tenant_id"]
            )
            """,
        )
        assert any(
            f.rule == "DF017" and "tenant_id" in f.message for f in fs
        )
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            C = _reg.counter(
                "scheduler_qos_served_total", "by class", ["tenant_class"]
            )
            """,
        )
        assert "DF017" not in rules_of(fs)
        # The bare spelling is banned too.
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            C = _reg.counter(
                "scheduler_qos_served_total", "per tenant!", ["tenant"]
            )
            """,
        )
        assert any(
            f.rule == "DF017" and "'tenant'" in f.message for f in fs
        )

    def test_naming_counter_without_total_fires(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            C = _reg.counter("daemon_fetches", "count")
            """,
        )
        assert any(
            f.rule == "DF017" and "_total" in f.message for f in fs
        )

    def test_naming_unknown_subsystem_fires(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            C = _reg.counter("frobnicator_ops_total", "count")
            """,
        )
        assert any(
            f.rule == "DF017" and "subsystem" in f.message for f in fs
        )

    def test_naming_sketch_needs_unit_suffix(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            S = _reg.sketch("daemon_fetch_latency", "no unit")
            """,
        )
        assert any(
            f.rule == "DF017" and "unit suffix" in f.message for f in fs
        )

    def test_gauge_exempt_from_unit_but_not_prefix(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            G = _reg.gauge("manager_role", "role flag")
            """,
        )
        assert "DF017" not in rules_of(fs)
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            G = _reg.gauge("role", "one token only")
            """,
        )
        assert any(f.rule == "DF017" for f in fs)

    def test_dynamic_name_not_checked(self):
        # Non-literal names (drill/test helpers) are out of scope.
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            def drill(name):
                return _reg.sketch(name, "drill metric")
            """,
        )
        assert "DF017" not in rules_of(fs)

    def test_non_registry_receiver_not_checked(self):
        fs = lint(
            """
            def f(store):
                return store.counter("not_a_metric", "kv api lookalike")
            """,
        )
        assert "DF017" not in rules_of(fs)

    def test_inventory_missing_metric_fires_by_name(self):
        fs = lint(
            """
            def quiet():
                return 1
            """,
            relpath="dragonfly2_tpu/utils/slo.py",
        )
        assert any(
            f.rule == "DF017" and "slo_burn_rate" in f.message for f in fs
        )

    def test_pragma_suppresses(self):
        fs = lint(
            """
            from ..utils.metrics import default_registry as _reg

            def f():
                return _reg.counter("daemon_x_total", "ok")  # dflint: disable=DF017
            """,
        )
        assert "DF017" not in rules_of(fs)

    def test_real_metric_modules_satisfy_inventory(self):
        from tools.dflint.checkers.df017_metrics import REQUIRED_METRICS, check
        from tools.dflint.core import load_module

        for rel in REQUIRED_METRICS:
            module = load_module(REPO / rel, REPO)
            findings = [f for f in check(module) if f.rule == "DF017"]
            assert findings == [], f"{rel}: {[f.message for f in findings]}"

    def test_inventory_not_stale(self):
        from tools.dflint.checkers.df017_metrics import stale_inventory_entries

        assert stale_inventory_entries(REPO) == []


class TestDF017MutationSensitivity:
    def _lint_source(self, relpath: str, source: str):
        module = Module(REPO / relpath, relpath, source)
        return run_checkers(module)

    def test_deleting_piece_fetch_sketch_fails_df017(self):
        # The acceptance mutation: delete the inventoried hot-path
        # sketch — tier-1 fails BY NAME.
        relpath = "dragonfly2_tpu/daemon/piece_pipeline.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert '"daemon_piece_fetch_seconds"' in source
        mutated = source.replace(
            '"daemon_piece_fetch_seconds"', '"daemon_piece_renamed_seconds"'
        )
        fs = [
            f for f in self._lint_source(relpath, mutated)
            if f.rule == "DF017"
        ]
        assert any("daemon_piece_fetch_seconds" in f.message for f in fs)

    def test_deleting_qos_shed_counter_fails_df017(self):
        """ISSUE 15: the QoS metrics are inventoried — deleting the
        tenant shed counter fails tier-1 by name."""
        relpath = "dragonfly2_tpu/qos/metrics.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert '"scheduler_qos_shed_total"' in source
        mutated = source.replace(
            '"scheduler_qos_shed_total"', '"scheduler_qos_gone_total"'
        )
        fs = [
            f for f in self._lint_source(relpath, mutated)
            if f.rule == "DF017"
        ]
        assert any("scheduler_qos_shed_total" in f.message for f in fs)

    def test_deleting_slo_gauge_fails_df017(self):
        relpath = "dragonfly2_tpu/utils/slo.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert '"slo_breached"' in source
        mutated = source.replace('"slo_breached"', '"slo_gone"')
        fs = [
            f for f in self._lint_source(relpath, mutated)
            if f.rule == "DF017"
        ]
        assert any("slo_breached" in f.message for f in fs)

    def test_cli_rule_filter_selects_df017(self, capsys):
        from tools.dflint.__main__ import main

        rc = main(["dragonfly2_tpu", "--rule", "DF017", "-q"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "0 new finding(s)" in out



# ---------------------------------------------------------------------------
# Replay-determinism analysis (tools/dflint/detrules.py): DF018 / DF019
# fixtures, plus the DESIGN.md §27 inventory staleness gate
# ---------------------------------------------------------------------------

from tools.dflint.detrules import DetAnalysis, det_witness_gaps  # noqa: E402

# A minimal contracts registry for fixture trees: one replay root with a
# declared `now` clock seam, a whole-module observability sink, and one
# artifact writer with a bounded two-key payload.  Fixture sources below
# are zero-indented strings (concatenation-friendly; textwrap.dedent in
# `prog` is a no-op on them).
DET_CONTRACTS_FIXTURE = '''
DETERMINISM_CONTRACTS = {
    "replay_roots": {
        "eng.run": {
            "file": "dragonfly2_tpu/utils/eng.py",
            "qual": "Engine.run",
        },
    },
    "injection_seams": [
        {
            "file": "dragonfly2_tpu/utils/eng.py",
            "qual": "Engine.run",
            "params": ["now"],
            "kind": "clock",
        },
    ],
    "sinks": [
        "dragonfly2_tpu/utils/obs.py:*",
    ],
    "serialization": {
        "eng.frame": {
            "file": "dragonfly2_tpu/utils/eng.py",
            "qual": "write_frame",
            "format": "J1",
            "builder": "build_payload",
            "keys": ["a", "b"],
        },
    },
}
'''

DET_CONTRACTS_RELPATH = "dragonfly2_tpu/records/determinism_contracts.py"


DET_SINK_FIXTURE = '''
import time

def record(event):
    return (event, time.time())
'''


def det(files: dict) -> DetAnalysis:
    tree = dict(files)
    tree.setdefault(DET_CONTRACTS_RELPATH, DET_CONTRACTS_FIXTURE)
    # The declared sink module must resolve or every tree would carry a
    # staleness finding.
    tree.setdefault("dragonfly2_tpu/utils/obs.py", DET_SINK_FIXTURE)
    return DetAnalysis(prog(tree))


def det_rules(a: DetAnalysis):
    return sorted({f.rule for f in a.findings()})


CLEAN_WRITER = '''
import json

def build_payload(state):
    return {"a": state[0], "b": state[1]}

def write_frame(state):
    return json.dumps(build_payload(state), sort_keys=True).encode()
'''


class TestDF018Fixtures:
    def test_wall_clock_in_root_fires(self):
        a = det({"dragonfly2_tpu/utils/eng.py": "import time\n" + CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        return now - time.time()
'''})
        fs = a.findings()
        assert det_rules(a) == ["DF018"]
        assert "time.time" in fs[0].message
        assert "eng.run" in fs[0].message

    def test_clock_through_declared_seam_is_clean(self):
        a = det({"dragonfly2_tpu/utils/eng.py": "import time\n" + CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        return now * 2.0

def live_edge(eng):
    # Ambient sampling OUTSIDE the closure, value through the
    # declared seam: the blessed pattern.
    return eng.run(time.time())
'''})
        assert a.findings() == []

    def test_transitive_taint_fires_with_chain(self):
        a = det({"dragonfly2_tpu/utils/eng.py": "import time\n" + CLEAN_WRITER + '''
def _stamp():
    return time.time()

class Engine:
    def run(self, now):
        return _stamp() - now
'''})
        fs = a.findings()
        assert det_rules(a) == ["DF018"]
        assert "->" in fs[0].message and "_stamp" in fs[0].message

    def test_declared_sink_stops_taint(self):
        a = det({
            "dragonfly2_tpu/utils/eng.py":
                "from .obs import record\n" + CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        record("run")
        return now * 2.0
''',
            "dragonfly2_tpu/utils/obs.py": '''
import time

def record(event):
    return (event, time.time())
''',
        })
        assert a.findings() == []

    def test_unseeded_rng_factory_fires_seeded_is_clean(self):
        dirty = det({"dragonfly2_tpu/utils/eng.py": "import numpy as np\n" + CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        rng = np.random.default_rng()
        return rng.random() + now
'''})
        assert "DF018" in det_rules(dirty)
        assert any("default_rng" in f.message for f in dirty.findings())
        clean = det({"dragonfly2_tpu/utils/eng.py": "import numpy as np\n" + CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        rng = np.random.default_rng(7)
        return rng.random() + now
'''})
        assert clean.findings() == []

    def test_ambient_module_rng_fires(self):
        a = det({"dragonfly2_tpu/utils/eng.py": "import random\n" + CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        return random.random() + now
'''})
        assert det_rules(a) == ["DF018"]
        assert "ambient global RNG" in a.findings()[0].message

    def test_hash_builtin_fires(self):
        a = det({"dragonfly2_tpu/utils/eng.py": CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        return hash(str(now)) & 0xFF
'''})
        assert det_rules(a) == ["DF018"]
        assert "PYTHONHASHSEED" in a.findings()[0].message

    def test_set_iteration_fires_sorted_is_clean(self):
        dirty = det({"dragonfly2_tpu/utils/eng.py": CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        return [k for k in {"b", "a"}]
'''})
        assert det_rules(dirty) == ["DF018"]
        assert "set iteration" in dirty.findings()[0].message
        clean = det({"dragonfly2_tpu/utils/eng.py": CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        return [k for k in sorted({"b", "a"})]
'''})
        assert clean.findings() == []

    def test_pragma_suppresses_but_site_stays_indexed(self):
        a = det({"dragonfly2_tpu/utils/eng.py": "import time\n" + CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        return time.time() - now  # dflint: disable=DF018
'''})
        assert a.findings() == []
        # The witness maps observations against *knowledge*: the
        # reviewed site still appears in the ambient index.
        assert any(
            "time.time" in sources
            for sources in a.ambient_site_index().values()
        )

    def test_stale_root_fails_by_name(self):
        a = det({"dragonfly2_tpu/utils/eng.py": CLEAN_WRITER + '''
class Renamed:
    def run(self, now):
        return now
'''})
        fs = [f for f in a.findings() if f.rule == "DF018"]
        assert any(
            "eng.run" in f.message and "does not resolve" in f.message
            for f in fs
        )

    def test_stale_seam_param_fails(self):
        a = det({"dragonfly2_tpu/utils/eng.py": CLEAN_WRITER + '''
class Engine:
    def run(self, clock):
        return clock
'''})
        fs = [f for f in a.findings() if f.rule == "DF018"]
        assert any(
            "no parameter" in f.message and "'now'" in f.message
            for f in fs
        )


class TestDF019Fixtures:
    def test_unsorted_dumps_in_writer_fires(self):
        a = det({"dragonfly2_tpu/utils/eng.py": '''
import json

def build_payload(state):
    return {"a": state[0], "b": state[1]}

def write_frame(state):
    return json.dumps(build_payload(state)).encode()

class Engine:
    def run(self, now):
        return now
'''})
        assert det_rules(a) == ["DF019"]
        assert "sort_keys=True" in a.findings()[0].message

    def test_canonical_writer_is_clean(self):
        a = det({"dragonfly2_tpu/utils/eng.py": CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        return now
'''})
        assert a.findings() == []

    def test_payload_key_drift_fails_both_directions(self):
        extra = det({"dragonfly2_tpu/utils/eng.py": '''
import json

def build_payload(state):
    return {"a": state[0], "b": state[1], "c": 3}

def write_frame(state):
    return json.dumps(build_payload(state), sort_keys=True).encode()

class Engine:
    def run(self, now):
        return now
'''})
        assert any(
            "'c'" in f.message and "declared bounded key set" in f.message
            for f in extra.findings()
        )
        missing = det({"dragonfly2_tpu/utils/eng.py": '''
import json

def build_payload(state):
    return {"a": state[0]}

def write_frame(state):
    return json.dumps(build_payload(state), sort_keys=True).encode()

class Engine:
    def run(self, now):
        return now
'''})
        assert any(
            "'b'" in f.message and "no longer builds" in f.message
            for f in missing.findings()
        )

    def test_dumps_in_taint_closure_must_sort(self):
        a = det({"dragonfly2_tpu/utils/eng.py": CLEAN_WRITER + '''
def _render(state):
    return json.dumps({"x": state})

class Engine:
    def run(self, now):
        return _render(now)
'''})
        fs = [f for f in a.findings() if f.rule == "DF019"]
        assert any("replay path" in f.message for f in fs)

    def test_stale_writer_fails_by_name(self):
        a = det({"dragonfly2_tpu/utils/eng.py": '''
class Engine:
    def run(self, now):
        return now
'''})
        fs = [f for f in a.findings() if f.rule == "DF019"]
        assert any(
            "eng.frame" in f.message and "does not resolve" in f.message
            for f in fs
        )


class TestDetWitnessGapsFixtures:
    def _analysis(self):
        return det({"dragonfly2_tpu/utils/eng.py": "import time\n" + CLEAN_WRITER + '''
class Engine:
    def run(self, now):
        return time.time() - now  # dflint: disable=DF018
'''})

    def test_known_site_is_excused(self):
        a = self._analysis()
        (site,) = list(a.ambient_site_index())
        observed = [
            {"relpath": site[0], "lineno": site[1],
             "source": "time.time", "root": "eng.run"},
        ]
        assert det_witness_gaps(a, observed) == []

    def test_sink_module_is_excused(self):
        a = self._analysis()
        observed = [
            {"relpath": "dragonfly2_tpu/utils/obs.py", "lineno": 42,
             "source": "time.time", "root": "eng.run"},
        ]
        assert det_witness_gaps(a, observed) == []

    def test_unknown_site_is_a_gap(self):
        a = self._analysis()
        observed = [
            {"relpath": "dragonfly2_tpu/utils/eng.py", "lineno": 9999,
             "source": "time.time", "root": "eng.run"},
        ]
        gaps = det_witness_gaps(a, observed)
        assert len(gaps) == 1 and "resolver missed" in gaps[0]

    def test_undeclared_root_is_a_stale_contract_gap(self):
        a = self._analysis()
        observed = [
            {"relpath": "dragonfly2_tpu/utils/eng.py", "lineno": 1,
             "source": "time.time", "root": "ghost.root"},
        ]
        gaps = det_witness_gaps(a, observed)
        assert len(gaps) == 1 and "stale contract" in gaps[0]


_REAL_DET_MODULES = None
_REAL_DET_ANALYSIS = None


def _real_tree_modules():
    """Parsed Modules for the full tree, loaded ONCE per session — the
    det batteries below build several whole-program views and the parse
    dominates; Program never mutates the Modules so they are shareable."""
    global _REAL_DET_MODULES
    if _REAL_DET_MODULES is None:
        from tools.dflint.core import collect_files, load_module

        _REAL_DET_MODULES = [
            load_module(p, REPO)
            for p in collect_files(
                [REPO / "dragonfly2_tpu", REPO / "tools"], REPO
            )
        ]
    return _REAL_DET_MODULES


def _real_det_analysis():
    global _REAL_DET_ANALYSIS
    if _REAL_DET_ANALYSIS is None:
        _REAL_DET_ANALYSIS = DetAnalysis(
            Program(list(_real_tree_modules())), REPO
        )
    return _REAL_DET_ANALYSIS


class TestDetInventoryStaleness:
    """DESIGN.md §27's committed det-inventory block must match a fresh
    emission — same discipline as the §16 lock graph and baseline.toml."""

    def test_design_md_det_inventory_is_current(self):
        from tools.dflint.__main__ import (
            DET_INVENTORY_BEGIN, DET_INVENTORY_END, render_det_inventory,
        )

        analysis = _real_det_analysis()
        text = (REPO / "DESIGN.md").read_text(encoding="utf-8")
        begin = text.find(DET_INVENTORY_BEGIN)
        end = text.find(DET_INVENTORY_END)
        assert begin >= 0 and end > begin, (
            "DESIGN.md §27 det-inventory markers missing"
        )
        committed = text[begin : end + len(DET_INVENTORY_END)]
        fresh = render_det_inventory(analysis)
        assert committed == fresh, (
            "DESIGN.md §27 det inventory is stale — regenerate with "
            "`python -m tools.dflint --update-det-inventory DESIGN.md "
            "dragonfly2_tpu tools`"
        )

    def test_update_det_inventory_rewrites_in_place(self, tmp_path):
        from tools.dflint.__main__ import main

        doc = tmp_path / "DESIGN.md"
        doc.write_text(
            "# doc\n\n<!-- dflint:det-inventory:begin -->\nstale\n"
            "<!-- dflint:det-inventory:end -->\ntail\n"
        )
        src = tmp_path / "eng.py"
        src.write_text("def run(now):\n    return now\n")
        assert main([str(src), "--update-det-inventory", str(doc)]) == 0
        body = doc.read_text()
        assert "stale" not in body and "replay root" in body and "tail" in body


class TestDetMutationSensitivity:
    """The acceptance contract against the REAL tree: a wall-clock read
    inserted into a declared replay root and a dropped ``sort_keys`` in
    a declared artifact writer must each fail BY RULE NAME (the same
    mutations the runtime witness catches in tests/test_zz_detwitness.py)."""

    def _analyze_with(self, relpath: str, mutated: str) -> DetAnalysis:
        modules = [
            Module(m.path, relpath, mutated) if m.relpath == relpath else m
            for m in _real_tree_modules()
        ]
        return DetAnalysis(Program(modules), REPO)

    @pytest.fixture(scope="class")
    def real_det(self):
        return _real_det_analysis()

    def test_real_tree_is_clean(self, real_det):
        assert real_det.findings() == [], [
            f.render() for f in real_det.findings()
        ]

    def test_wall_clock_in_slo_evaluate_fails_df018(self):
        relpath = "dragonfly2_tpu/utils/slo.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = "        else:\n            t = now"
        assert needle in source, "SLOEngine.evaluate clock seam drifted"
        mutated = source.replace(needle, needle + "\n        t = time.time()")
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF018" and "time.time" in f.message
            and f.path == relpath
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_sort_keys_drop_in_journal_writer_fails_df019(self):
        relpath = "dragonfly2_tpu/utils/metric_journal.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = "payload = json.dumps(snapshot, sort_keys=True).encode()"
        assert needle in source, "encode_frame writer drifted"
        mutated = source.replace(
            needle, "payload = json.dumps(snapshot).encode()"
        )
        a = self._analyze_with(relpath, mutated)
        assert any(
            f.rule == "DF019" and "sort_keys" in f.message
            and f.path == relpath
            for f in a.findings()
        ), [f.render() for f in a.findings()]

    def test_cli_rule_filter_selects_df018_df019(self, capsys):
        from tools.dflint.__main__ import main

        rc = main(["dragonfly2_tpu", "tools", "--rule", "DF018,DF019", "-q"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "0 new finding(s)" in out


# ---------------------------------------------------------------------------
# DF020 / DF021 — native ABI contract parity + exception containment
# (tools/dflint/checkers/df020_abi.py / df021_nativeexc.py, DESIGN.md §30)
# ---------------------------------------------------------------------------

import ast  # noqa: E402

from tools.dflint.checkers import df020_abi, df021_nativeexc  # noqa: E402


_ABI_FX_CPP = """\
constexpr int32_t kFoo = 3 + 4;
constexpr int64_t kBig = 512 * 1024;
constexpr char kTag[] = "ABCD";

#pragma pack(push, 1)
struct Rec {
  uint32_t a;
  int64_t b;
};
#pragma pack(pop)

static std::map<int64_t, RecPtr> g_recs;
static std::map<int64_t, Widget*> g_widgets;

extern "C" {

int32_t do_thing(int64_t handle, const uint8_t* buf, uint32_t len) try {
  return 0;
} catch (...) {
  return kAbiTrap;
}

}  // extern "C"
"""

_ABI_FX_CONTRACTS = {
    "exports": {"do_thing": ["i32", "i64", "u8p", "u32"]},
    "records": {
        "Rec": {"fields": [["a", "u32"], ["b", "i64"]], "size": 12},
    },
    "constants": {"kFoo": 7, "kBig": 524288, "kTag": "ABCD"},
    "handle_families": {
        "rec_": {"registry": "g_recs", "lifetime": "shared_ptr"},
        "widget_": {"registry": "g_widgets", "lifetime": "raw"},
    },
}

_ABI_FX_BINDINGS = """\
import ctypes

i32 = ctypes.c_int32
i64 = ctypes.c_int64
u32 = ctypes.c_uint32
p8 = ctypes.POINTER(ctypes.c_uint8)


def _declare(lib):
    lib.do_thing.restype = i32
    lib.do_thing.argtypes = [i64, p8, u32]
"""


def _abi_fixture_findings(cpp_src=None, contracts=None, bindings_src=None):
    cpp = df020_abi.extract_cpp(cpp_src if cpp_src is not None else _ABI_FX_CPP)
    py = df020_abi.extract_bindings(
        ast.parse(bindings_src if bindings_src is not None else _ABI_FX_BINDINGS)
    )
    got = df020_abi.compare_all(
        contracts if contracts is not None else _ABI_FX_CONTRACTS, cpp, py
    )
    return [msg for _node, msg in got]


class TestDF020Fixtures:
    def test_consistent_fixture_is_clean(self):
        assert _abi_fixture_findings() == []

    def test_widened_c_param_named(self):
        msgs = _abi_fixture_findings(
            cpp_src=_ABI_FX_CPP.replace("uint32_t len", "uint64_t len")
        )
        assert any("do_thing" in m and "C parameters" in m for m in msgs)

    def test_c_return_drift_named(self):
        msgs = _abi_fixture_findings(
            cpp_src=_ABI_FX_CPP.replace("int32_t do_thing", "int64_t do_thing")
        )
        assert any("do_thing" in m and "return type" in m for m in msgs)

    def test_record_field_swap_named(self):
        msgs = _abi_fixture_findings(
            cpp_src=_ABI_FX_CPP.replace(
                "  uint32_t a;\n  int64_t b;", "  int64_t b;\n  uint32_t a;"
            )
        )
        assert any("record Rec" in m and "layout" in m for m in msgs)

    def test_record_size_mismatch_named(self):
        bad = {**_ABI_FX_CONTRACTS,
               "records": {"Rec": {"fields": [["a", "u32"], ["b", "i64"]],
                                   "size": 16}}}
        msgs = _abi_fixture_findings(contracts=bad)
        assert any("record Rec" in m and "size 16" in m for m in msgs)

    def test_int_constant_drift_named(self):
        bad = {**_ABI_FX_CONTRACTS,
               "constants": {**_ABI_FX_CONTRACTS["constants"], "kBig": 262144}}
        msgs = _abi_fixture_findings(contracts=bad)
        assert any("kBig" in m and "262144" in m for m in msgs)

    def test_string_constant_drift_named(self):
        msgs = _abi_fixture_findings(
            cpp_src=_ABI_FX_CPP.replace('kTag[] = "ABCD"', 'kTag[] = "ABCE"')
        )
        assert any("kTag" in m for m in msgs)

    def test_undeclared_constant_named(self):
        msgs = _abi_fixture_findings(
            cpp_src=_ABI_FX_CPP + "\nconstexpr int32_t kGhost = 9;\n"
        )
        assert any("kGhost" in m and "undeclared shared constant" in m
                   for m in msgs)

    def test_stale_registry_export_named(self):
        bad = {**_ABI_FX_CONTRACTS,
               "exports": {**_ABI_FX_CONTRACTS["exports"],
                           "ghost_fn": ["i32", "i64"]}}
        msgs = _abi_fixture_findings(contracts=bad)
        assert any("stale registry export: ghost_fn" in m for m in msgs)

    def test_exported_but_undeclared_named(self):
        extra = _ABI_FX_CPP.replace(
            "}  // extern \"C\"",
            "int32_t rogue_fn(int64_t h) try { return 0; } "
            "catch (...) { return kAbiTrap; }\n\n}  // extern \"C\"",
        )
        msgs = _abi_fixture_findings(cpp_src=extra)
        assert any("exported-but-undeclared: rogue_fn" in m for m in msgs)

    def test_exported_but_unbound_named(self):
        stripped = _ABI_FX_BINDINGS.replace(
            "    lib.do_thing.restype = i32\n"
            "    lib.do_thing.argtypes = [i64, p8, u32]\n",
            "    pass\n",
        )
        msgs = _abi_fixture_findings(bindings_src=stripped)
        assert any("exported-but-unbound: do_thing" in m for m in msgs)

    def test_bound_but_undeclared_named(self):
        extra = _ABI_FX_BINDINGS + (
            "    lib.mystery_fn.restype = i32\n"
            "    lib.mystery_fn.argtypes = [i64]\n"
        )
        msgs = _abi_fixture_findings(bindings_src=extra)
        assert any("bound-but-undeclared" in m and "mystery_fn" in m
                   for m in msgs)

    def test_ctypes_argtype_drift_named(self):
        drift = _ABI_FX_BINDINGS.replace("[i64, p8, u32]", "[i64, p8, i64]")
        msgs = _abi_fixture_findings(bindings_src=drift)
        assert any("do_thing" in m and "ctypes argtypes" in m for m in msgs)

    def test_handle_lifetime_mismatch_named(self):
        bad = {**_ABI_FX_CONTRACTS,
               "handle_families": {"widget_": {"registry": "g_widgets",
                                               "lifetime": "shared_ptr"}}}
        msgs = _abi_fixture_findings(contracts=bad)
        assert any("handle family widget_" in m for m in msgs)

    def test_missing_handle_registry_named(self):
        bad = {**_ABI_FX_CONTRACTS,
               "handle_families": {"gone_": {"registry": "g_gone",
                                             "lifetime": "raw"}}}
        msgs = _abi_fixture_findings(contracts=bad)
        assert any("handle family gone_" in m and "g_gone" in m for m in msgs)


class TestDF021Fixtures:
    def _msgs(self, cpp_src):
        return list(
            df021_nativeexc.findings_for_cpp(df020_abi.extract_cpp(cpp_src))
        )

    def test_function_try_block_is_clean(self):
        assert self._msgs(_ABI_FX_CPP) == []

    def test_depth1_try_catch_all_is_clean(self):
        src = _ABI_FX_CPP.replace(
            ") try {\n  return 0;\n} catch (...) {\n  return kAbiTrap;\n}",
            ") {\n  try {\n    return 0;\n  } catch (...) {\n"
            "    return kAbiTrap;\n  }\n}",
        )
        assert src != _ABI_FX_CPP
        assert self._msgs(src) == []

    def test_uncontained_export_named(self):
        src = _ABI_FX_CPP.replace(
            ") try {\n  return 0;\n} catch (...) {\n  return kAbiTrap;\n}",
            ") {\n  return 0;\n}",
        )
        assert src != _ABI_FX_CPP
        msgs = self._msgs(src)
        assert any("do_thing" in m and "no catch-all" in m for m in msgs)

    def test_typed_catch_only_is_not_containment(self):
        src = _ABI_FX_CPP.replace(
            "} catch (...) {\n  return kAbiTrap;\n}",
            "} catch (const std::exception&) {\n  return kAbiTrap;\n}",
        )
        assert src != _ABI_FX_CPP
        msgs = self._msgs(src)
        assert any("do_thing" in m for m in msgs)

    def test_pragma_suppresses(self):
        src = _ABI_FX_CPP.replace(
            ") try {\n  return 0;\n} catch (...) {\n  return kAbiTrap;\n}",
            ") {  // dflint: disable=DF021\n  return 0;\n}",
        )
        assert src != _ABI_FX_CPP
        assert self._msgs(src) == []

    def test_uncontained_thread_entry_named(self):
        src = _ABI_FX_CPP + (
            "\nstatic void worker(int64_t h) {\n  spin(h);\n}\n"
            "static void start() {\n  std::thread(worker, 1).detach();\n}\n"
        )
        msgs = self._msgs(src)
        assert any("thread entry worker" in m and "std::terminate" in m
                   for m in msgs)

    def test_contained_thread_entry_is_clean(self):
        src = _ABI_FX_CPP + (
            "\nstatic void worker(int64_t h) {\n  try {\n    spin(h);\n"
            "  } catch (...) {\n  }\n}\n"
            "static void start() {\n  std::thread(worker, 1).detach();\n}\n"
        )
        assert self._msgs(src) == []


def _abi_real_inputs():
    cpp_text = (REPO / df020_abi.NATIVE_RELPATH).read_text(encoding="utf-8")
    contracts_text = (REPO / df020_abi.CONTRACTS_RELPATH).read_text(
        encoding="utf-8"
    )
    bindings_text = (REPO / df020_abi.BINDINGS_RELPATH).read_text(
        encoding="utf-8"
    )
    return cpp_text, contracts_text, bindings_text


def _abi_real_findings(cpp_text, contracts_text, bindings_text):
    contracts = df020_abi.load_contracts_text(contracts_text)
    assert contracts is not None, "ABI_CONTRACTS must stay a pure literal"
    cpp = df020_abi.extract_cpp(cpp_text)
    tree = ast.parse(bindings_text)

    def read_tree(relpath):
        p = REPO / relpath
        if not p.exists():
            return None
        return ast.parse(p.read_text(encoding="utf-8"))

    msgs = [
        m
        for _n, m in df020_abi.compare_all(
            contracts, cpp, df020_abi.extract_bindings(tree),
            tree=tree, read_tree=read_tree,
        )
    ]
    msgs += list(df021_nativeexc.findings_for_cpp(cpp))
    return msgs


class TestAbiMutationSensitivity:
    """ISSUE acceptance: the four canonical ABI drifts against the REAL
    tree each fail by rule/symbol name, and the pristine tree is clean
    (the checkers run on disk state, so mutations are applied to in-
    memory copies of the real sources)."""

    def test_real_tree_is_clean(self):
        cpp_text, contracts_text, bindings_text = _abi_real_inputs()
        assert _abi_real_findings(cpp_text, contracts_text, bindings_text) == []

    def test_real_tree_sweep_emits_no_df020_df021(self):
        relpath = df020_abi.BINDINGS_RELPATH
        module = Module(
            REPO / relpath, relpath,
            (REPO / relpath).read_text(encoding="utf-8"),
        )
        fs = [f for f in run_checkers(module) if f.rule in ("DF020", "DF021")]
        assert fs == [], [f.render() for f in fs]

    def test_widening_ps_write_piece_argtype_fails_df020(self):
        cpp_text, contracts_text, bindings_text = _abi_real_inputs()
        needle = "const uint8_t* data, uint32_t length) try {"
        assert needle in cpp_text, "ps_write_piece signature drifted"
        msgs = _abi_real_findings(
            cpp_text.replace(
                needle, "const uint8_t* data, uint64_t length) try {", 1
            ),
            contracts_text, bindings_text,
        )
        assert any("ps_write_piece" in m and "C parameters" in m
                   for m in msgs), msgs

    def test_reordering_fetchdone_fields_fails_df020(self):
        cpp_text, contracts_text, bindings_text = _abi_real_inputs()
        status_line = (
            "  int32_t status;         // kFetchStatusOk / >0 HTTP / "
            "kFetchStatus{Conn,Proto,Commit}\n"
        )
        needle = status_line + "  uint32_t length;"
        assert needle in cpp_text, "FetchDone layout anchor drifted"
        msgs = _abi_real_findings(
            cpp_text.replace(needle, "  uint32_t length;\n" + status_line.rstrip("\n")),
            contracts_text, bindings_text,
        )
        assert any("record FetchDone" in m and "layout" in m for m in msgs), msgs

    def test_registry_constant_drift_fails_df020(self):
        cpp_text, contracts_text, bindings_text = _abi_real_inputs()
        needle = '"kBatchBytesMax": 524288,'
        assert needle in contracts_text, "registry constant anchor drifted"
        msgs = _abi_real_findings(
            cpp_text,
            contracts_text.replace(needle, '"kBatchBytesMax": 262144,'),
            bindings_text,
        )
        assert any("kBatchBytesMax" in m and "262144" in m for m in msgs), msgs

    def test_stripping_accept_loop_catch_fails_df021(self):
        cpp_text, contracts_text, bindings_text = _abi_real_inputs()
        needle = "void accept_loop(HttpServer* srv) try {"
        assert needle in cpp_text, "accept_loop signature drifted"
        mutated = cpp_text.replace(
            needle, "void accept_loop(HttpServer* srv) {", 1
        )
        msgs = _abi_real_findings(mutated, contracts_text, bindings_text)
        assert any("thread entry accept_loop" in m for m in msgs), msgs


class TestAbiManifestStaleness:
    """DESIGN.md §30's committed manifest block must match a fresh
    emission — same discipline as the lock-graph and det-inventory
    blocks."""

    def test_design_md_abi_manifest_is_current(self):
        from tools.dflint.__main__ import (
            ABI_MANIFEST_BEGIN, ABI_MANIFEST_END, render_abi_manifest,
        )

        text = (REPO / "DESIGN.md").read_text(encoding="utf-8")
        begin = text.find(ABI_MANIFEST_BEGIN)
        end = text.find(ABI_MANIFEST_END)
        assert begin >= 0 and end > begin, "DESIGN.md §30 manifest markers missing"
        committed = text[begin : end + len(ABI_MANIFEST_END)]
        fresh = render_abi_manifest(REPO)
        assert committed == fresh, (
            "DESIGN.md §30 abi manifest is stale — regenerate with "
            "`python -m tools.dflint --update-abi-manifest DESIGN.md`"
        )

    def test_update_abi_manifest_rewrites_in_place(self, tmp_path):
        from tools.dflint.__main__ import update_abi_manifest_file

        doc = tmp_path / "DESIGN.md"
        doc.write_text(
            "# doc\n\n<!-- dflint:abi-manifest:begin -->\nstale\n"
            "<!-- dflint:abi-manifest:end -->\ntail\n"
        )
        assert update_abi_manifest_file(doc, REPO) is True
        body = doc.read_text()
        assert "stale" not in body and '"version": 1' in body and "tail" in body
        # idempotent: a second run reports no change
        assert update_abi_manifest_file(doc, REPO) is False


class TestCLIAbiRules:
    def test_cli_rule_filter_selects_df020_df021(self, capsys):
        from tools.dflint.__main__ import main

        # Both rules anchor on the bindings module, so sweeping just
        # native/ exercises them fully without re-parsing the tree.
        rc = main(["dragonfly2_tpu/native", "--rule", "DF020,DF021", "-q"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "0 new finding(s)" in out

    def test_cli_emit_abi_manifest(self, capsys):
        from tools.dflint.__main__ import main

        rc = main(["--emit-abi-manifest"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "df_abi_manifest" in out and "sha256" in out
