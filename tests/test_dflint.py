"""dflint self-tests: every DF rule fires on a minimal true-positive
fixture and stays quiet on the accepted shapes, pragmas, and baseline
entries (tools/dflint — the tier-1 invariant gate's own coverage)."""

from __future__ import annotations

import sys
import textwrap
from pathlib import Path

import pytest  # noqa: F401  (parity with the suite's import style)

_REPO = Path(__file__).resolve().parents[1]
if str(_REPO) not in sys.path:  # `python -m pytest` from elsewhere
    sys.path.insert(0, str(_REPO))

from tools.dflint.baseline import Baseline, parse_toml_subset, render  # noqa: E402
from tools.dflint.core import Module, run_checkers  # noqa: E402


def lint(source: str, relpath: str = "dragonfly2_tpu/daemon/fixture.py"):
    src = textwrap.dedent(source)
    module = Module(Path("/fixture.py"), relpath, src)
    return run_checkers(module)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# DF001 — exception swallowing
# ---------------------------------------------------------------------------


class TestDF001:
    def test_silent_pass_fires(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception:
                    pass
        """)
        assert rules_of(fs) == ["DF001"]

    def test_bare_except_fires(self):
        fs = lint("""
            def f():
                try:
                    work()
                except:
                    return None
        """)
        assert "DF001" in rules_of(fs)

    def test_logging_call_is_handled(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception as exc:
                    log.warning("failed: %s", exc)
        """)
        assert fs == []

    def test_reraise_is_handled(self):
        fs = lint("""
            def f():
                try:
                    work()
                except BaseException:
                    raise
        """)
        assert fs == []

    def test_bound_name_use_is_handled(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception as exc:
                    result = exc
                return result
        """)
        assert fs == []

    def test_narrow_except_is_exempt(self):
        fs = lint("""
            def f():
                try:
                    work()
                except KeyError:
                    pass
        """)
        assert fs == []

    def test_pragma_suppresses(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception:  # dflint: disable=DF001
                    pass
        """)
        assert fs == []

    def test_file_pragma_suppresses(self):
        fs = lint("""
            # dflint: disable-file=DF001
            def f():
                try:
                    work()
                except Exception:
                    pass
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# DF002 — thread hygiene
# ---------------------------------------------------------------------------


class TestDF002:
    def test_thread_without_daemon_fires(self):
        fs = lint("""
            import threading

            def start():
                t = threading.Thread(target=loop)
                t.start()
        """)
        assert rules_of(fs) == ["DF002"]

    def test_daemon_kwarg_ok(self):
        fs = lint("""
            import threading

            def start():
                threading.Thread(target=loop, daemon=True).start()
        """)
        assert fs == []

    def test_joined_thread_still_needs_explicit_daemon(self):
        fs = lint("""
            import threading

            def run_all():
                ts = [threading.Thread(target=loop) for _ in range(4)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
        """)
        assert rules_of(fs) == ["DF002"]
        assert any("implicit" in f.message for f in fs)

    def test_joined_thread_with_explicit_daemon_false_ok(self):
        fs = lint("""
            import threading

            def run_all():
                ts = [threading.Thread(target=loop, daemon=False) for _ in range(4)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
        """)
        assert fs == []

    def test_unlocked_shared_mutation_fires(self):
        fs = lint("""
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._loop, daemon=True).start()

                def _loop(self):
                    self.count += 1

                def reset(self):
                    self.count = 0
        """)
        assert "DF002" in rules_of(fs)
        assert any("reset" in f.message for f in fs)

    def test_locked_mutation_ok(self):
        fs = lint("""
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._loop, daemon=True).start()

                def _loop(self):
                    with self._mu:
                        self.count += 1

                def reset(self):
                    with self._mu:
                        self.count = 0
        """)
        assert fs == []

    def test_private_method_mutation_not_flagged(self):
        fs = lint("""
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._loop, daemon=True).start()

                def _loop(self):
                    self.count += 1

                def _internal(self):
                    self.count = 0
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# DF003 — JAX trace purity
# ---------------------------------------------------------------------------


class TestDF003:
    def test_time_in_jit_decorator_fires(self):
        fs = lint("""
            import time
            import jax

            @jax.jit
            def step(x):
                t0 = time.time()
                return x + t0
        """)
        assert rules_of(fs) == ["DF003"]

    def test_wrapped_method_resolution(self):
        fs = lint("""
            import jax

            class Trainer:
                def __init__(self):
                    self._fn = jax.jit(self._step)

                def _step(self, x):
                    print(x)
                    return x
        """)
        assert rules_of(fs) == ["DF003"]

    def test_partial_jit_decorator(self):
        fs = lint("""
            import random
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames="n")
            def step(x, n):
                return x * random.random()
        """)
        assert rules_of(fs) == ["DF003"]

    def test_item_escape_fires(self):
        fs = lint("""
            import jax

            @jax.jit
            def step(x):
                return float(x.sum().item())
        """)
        assert "DF003" in rules_of(fs)

    def test_np_asarray_fires(self):
        fs = lint("""
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return np.asarray(x)
        """)
        assert "DF003" in rules_of(fs)

    def test_jax_random_exempt(self):
        fs = lint("""
            import jax

            @jax.jit
            def step(key, x):
                noise = jax.random.normal(key, x.shape)
                return x + noise
        """)
        assert fs == []

    def test_untraced_function_free(self):
        fs = lint("""
            import time

            def host_loop(x):
                time.sleep(1)
                print(x)
        """)
        assert fs == []

    def test_pallas_kernel_resolution(self):
        fs = lint("""
            import time
            import jax
            from jax.experimental import pallas as pl

            def kernel(x_ref, o_ref):
                time.sleep(0.1)
                o_ref[...] = x_ref[...]

            def run(x):
                return pl.pallas_call(kernel, out_shape=x)(x)
        """)
        assert rules_of(fs) == ["DF003"]


# ---------------------------------------------------------------------------
# DF004 — fault-seam coverage
# ---------------------------------------------------------------------------


class TestDF004:
    def test_urlopen_without_fire_fires(self):
        fs = lint("""
            import urllib.request

            def fetch(url):
                with urllib.request.urlopen(url, timeout=5) as resp:
                    return resp.read()
        """)
        assert rules_of(fs) == ["DF004"]

    def test_urlopen_with_fire_ok(self):
        fs = lint("""
            import urllib.request
            from dragonfly2_tpu.utils import faultinject

            def fetch(url):
                faultinject.fire("fixture.fetch")
                with urllib.request.urlopen(url, timeout=5) as resp:
                    return resp.read()
        """)
        assert fs == []

    def test_socket_send_without_fire_fires(self):
        fs = lint("""
            def push(sock, data):
                sock.sendall(data)
        """)
        assert rules_of(fs) == ["DF004"]

    def test_allowlisted_module_exempt(self):
        fs = lint(
            """
            import urllib.request

            def export(url):
                urllib.request.urlopen(url, timeout=5).close()
            """,
            relpath="dragonfly2_tpu/utils/tracing.py",
        )
        assert fs == []

    def test_fire_in_other_function_does_not_cover(self):
        fs = lint("""
            from dragonfly2_tpu.utils import faultinject

            def seam():
                faultinject.fire("fixture.other")

            def push(sock, data):
                sock.sendall(data)
        """)
        assert rules_of(fs) == ["DF004"]

    def test_seam_inventory_missing_site_fires(self):
        # daemon/upload.py owns two required sites; a module with only
        # one of them must be flagged for the other.
        fs = lint(
            """
            from ..utils import faultinject

            def serve_piece(task_id, number):
                faultinject.fire("daemon.upload.serve_piece")
                return b""
            """,
            relpath="dragonfly2_tpu/daemon/upload.py",
        )
        assert rules_of(fs) == ["DF004"]
        assert any("daemon.upload.body" in f.message for f in fs)

    def test_seam_inventory_fstring_prefix_matches(self):
        fs = lint(
            """
            from ..utils import faultinject

            def call(self, method):
                faultinject.fire(f"rpc.client.{method}")
            """,
            relpath="dragonfly2_tpu/rpc/scheduler_client.py",
        )
        assert [f for f in fs if f.rule == "DF004"] == []

    def test_real_seam_modules_satisfy_inventory(self):
        from tools.dflint.checkers.df004_fault_seams import (
            REQUIRED_SEAMS, fire_sites,
        )
        from tools.dflint.core import load_module

        repo = Path(__file__).resolve().parents[1]
        for relpath, required in REQUIRED_SEAMS.items():
            module = load_module(repo / relpath, repo)
            present = fire_sites(module)
            missing = [s for s in required if s not in present]
            assert not missing, f"{relpath}: missing seams {missing}"


# ---------------------------------------------------------------------------
# DF005 — resource hygiene
# ---------------------------------------------------------------------------


class TestDF005:
    def test_discarded_open_fires(self):
        fs = lint("""
            def touch(path):
                f = open(path, "w")
                f.write("x")
        """)
        assert rules_of(fs) == ["DF005"]

    def test_with_ok(self):
        fs = lint("""
            def touch(path):
                with open(path, "w") as f:
                    f.write("x")
        """)
        assert fs == []

    def test_immediate_close_ok(self):
        fs = lint("""
            def touch(path):
                open(path, "wb").close()
        """)
        assert fs == []

    def test_tracked_close_in_finally_ok(self):
        fs = lint("""
            import socket

            def probe():
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                try:
                    s.connect(("10.0.0.1", 1))
                    return s.getsockname()[0]
                finally:
                    s.close()
        """)
        assert fs == []

    def test_self_owned_ok(self):
        fs = lint("""
            class Store:
                def __init__(self, path):
                    self._f = open(path, "ab")

                def close(self):
                    self._f.close()
        """)
        assert fs == []

    def test_factory_return_ok(self):
        fs = lint("""
            import socket

            def connect(cid, port):
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.connect((cid, port))
                return s
        """)
        assert fs == []

    def test_expression_statement_open_fires(self):
        fs = lint("""
            def leak(path):
                open(path, "w").read()
        """)
        assert rules_of(fs) == ["DF005"]


# ---------------------------------------------------------------------------
# DF006 — deadline propagation in rpc/
# ---------------------------------------------------------------------------

RPC_PATH = "dragonfly2_tpu/rpc/fixture.py"


class TestDF006:
    def test_retry_without_deadline_fires(self):
        fs = lint(
            """
            from .retry import retry_call

            def call(fn):
                return retry_call(fn, attempts=3)
            """,
            relpath=RPC_PATH,
        )
        assert rules_of(fs) == ["DF006"]

    def test_deadline_passed_but_not_accepted_fires(self):
        fs = lint(
            """
            from .retry import retry_call

            def call(fn):
                return retry_call(fn, deadline_s=5.0)
            """,
            relpath=RPC_PATH,
        )
        assert rules_of(fs) == ["DF006"]

    def test_threaded_deadline_ok(self):
        fs = lint(
            """
            from .retry import retry_call

            def call(fn, *, deadline_s=None):
                return retry_call(fn, deadline_s=deadline_s)
            """,
            relpath=RPC_PATH,
        )
        assert fs == []

    def test_urlopen_without_timeout_fires(self):
        fs = lint(
            """
            import urllib.request
            from dragonfly2_tpu.utils import faultinject

            def get(url):
                faultinject.fire("rpc.fixture.get")
                with urllib.request.urlopen(url) as resp:
                    return resp.read()
            """,
            relpath=RPC_PATH,
        )
        assert rules_of(fs) == ["DF006"]

    def test_outside_rpc_exempt(self):
        fs = lint("""
            from dragonfly2_tpu.rpc.retry import retry_call

            def call(fn):
                return retry_call(fn, attempts=3)
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# DF007 — hot-path hygiene
# ---------------------------------------------------------------------------


class TestDF007:
    def test_loop_in_marked_function_fires(self):
        fs = lint("""
            import numpy as np

            def gather(rows):  # dflint: hotpath
                out = []
                for r in rows:
                    out.append(r * 2)
                return np.stack(out)
        """)
        assert "DF007" in rules_of(fs)

    def test_concatenate_in_marked_function_fires(self):
        fs = lint("""
            import numpy as np

            def featurize(a, b):  # dflint: hotpath
                return np.concatenate([a, b])
        """)
        assert "DF007" in rules_of(fs)

    def test_mark_on_line_above_def_applies(self):
        fs = lint("""
            import numpy as np

            # dflint: hotpath
            def featurize(a, b):
                return np.vstack([a, b])
        """)
        assert "DF007" in rules_of(fs)

    def test_comprehension_and_fromiter_are_accepted(self):
        fs = lint("""
            import numpy as np

            def score_all(parents):  # dflint: hotpath
                vals = np.fromiter((p.x for p in parents), np.float64)
                ids = [p.id for p in parents]
                return vals, ids
        """)
        assert fs == []

    def test_unmarked_function_is_free(self):
        fs = lint("""
            import numpy as np

            def build(rows):
                out = []
                for r in rows:
                    out.append(np.concatenate([r, r]))
                return out
        """)
        assert fs == []

    def test_pragma_suppresses_reviewed_constant_loop(self):
        fs = lint("""
            def mlp(x, weights):  # dflint: hotpath
                for w, b in weights:  # dflint: disable=DF007 — per-LAYER
                    x = x @ w + b
                return x
        """)
        assert fs == []

    def test_inventory_missing_function_fires_by_name(self):
        fs = lint(
            """
            def unrelated():
                return 1
            """,
            relpath="dragonfly2_tpu/scheduler/featcache.py",
        )
        assert any(
            f.rule == "DF007" and "HostFeatureCache.gather" in f.message
            for f in fs
        )

    def test_inventory_unmarked_function_fires(self):
        fs = lint(
            """
            class HostFeatureCache:
                def gather(self, hosts):
                    return hosts
            """,
            relpath="dragonfly2_tpu/scheduler/featcache.py",
        )
        assert any(
            f.rule == "DF007" and "lost its" in f.message for f in fs
        )


class TestBaseline:
    def _findings(self):
        return lint("""
            def f():
                try:
                    work()
                except Exception:
                    pass

            def g():
                try:
                    work()
                except Exception:
                    pass
        """)

    def test_split_budget(self):
        findings = self._findings()
        assert len(findings) == 2
        key_f = next(f for f in findings if f.qual == "f").key()
        bl = Baseline({key_f: 1})
        new, accepted = bl.split(findings)
        assert [f.qual for f in accepted] == ["f"]
        assert [f.qual for f in new] == ["g"]

    def test_budget_overflow_is_new(self):
        fs = lint("""
            def f():
                try:
                    work()
                except Exception:
                    pass
                try:
                    more()
                except Exception:
                    pass
        """)
        assert len(fs) == 2
        bl = Baseline({fs[0].key(): 1})   # both share the key (same qual)
        new, accepted = bl.split(fs)
        assert len(accepted) == 1 and len(new) == 1

    def test_stale_keys_reported(self):
        bl = Baseline({"DF001:gone.py:f": 1})
        assert bl.stale_keys([]) == ["DF001:gone.py:f"]

    def test_round_trip_through_toml(self, tmp_path):
        findings = self._findings()
        path = tmp_path / "baseline.toml"
        path.write_text(render(findings), encoding="utf-8")
        bl = Baseline.load(path)
        new, accepted = bl.split(findings)
        assert new == [] and len(accepted) == 2

    def test_toml_subset_parser(self):
        data = parse_toml_subset(
            '# comment\n[accepted]\n"DF001:a.py:f" = 2  # trailing\nplain = "x"\n'
        )
        assert data["accepted"]["DF001:a.py:f"] == 2
        assert data["accepted"]["plain"] == "x"

    def test_checked_in_baseline_parses(self):
        from tools.dflint.baseline import DEFAULT_PATH

        bl = Baseline.load(DEFAULT_PATH)
        assert isinstance(bl.budgets, dict)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


class TestCLI:
    def test_exit_zero_on_clean_file(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        clean = tmp_path / "clean.py"
        clean.write_text("def f():\n    return 1\n")
        assert main([str(clean)]) == 0

    def test_exit_nonzero_on_finding(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(
            "def f():\n    try:\n        g()\n    except Exception:\n        pass\n"
        )
        assert main([str(dirty)]) == 1
        out = capsys.readouterr().out
        assert "DF001" in out

    def test_select_filters_rules(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        dirty = tmp_path / "dirty.py"
        dirty.write_text(
            "def f():\n    try:\n        g()\n    except Exception:\n        pass\n"
        )
        assert main([str(dirty), "--select", "DF004"]) == 0

    def test_parse_error_exit_code(self, tmp_path, capsys):
        from tools.dflint.__main__ import main

        bad = tmp_path / "bad.py"
        bad.write_text("def f(:\n")
        assert main([str(bad)]) == 2

    def test_list_rules(self, capsys):
        from tools.dflint.__main__ import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ("DF001", "DF002", "DF003", "DF004", "DF005", "DF006"):
            assert rule in out


# ---------------------------------------------------------------------------
# Mutation sensitivity against the REAL tree (the acceptance contract:
# deleting a seam or a daemon= kwarg must fail the lint test by name)
# ---------------------------------------------------------------------------

REPO = Path(__file__).resolve().parents[1]


class TestMutationSensitivity:
    def _lint_source(self, relpath: str, source: str):
        module = Module(REPO / relpath, relpath, source)
        return run_checkers(module)

    def test_current_tree_is_clean(self):
        src_path = REPO / "dragonfly2_tpu/rpc/piece_transport.py"
        fs = self._lint_source(
            "dragonfly2_tpu/rpc/piece_transport.py",
            src_path.read_text(encoding="utf-8"),
        )
        assert fs == []

    def test_deleting_fire_seam_fails_df004(self):
        # download_via_daemon has exactly one seam guarding its urlopen;
        # removing it must re-expose the raw network call.
        relpath = "dragonfly2_tpu/rpc/daemon_control.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert 'faultinject.fire("daemon.control.download")' in source
        mutated = source.replace(
            'faultinject.fire("daemon.control.download")', "pass"
        )
        fs = self._lint_source(relpath, mutated)
        assert "DF004" in {f.rule for f in fs}

    def test_deleting_both_piece_fetch_seams_fails_df004(self):
        relpath = "dragonfly2_tpu/rpc/piece_transport.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        mutated = source.replace(
            'faultinject.fire("piece.fetch")', "pass"
        ).replace('faultinject.fire("piece.fetch.body", resp.read())',
                  "resp.read()")
        assert mutated != source
        fs = self._lint_source(relpath, mutated)
        assert "DF004" in {f.rule for f in fs}

    def test_deleting_daemon_kwarg_fails_df002(self):
        relpath = "dragonfly2_tpu/scheduler/push.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert "daemon=True" in source
        mutated = source.replace("daemon=True", "").replace(
            ", \n", "\n"
        )
        fs = self._lint_source(relpath, mutated)
        assert "DF002" in {f.rule for f in fs}

    def test_deleting_daemon_kwarg_on_joined_thread_fails_df002(self):
        # conductor's piece workers are join()ed, but the daemon flag must
        # still be explicit — deleting it is a lint regression, not a pass.
        relpath = "dragonfly2_tpu/daemon/conductor.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert ", daemon=True)" in source
        mutated = source.replace(", daemon=True)", ")")
        assert mutated != source
        fs = self._lint_source(relpath, mutated)
        assert "DF002" in {f.rule for f in fs}

    def test_unmarking_hotpath_inventory_fails_df007(self):
        # The serving-engine hygiene inventory pins evaluate_parents &co.;
        # stripping the hotpath marks must fail tier-1 by name.
        relpath = "dragonfly2_tpu/scheduler/evaluator.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        assert "# dflint: hotpath" in source
        mutated = source.replace("# dflint: hotpath", "")
        fs = self._lint_source(relpath, mutated)
        assert any(
            f.rule == "DF007" and "lost its" in f.message for f in fs
        )

    def test_looping_a_marked_hotpath_fails_df007(self):
        # Re-introducing the per-parent concatenate featurize (the exact
        # pre-PR shape) inside the marked function must be caught.
        relpath = "dragonfly2_tpu/scheduler/featcache.py"
        source = (REPO / relpath).read_text(encoding="utf-8")
        needle = "return self.gather_with_buckets(hosts)[0]"
        assert needle in source
        mutated = source.replace(
            needle,
            "rows = []\n"
            "        for h in hosts:\n"
            "            rows.append(self.features(h))\n"
            "        return np.stack(rows)",
        )
        fs = self._lint_source(relpath, mutated)
        assert "DF007" in {f.rule for f in fs}
