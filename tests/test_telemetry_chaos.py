"""Telemetry-plane chaos drills (ISSUE 12 acceptance; DESIGN.md §23).

Kill drill: SIGKILL one of three journaling daemons mid-storm (crash
fault on the ``metrics.journal.write`` seam), tear the dead journal's
tail frame and bit-rot a survivor's mid-file frame — ``fleet_assemble``
must still merge all three runs into fleet quantiles with 0 digest-bad
frames admitted, and the merged sketch p50/p99 must sit within the
declared relative-error bound of an EXACT oracle computed from the raw
samples the admitted frames cover.

Burn-rate drill: synthetic overload flips ``slo_breached`` within one
fast window, clears after recovery, and the journal replay
(``slo.replay_fleet``) reconstructs the same state ``/debug/slo``
served live.
"""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.sim import telemetry  # noqa: E402


class TestTelemetryKillDrill:
    def test_sigkill_mid_storm_fleet_quantiles_survive(self, tmp_path):
        report = telemetry.run_kill_drill(str(tmp_path / "kill"))
        # The drill asserts the hard invariants internally (victim
        # SIGKILLed, torn tail tolerated, digest-bad frame rejected,
        # count parity with the oracle, quantiles within α); the test
        # re-states the headline numbers for the failure report.
        assert report["ok"] is True
        assert report["children"] == 3
        assert report["victim_sigkilled"] is True
        assert report["corrupt_rejected"] == 1
        assert report["torn_tail_tolerated"] is True
        # The victim contributed a strict prefix of its storm: its
        # admitted frames cover fewer samples than the survivors'.
        covered = report["per_run_covered"]
        assert covered["dfdaemon0"] < covered["dfdaemon1"]
        for q, chk in report["quantile_checks"].items():
            assert chk["rel_error"] <= report["alpha"] * 1.0001, (q, chk)

    def test_fleet_assemble_renders_and_reports_slo(self, tmp_path):
        """The CLI surface over a journal set: human rendering + JSON +
        SLO replay through --slo-config."""
        import json
        import subprocess

        from dragonfly2_tpu.utils.metric_journal import MetricJournal
        from dragonfly2_tpu.utils.metrics import Registry

        journals = []
        for i in range(2):
            reg = Registry()
            sk = reg.sketch("drill_fetch_seconds", "")
            c = reg.counter("drill_ops_total", "")
            path = str(tmp_path / f"p{i}.dfmj")
            j = MetricJournal(path, registry=reg, service=f"d{i}",
                              interval_s=60, run_id=f"run-{i}")
            for k in range(100):
                sk.observe(0.01 if k % 10 else 0.5)
                c.inc()
            j.close()
            journals.append(path)
        slo_cfg = tmp_path / "slos.json"
        slo_cfg.write_text(json.dumps([telemetry.DRILL_SLO]))
        out = subprocess.run(
            [sys.executable, str(REPO / "tools" / "fleet_assemble.py"),
             *journals, "--json", "--slo-config", str(slo_cfg)],
            capture_output=True, text=True, cwd=str(REPO), timeout=60,
        )
        assert out.returncode == 0, out.stderr
        report = json.loads(out.stdout)
        assert report["total_corrupt"] == 0
        assert len(report["runs"]) == 2
        assert report["counters"]["drill_ops_total"]["total"] == 200.0
        q = report["quantiles"]["drill_fetch_seconds"]
        assert q["count"] == 200
        assert q["p50"] is not None and q["p99"] is not None
        assert report["slos"][0]["name"] == telemetry.DRILL_SLO["name"]
        # Human rendering too.
        out2 = subprocess.run(
            [sys.executable, str(REPO / "tools" / "fleet_assemble.py"),
             *journals],
            capture_output=True, text=True, cwd=str(REPO), timeout=60,
        )
        assert out2.returncode == 0, out2.stderr
        assert "Fleet quantiles" in out2.stdout
        assert "2 run(s) merged" in out2.stdout


class TestBurnRateDrill:
    def test_overload_fires_and_clears_and_replays(self, tmp_path):
        report = telemetry.run_burnrate_drill(
            str(tmp_path / "burn.dfmj")
        )
        assert report["ok"] is True
        assert report["fired_within_fast_window"] is True
        assert report["replay_matches_live"] is True
        assert report["replay_breached_at_fire"] is True
        assert report["replay_burn_drift"] <= 0.25
        final = report["final_state"]
        assert final["live"]["breached"] == final["replay"]["breached"]

    def test_debug_slo_endpoint_matches_engine_during_drill(self):
        """/debug/slo serves the installed engine's state verbatim —
        the wire half of the live-vs-replay parity bar."""
        import json
        import urllib.request

        from dragonfly2_tpu.utils import slo as slo_mod
        from dragonfly2_tpu.utils.diagnostics import DiagnosticsServer
        from dragonfly2_tpu.utils.metrics import Registry
        from dragonfly2_tpu.utils.slo import SLOEngine

        reg = Registry()
        sk = reg.sketch("drill_fetch_seconds", "")
        eng = SLOEngine([telemetry.DRILL_SLO], registry=reg)
        for _ in range(50):
            sk.observe(0.01)
        eng.tick(now=0.0)
        for _ in range(50):
            sk.observe(0.5)
        eng.tick(now=0.3)
        slo_mod.install_engine(eng)
        srv = DiagnosticsServer(port=0)
        srv.serve()
        try:
            with urllib.request.urlopen(
                srv.url + "/debug/slo", timeout=5
            ) as r:
                payload = json.loads(r.read())
        finally:
            srv.stop()
            slo_mod.install_engine(None)
        assert payload["slos"] == eng.state()["slos"]
        assert payload["slos"][0]["breached"] is True
