"""Seed-peer seeder: SeedQueue priority, ObtainSeeds event stream, the
scheduler's remote trigger client, and the cross-process cold-task flow
(reference: client/daemon/rpcserver/seeder.go:41-151,
scheduler/resource/seed_peer.go:93-229)."""

import json
import os
import select
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
from dragonfly2_tpu.daemon.conductor import Conductor
from dragonfly2_tpu.daemon.seeder import Seeder, SeedQueue
from dragonfly2_tpu.scheduler import (
    Evaluator,
    Resource,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
)
from dragonfly2_tpu.scheduler.resource import Host
from dragonfly2_tpu.scheduler.seed_client import pick_seed_host
from dragonfly2_tpu.utils.types import HostType, Priority

PIECE = 32 * 1024


class _Origin:
    def content(self, url, number):
        seed = (hash(url) ^ number) & 0xFF
        return bytes((seed + i) % 256 for i in range(PIECE))

    def fetch(self, url, number, piece_size):
        return self.content(url, number)


class TestSeedQueue:
    def test_priority_order(self):
        q = SeedQueue(max_concurrent=1)
        gate = threading.Event()
        ran = []
        done = threading.Event()

        def blocker():
            gate.wait(5)

        def job(name):
            def run():
                ran.append(name)
                if name == "l2":
                    done.set()
            return run

        q.submit(blocker, Priority.LEVEL0)
        time.sleep(0.05)  # blocker occupies the single worker
        q.submit(job("l2"), Priority.LEVEL2)
        q.submit(job("l0"), Priority.LEVEL0)
        q.submit(job("l1"), Priority.LEVEL1)
        gate.set()
        assert done.wait(5)
        assert ran == ["l0", "l1", "l2"]
        q.stop()

    def test_fifo_within_level(self):
        q = SeedQueue(max_concurrent=1)
        gate = threading.Event()
        ran = []
        done = threading.Event()
        q.submit(lambda: gate.wait(5), Priority.LEVEL0)
        time.sleep(0.05)
        for i in range(3):
            def mk(i=i):
                def run():
                    ran.append(i)
                    if i == 2:
                        done.set()
                return run
            q.submit(mk(), Priority.LEVEL1)
        gate.set()
        assert done.wait(5)
        assert ran == [0, 1, 2]
        q.stop()


class TestSeederStream:
    def _daemon(self, tmp_path, service):
        storage = DaemonStorage(str(tmp_path / "seednode"), prefer_native=False)
        host = Host(id="seed-0", hostname="seed-0", ip="127.0.0.1",
                    download_port=1, type=HostType.SUPER_SEED)
        conductor = Conductor(host, storage, service,
                              piece_fetcher=None, source_fetcher=_Origin())
        return storage, conductor

    def test_event_sequence_and_result(self, tmp_path):
        service = SchedulerService(
            Resource(), Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        )
        storage, conductor = self._daemon(tmp_path, service)

        # Deterministic mid-download observation: the in-process origin
        # is instant, so under suite load all 4 pieces could land before
        # the progress poller's first tick — hold the TAIL pieces until
        # a "piece" event proves the poller observed progress (direct
        # evidence, not a timing bet).
        progress_seen = threading.Event()
        inner_fetch = conductor.source_fetcher.fetch

        def gated_fetch(url, number, piece_size):
            if number >= 2:
                progress_seen.wait(10)
            return inner_fetch(url, number, piece_size)

        conductor.source_fetcher.fetch = gated_fetch
        seeder = Seeder(conductor, storage)
        events = []

        def emit(e):
            events.append(e)
            if e["event"] == "piece":
                progress_seen.set()

        url = "https://origin/seed-blob"
        # content_length comes from the request (the scheduler knows it or
        # the origin is sized by the daemon).
        res = seeder.obtain(
            url, piece_size=PIECE, content_length=4 * PIECE,
            priority=Priority.LEVEL1, emit=emit,
            poll_interval_s=0.01,
        )
        assert res["ok"] and res["pieces"] == 4
        kinds = [e["event"] for e in events]
        assert kinds[0] == "accepted" and events[0]["priority"] == 1
        assert kinds[-1] == "done" and events[-1]["ok"]
        # piece progress was observable before completion
        assert "piece" in kinds

    def test_pick_seed_host_ranking(self):
        normal = Host(id="n", hostname="n", ip="1.1.1.1", port=9)
        weak = Host(id="w", hostname="w", ip="1.1.1.2", port=9,
                    type=HostType.WEAK_SEED)
        sup = Host(id="s", hostname="s", ip="1.1.1.3", port=9,
                   type=HostType.SUPER_SEED)
        portless = Host(id="p", hostname="p", ip="1.1.1.4", port=0,
                        type=HostType.SUPER_SEED)
        assert pick_seed_host([normal, weak, sup, portless]).id == "s"
        assert pick_seed_host([normal, weak]).id == "w"
        assert pick_seed_host([normal]) is None


class _RangeOrigin(BaseHTTPRequestHandler):
    """Range-serving HTTP origin for real source fetches."""

    BLOB = bytes(i % 251 for i in range(6 * PIECE))
    hits = []

    def log_message(self, *args):
        pass

    def do_HEAD(self):
        self.send_response(200)
        self.send_header("Content-Length", str(len(self.BLOB)))
        self.send_header("Accept-Ranges", "bytes")
        self.end_headers()

    def do_GET(self):
        type(self).hits.append(self.path)
        rng = self.headers.get("Range")
        body = self.BLOB
        code = 200
        if rng:
            spec = rng.split("=", 1)[1]
            start, end = spec.split("-")
            end = int(end) if end else len(body) - 1
            body = body[int(start): end + 1]
            code = 206
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TestColdTaskCrossProcess:
    """VERDICT r1 missing-#2 done-condition: registering a COLD task makes
    a seed daemon (own OS process) source-download and serve pieces — the
    client peer never goes back-to-source."""

    def test_cold_task_triggers_seed_daemon(self, tmp_path):
        env = {**os.environ, "PYTHONPATH": os.getcwd(),
               "DF_DAEMON_STATE": str(tmp_path / "daemon.json")}
        procs = []

        def spawn(argv, ready_prefix):
            proc = subprocess.Popen(
                [sys.executable, *argv],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            )
            procs.append(proc)
            deadline = time.time() + 30
            while time.time() < deadline:
                ready, _, _ = select.select([proc.stdout], [], [], 30)
                assert ready, f"{argv}: no output"
                line = proc.stdout.readline().strip()
                if line.startswith(ready_prefix):
                    return line
            raise AssertionError(f"{argv}: never printed {ready_prefix}")

        origin_srv = ThreadingHTTPServer(("127.0.0.1", 0), _RangeOrigin)
        threading.Thread(target=origin_srv.serve_forever, daemon=True).start()
        origin_url = f"http://127.0.0.1:{origin_srv.server_address[1]}/cold-blob"
        _RangeOrigin.hits.clear()

        sched_cfg = tmp_path / "sched.yaml"
        sched_cfg.write_text(
            "server: {host: 127.0.0.1, port: 0, grpc_port: 0}\n"
            "scheduling: {retry_interval_s: 0.0}\n"
            f"storage: {{dir: {tmp_path / 'records'}, buffer_size: 1}}\n"
        )
        daemon_cfg = tmp_path / "daemon.yaml"
        daemon_cfg.write_text(
            # advertise_ip must match where the control/piece servers bind
            # — the scheduler dials the ANNOUNCED ip for /obtain_seeds.
            "server: {host: 127.0.0.1, port: 0, advertise_ip: 127.0.0.1}\n"
            f"storage: {{dir: {tmp_path / 'seedstore'}}}\n"
            f"piece_size: {PIECE}\n"
        )

        try:
            line = spawn(
                ["-m", "dragonfly2_tpu.cli.scheduler", "--config", str(sched_cfg)],
                "scheduler: serving",
            )
            import re

            http_url = re.search(r"rpc on (\S+)", line).group(1)
            spawn(
                ["-m", "dragonfly2_tpu.cli.dfdaemon", "--scheduler", http_url,
                 "--config", str(daemon_cfg), "--seed-peer"],
                "dfdaemon: serving",
            )

            # Client peer in this process: registers the COLD task.
            from dragonfly2_tpu.rpc import (
                HTTPPieceFetcher,
                PieceHTTPServer,
                RemoteScheduler,
            )

            storage = DaemonStorage(str(tmp_path / "clientnode"),
                                    prefer_native=False)
            upload = UploadManager(storage)
            ps = PieceHTTPServer(upload)
            ps.serve()
            host = Host(id="client-0", hostname="client-0", ip="127.0.0.1",
                        download_port=ps.port)
            client = RemoteScheduler(http_url)
            conductor = Conductor(
                host, storage, client,
                piece_fetcher=HTTPPieceFetcher(client.resolve_host),
                source_fetcher=None,  # MUST come from the seed, not origin
            )
            r = conductor.download(
                url=origin_url, piece_size=PIECE, content_length=6 * PIECE
            )
            assert r.ok, "cold download failed"
            assert not r.back_to_source
            assert r.pieces == 6
            # The SEED fetched from the origin (range GETs), not the client.
            assert _RangeOrigin.hits, "origin never touched — where did bytes come from?"
            for n in range(6):
                assert storage.read_piece(r.task_id, n) == \
                    _RangeOrigin.BLOB[n * PIECE:(n + 1) * PIECE]
            ps.stop()
        finally:
            for proc in procs:
                proc.terminate()
            origin_srv.shutdown()


class TestPublicSurfaceLockdown:
    def test_public_endpoint_rejects_download(self, tmp_path):
        """The routable seed endpoint must NOT expose /download (it writes
        arbitrary local files — a same-machine contract)."""
        from dragonfly2_tpu.rpc.daemon_control import DaemonControlServer

        service = SchedulerService(
            Resource(), Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        )
        storage = DaemonStorage(str(tmp_path / "pub"), prefer_native=False)
        host = Host(id="s0", hostname="s0", ip="127.0.0.1", download_port=1,
                    type=HostType.SUPER_SEED)
        conductor = Conductor(host, storage, service,
                              piece_fetcher=None, source_fetcher=_Origin())
        srv = DaemonControlServer(
            conductor, piece_size=PIECE,
            seeder=Seeder(conductor, storage), public=True,
        )
        srv.serve()
        try:
            req = urllib.request.Request(
                srv.url + "/download",
                data=json.dumps({"url": "https://x", "output": "/tmp/evil"}).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 404
            # Malformed obtain_seeds bodies get clean 400s, not dropped
            # connections.
            for bad in ([1, 2], {"url": "https://x", "priority": 99}):
                req = urllib.request.Request(
                    srv.url + "/obtain_seeds", data=json.dumps(bad).encode(),
                    headers={"Content-Type": "application/json"}, method="POST",
                )
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(req, timeout=5)
                assert exc.value.code == 400
        finally:
            srv.stop()
