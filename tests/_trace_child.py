"""Subprocess body for the flight-recorder chaos drill
(tests/test_trace_chaos.py).

A wire daemon with its OWN durable trace log: registers with the
parent's scheduler over HTTP, pulls pieces from the warm parent over the
piece plane, and — via a ``crash`` FaultSpec on the
``rpc.client.report_pieces_finished`` seam (DF_FAULTINJECT) — SIGKILLs
itself at a deterministic report flush, mid-download.  The spans that
finished before the kill are already durable (the exporter writes one
digest-checked frame per span at export time); everything in flight dies
with the process, exactly like production.  The parent test then proves
``tools/trace_assemble.py`` reconstructs the end-to-end trace from this
log plus the scheduler's.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from dragonfly2_tpu.utils import faultinject, tracing  # noqa: E402


def main():
    scheduler_url, store_dir, trace_log, url = sys.argv[1:5]
    content_length, piece_size = int(sys.argv[5]), int(sys.argv[6])
    faultinject.install_from_env()
    tracing.default_tracer.service = "dfdaemon"
    tracing.default_tracer.exporter = tracing.DurableSpanExporter(
        trace_log, service="dfdaemon", sample_rate=1.0
    )

    from dragonfly2_tpu.daemon import DaemonStorage
    from dragonfly2_tpu.daemon.conductor import Conductor
    from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler
    from dragonfly2_tpu.scheduler.resource import Host

    host = Host(
        id="trace-child", hostname="trace-child", ip="127.0.0.1",
        port=8002, download_port=1,
    )
    host.stats.network.idc = "idc-a"
    client = RemoteScheduler(scheduler_url, timeout=5.0)
    storage = DaemonStorage(store_dir, prefer_native=False)
    conductor = Conductor(
        host, storage, client,
        piece_fetcher=HTTPPieceFetcher(client.resolve_host, timeout=5.0),
        source_fetcher=None,
        piece_parallelism=2,
        # Zero linger: report flushes track pieces closely, so the
        # parent drill's crash-at-flush-2 fault lands mid-download.
        report_linger_s=0.0,
    )
    print("trace-child: ready", flush=True)
    r = conductor.download(
        url, piece_size=piece_size, content_length=content_length
    )
    print(json.dumps({"ok": r.ok, "pieces": r.pieces}), flush=True)


if __name__ == "__main__":
    main()
