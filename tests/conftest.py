"""Test config: virtual 8-device CPU mesh, lock witness, compile witness,
crash witness, deadlock watchdog.

Session-wide concerns live here, in load order:

1. **Lock witness** (``dragonfly2_tpu/utils/dflock.py``): installed
   BEFORE any ``dragonfly2_tpu`` import so every project lock created
   during the tier-1 run is wrapped in a recording proxy.  The module is
   bootstrapped by file path (not package import) so no package
   ``__init__`` runs — and thus no module-level lock is created — ahead
   of the install.  ``tests/test_zz_lockwitness.py`` cross-validates the
   recorded acquisition-order edges against dflint's static lock graph.
   Set ``DF_LOCK_WITNESS=0`` to disable.

2. **JAX platform**: multi-chip hardware is unavailable in CI; all
   sharding tests run against ``--xla_force_host_platform_device_count=8``
   (the driver separately dry-runs the multi-chip path via
   ``__graft_entry__.dryrun_multichip``).  The environment presets
   ``JAX_PLATFORMS=axon`` (the real TPU tunnel) and its sitecustomize
   re-prepends "axon" at interpreter startup, so the env var alone
   cannot win — unit tests force the CPU mesh via jax.config below.

3. **Deadlock watchdog**: the tier-1 runner wraps pytest in
   ``timeout -k 10 870``, which SIGKILLs a hung run with no diagnostics —
   a deadlock dies silently.  ``faulthandler.dump_traceback_later`` is
   armed slightly inside that budget (default 840 s, override with
   ``DF_TEST_WATCHDOG_S``; 0 disables) so a wedged test dumps every
   thread's stack to stderr BEFORE the outer timeout fires.
"""

import faulthandler
import importlib.util
import os
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parents[1]

# -- 1. lock witness (must precede any dragonfly2_tpu import) ---------------

if os.environ.get("DF_LOCK_WITNESS", "1") != "0":
    _spec = importlib.util.spec_from_file_location(
        "dragonfly2_tpu.utils.dflock",
        str(_REPO / "dragonfly2_tpu" / "utils" / "dflock.py"),
    )
    _dflock = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(_dflock)
    # Register under the canonical name so later package imports reuse
    # THIS instance (and its installed witness) instead of re-executing.
    sys.modules["dragonfly2_tpu.utils.dflock"] = _dflock
    _dflock.install(str(_REPO / "dragonfly2_tpu"))

# -- 2. JAX virtual mesh ----------------------------------------------------

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# -- 2b. compile witness (dftrace) ------------------------------------------
# Installed AFTER jax exists but BEFORE any dragonfly2_tpu import, so every
# module-level `jax.jit(...)` in project code is created through the
# counting factory.  Bootstrapped by file path like dflock (no package
# __init__ runs ahead of the install).  tests/test_zz_compilewitness.py
# cross-validates the recorded per-creation compile counts against the
# static jit-site index (tools/dflint/tracerules.py) and the checked-in
# compile budget (tools/dflint/compile_budget.toml).
# Set DF_COMPILE_WITNESS=0 to disable.

if os.environ.get("DF_COMPILE_WITNESS", "1") != "0":
    _tspec = importlib.util.spec_from_file_location(
        "dragonfly2_tpu.utils.dftrace",
        str(_REPO / "dragonfly2_tpu" / "utils" / "dftrace.py"),
    )
    _dftrace = importlib.util.module_from_spec(_tspec)
    _tspec.loader.exec_module(_dftrace)
    sys.modules["dragonfly2_tpu.utils.dftrace"] = _dftrace
    _dftrace.install(str(_REPO / "dragonfly2_tpu"))

# -- 2c. crash witness (dfcrash) --------------------------------------------
# Installed AFTER dflock/dftrace (so the state module's import is itself
# witnessed) and BEFORE any test imports: every KVTable write the suite
# performs from project code records (namespace, caller site, method,
# rows).  tests/test_zz_crashwitness.py cross-validates the observations
# against DF014's static persistence inventory
# (tools/dflint/staterules.py) and crash-injects at the declared
# multi-row sites.  Set DF_CRASH_WITNESS=0 to disable.

if os.environ.get("DF_CRASH_WITNESS", "1") != "0":
    if str(_REPO) not in sys.path:
        sys.path.insert(0, str(_REPO))
    from dragonfly2_tpu.utils import dfcrash as _dfcrash

    _dfcrash.install(str(_REPO / "dragonfly2_tpu"))

# -- 2d. span witness (dfspan) ----------------------------------------------
# Installed alongside dfcrash: wraps Tracer.span/remote_span so every
# span OPENED from project code during the suite records its caller
# module + name.  tests/test_zz_spanwitness.py cross-validates the
# observations against DF016's REQUIRED_SPANS inventory
# (tools/dflint/checkers/df016_spans.py) — the runtime half of the
# span-coverage contract (DESIGN.md §21).  Set DF_SPAN_WITNESS=0 to
# disable.

if os.environ.get("DF_SPAN_WITNESS", "1") != "0":
    if str(_REPO) not in sys.path:
        sys.path.insert(0, str(_REPO))
    from dragonfly2_tpu.utils import dfspan as _dfspan

    _dfspan.install(str(_REPO / "dragonfly2_tpu"))

# -- 2e. determinism witness (dfdet) ----------------------------------------
# Installed last of the witnesses: patches the ambient nondeterminism
# sources (time.time/monotonic/perf_counter + _ns, os.urandom,
# uuid.uuid1/uuid4, ambient random draws) with call-site recorders and
# wraps every declared replay root (records/determinism_contracts.py)
# so the recorder is ARMED only while a root is on the stack.
# tests/test_zz_detwitness.py cross-validates the observations against
# DF018's static taint report (tools/dflint/detrules.py) and re-runs
# every root under different PYTHONHASHSEED — the runtime half of the
# replay-determinism contract (DESIGN.md §27).  Set DF_DET_WITNESS=0 to
# disable.

if os.environ.get("DF_DET_WITNESS", "1") != "0":
    if str(_REPO) not in sys.path:
        sys.path.insert(0, str(_REPO))
    from dragonfly2_tpu.utils import dfdet as _dfdet

    _dfdet.install(str(_REPO / "dragonfly2_tpu"))

# -- 2f. ABI witness (dfabi) -------------------------------------------------
# Bookkeeping-only install (the native .so is NOT built or loaded here —
# most tier-1 tests never touch native; the witness test triggers the
# lazy load itself).  tests/test_zz_abiwitness.py requires the compiled
# library's df_abi_manifest() to byte-match the canonical JSON rendered
# from records/abi_contracts.py and round-trips a sentinel FetchDone
# through df_abi_probe_fetchdone() — the runtime half of the DF020/DF021
# ABI contract (DESIGN.md §30).  Set DF_ABI_WITNESS=0 to disable.

if os.environ.get("DF_ABI_WITNESS", "1") != "0":
    if str(_REPO) not in sys.path:
        sys.path.insert(0, str(_REPO))
    from dragonfly2_tpu.utils import dfabi as _dfabi

    _dfabi.install(str(_REPO / "dragonfly2_tpu"))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# -- 3. faulthandler deadlock watchdog --------------------------------------

_WATCHDOG_S = float(os.environ.get("DF_TEST_WATCHDOG_S", "840"))


def pytest_sessionstart(session):
    if _WATCHDOG_S > 0:
        # exit=False: dump all thread stacks, then leave the outer
        # `timeout -k` to deliver the kill — the dump is the diagnosis,
        # the runner stays the executioner.
        faulthandler.dump_traceback_later(_WATCHDOG_S, exit=False)


def pytest_sessionfinish(session, exitstatus):
    if _WATCHDOG_S > 0:
        faulthandler.cancel_dump_traceback_later()
    # Budget-calibration aid: DF_COMPILE_OBSERVED=<path> dumps the compile
    # witness's per-site stats as JSON at session end (docs: DESIGN.md §17).
    out_path = os.environ.get("DF_COMPILE_OBSERVED")
    if out_path:
        try:
            from dragonfly2_tpu.utils import dftrace

            w = dftrace.witness()
            if w is not None:
                import json

                with open(out_path, "w", encoding="utf-8") as f:
                    json.dump(
                        {
                            f"{site[0]}:{site[1]}": stats
                            for site, stats in sorted(w.snapshot().items())
                        },
                        f, indent=2, sort_keys=True,
                    )
        except Exception as exc:  # noqa: BLE001 — diagnostics-only dump
            print(f"DF_COMPILE_OBSERVED dump failed: {exc}", file=sys.stderr)


@pytest.fixture(scope="session")
def cluster():
    from dragonfly2_tpu.records.synthetic import SyntheticCluster

    return SyntheticCluster(num_hosts=48, seed=42)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
