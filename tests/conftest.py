"""Test config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding tests run against
``--xla_force_host_platform_device_count=8`` (the driver separately
dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).

Must run before any jax import, hence the env mutation at module import.
"""

import os

# Hard override: the environment presets JAX_PLATFORMS=axon (the real TPU
# tunnel) and its sitecustomize re-prepends "axon" to jax_platforms at
# interpreter startup, so the env var alone cannot win — unit tests must
# run on the virtual 8-device CPU mesh, forced via jax.config below.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cluster():
    from dragonfly2_tpu.records.synthetic import SyntheticCluster

    return SyntheticCluster(num_hosts=48, seed=42)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
