"""Test config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding tests run against
``--xla_force_host_platform_device_count=8`` (the driver separately
dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).

Must run before any jax import, hence the env mutation at module import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cluster():
    from dragonfly2_tpu.records.synthetic import SyntheticCluster

    return SyntheticCluster(num_hosts=48, seed=42)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
