"""Federated trainer tests (BASELINE configs[3]): non-IID cluster shards,
FedAvg improves the global model round over round, aggregated artifact
registers and serves."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dragonfly2_tpu.manager import ModelRegistry
from dragonfly2_tpu.records.synthetic import SyntheticCluster
from dragonfly2_tpu.trainer.federated import (
    ClusterShard,
    FederatedConfig,
    FederatedTrainer,
)


@pytest.fixture(scope="module")
def federation():
    """4 scheduler clusters with non-IID data: each latent cluster has its
    own topology/capacity distribution (different seeds)."""
    shards, evals = [], []
    for c in range(4):
        cluster = SyntheticCluster(num_hosts=32, seed=100 + c)
        rows = cluster.generate_feature_rows(3000, seed=c)
        shards.append(ClusterShard(cluster_id=f"cluster-{c}", rows=rows[:2500]))
        evals.append(rows[2500:])
    return shards, np.concatenate(evals, axis=0)


class TestFederated:
    def test_rounds_improve_global_mae(self, federation):
        shards, eval_rows = federation
        trainer = FederatedTrainer(
            shards,
            config=FederatedConfig(rounds=4, local_epochs=3, learning_rate=3e-3),
        )
        baseline = float(
            np.mean(np.abs(eval_rows[:, -1] - eval_rows[:, -1].mean()))
        )
        metrics = trainer.run(eval_rows)
        maes = [h["mae"] for h in trainer.history]
        assert maes[-1] < maes[0], maes          # rounds improve the model
        assert metrics.mae < baseline, (metrics.mae, baseline)

    def test_weighted_aggregation(self, federation):
        shards, _ = federation
        # A tiny shard must not dominate: weight by sample count.
        big = shards[0]
        small = ClusterShard("tiny", shards[1].rows[:50])
        trainer = FederatedTrainer(
            [big, small], config=FederatedConfig(rounds=1, local_epochs=1)
        )
        p_big, n_big = trainer.train_local(big, trainer.global_params)
        p_small, n_small = trainer.train_local(small, trainer.global_params)
        trainer.run_round()
        leaf = lambda t: np.asarray(
            jax.tree_util.tree_leaves(t)[0], dtype=np.float64
        )
        agg = leaf(trainer.global_params)
        expect = (leaf(p_big) * n_big + leaf(p_small) * n_small) / (n_big + n_small)
        np.testing.assert_allclose(agg, expect, rtol=1e-4, atol=1e-5)

    def test_publish_to_registry_and_score(self, federation):
        shards, eval_rows = federation
        trainer = FederatedTrainer(
            shards, config=FederatedConfig(rounds=2, local_epochs=2, learning_rate=3e-3)
        )
        trainer.run(eval_rows)
        registry = ModelRegistry()
        model = trainer.publish(registry)
        assert model.version == 1
        from dragonfly2_tpu.trainer.export import load_scorer

        scorer = load_scorer(registry.load_artifact(model))
        pred = scorer.score(eval_rows[:100, 2:-1])
        assert np.isfinite(pred).all()
        mae = float(np.mean(np.abs(pred - eval_rows[:100, -1])))
        baseline = float(np.mean(np.abs(eval_rows[:100, -1] - eval_rows[:, -1].mean())))
        assert mae < baseline
