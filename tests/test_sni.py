"""SNI-hijack proxy: ClientHello parsing, TLS interception into P2P,
and byte-faithful relay of unmatched hosts."""

import socket
import ssl
import threading

import pytest

pytest.importorskip(
    "cryptography", reason="SNI interception needs the gated CA surface"
)

from dragonfly2_tpu.daemon.sni import SNIProxy, parse_client_hello_sni
from dragonfly2_tpu.security.ca import CertificateAuthority, PeerIdentity
from dragonfly2_tpu.utils import idgen

from tests.test_daemon import PIECE, _Swarm


def _capture_client_hello(server_hostname: str) -> bytes:
    """Record the raw bytes the ssl module actually sends for an SNI."""
    listener = socket.create_server(("127.0.0.1", 0))
    captured = {}

    def server():
        conn, _ = listener.accept()
        captured["hello"] = conn.recv(16384)
        conn.close()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = True
    try:
        with socket.create_connection(listener.getsockname()) as raw:
            with ctx.wrap_socket(raw, server_hostname=server_hostname):
                pass
    except (ssl.SSLError, OSError):
        pass  # handshake can't complete; we only want the ClientHello
    t.join(timeout=5)
    listener.close()
    return captured["hello"]


class TestClientHelloParser:
    def test_parses_real_ssl_module_hello(self):
        hello = _capture_client_hello("origin.internal.example")
        assert parse_client_hello_sni(hello) == "origin.internal.example"

    def test_garbage_and_short_input(self):
        assert parse_client_hello_sni(b"") is None
        assert parse_client_hello_sni(b"GET / HTTP/1.1\r\n") is None
        assert parse_client_hello_sni(b"\x16\x03\x01\x00\x05ab") is None

    def test_hello_without_sni(self):
        hello = _capture_client_hello("no-sni.example")
        # Strip the server_name extension bytes wholesale → parser must
        # return None, not crash.
        idx = hello.find(b"no-sni.example")
        assert idx > 0
        broken = hello[: idx - 9]  # truncate inside the extension block
        assert parse_client_hello_sni(broken) is None


class TestCAPersistence:
    def test_persistent_ca_survives_restart(self, tmp_path):
        d = str(tmp_path / "ca")
        ca1 = CertificateAuthority.persistent(d)
        ca2 = CertificateAuthority.persistent(d)
        assert ca1.cert_pem == ca2.cert_pem
        # The reloaded CA can still issue working identities.
        identity = PeerIdentity.issue(ca2, common_name="x", hostnames=["x"])
        assert b"BEGIN CERTIFICATE" in identity.cert_pem

    def test_slow_client_hello_times_out_not_spins(self):
        import time as _time

        from dragonfly2_tpu.daemon.sni import _peek_client_hello

        listener = socket.create_server(("127.0.0.1", 0))
        client = socket.create_connection(listener.getsockname())
        conn, _ = listener.accept()
        client.sendall(b"\x16\x03\x01")  # 3 bytes of a record, then stall
        t0 = _time.monotonic()
        data = _peek_client_hello(conn, timeout=0.5)
        elapsed = _time.monotonic() - t0
        assert data == b"\x16\x03\x01"
        assert 0.3 < elapsed < 5.0  # returned at the deadline, no hang
        client.close()
        conn.close()
        listener.close()


def _client_ctx(ca: CertificateAuthority) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_verify_locations(cadata=ca.cert_pem.decode())
    return ctx


class TestSNIHijack:
    def test_hijacked_host_served_from_p2p(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=2)
        swarm.origin.content_length = lambda u: 3 * PIECE
        ca = CertificateAuthority()
        proxy = SNIProxy(
            swarm.daemons[0], ca=ca, hijack=[r"\.hijack\.test$"],
            piece_size=PIECE,
        )
        proxy.serve()
        try:
            ctx = _client_ctx(ca)
            with socket.create_connection(("127.0.0.1", proxy.port)) as raw:
                with ctx.wrap_socket(
                    raw, server_hostname="origin.hijack.test"
                ) as tls:
                    # The leaf cert was minted on the fly for this SNI and
                    # chains to the daemon CA (check_hostname verified it).
                    tls.sendall(
                        b"GET /blob-sni HTTP/1.1\r\n"
                        b"Host: origin.hijack.test\r\n\r\n"
                    )
                    resp = b""
                    while True:
                        chunk = tls.recv(65536)
                        if not chunk:
                            break
                        resp += chunk
            head, _, body = resp.partition(b"\r\n\r\n")
            assert head.startswith(b"HTTP/1.1 200")
            expected = b"".join(
                swarm.origin.content("https://origin.hijack.test/blob-sni", n)
                for n in range(3)
            )
            assert body == expected
            assert proxy.stats["hijacked"] == 1
            # The bytes came through the P2P engine, not a direct fetch.
            tid = idgen.task_id("https://origin.hijack.test/blob-sni")
            assert swarm.daemons[0].storage.engine.piece_count(tid) == 3
        finally:
            proxy.stop()

    def test_unmatched_host_relayed_to_origin(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=1)
        ca = CertificateAuthority()
        # Real TLS upstream for "localhost", its own CA-issued identity.
        upstream_id = PeerIdentity.issue(
            ca, common_name="localhost", hostnames=["localhost"]
        )
        upstream_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            paths = upstream_id.write(d)
            upstream_ctx.load_cert_chain(paths["cert"], paths["key"])
        listener = socket.create_server(("127.0.0.1", 0))

        def upstream():
            conn, _ = listener.accept()
            with upstream_ctx.wrap_socket(conn, server_side=True) as tls:
                data = tls.recv(1024)
                tls.sendall(b"echo:" + data)

        t = threading.Thread(target=upstream, daemon=True)
        t.start()

        proxy = SNIProxy(
            swarm.daemons[0], ca=ca, hijack=[r"\.hijack\.test$"],
            relay_port=listener.getsockname()[1],
        )
        proxy.serve()
        try:
            ctx = _client_ctx(ca)
            with socket.create_connection(("127.0.0.1", proxy.port)) as raw:
                with ctx.wrap_socket(raw, server_hostname="localhost") as tls:
                    tls.sendall(b"ping")
                    assert tls.recv(1024) == b"echo:ping"
            assert proxy.stats["relayed"] == 1
            assert proxy.stats["hijacked"] == 0
        finally:
            proxy.stop()
            listener.close()
        t.join(timeout=5)
