"""Wire-transport tests: scheduler RPC over real TCP, HTTP piece data
plane, consistent-hash balancer, retry — a multi-"node" swarm where every
byte and control message crosses a socket."""

import threading

import numpy as np
import pytest

from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
from dragonfly2_tpu.daemon.conductor import Conductor
from dragonfly2_tpu.records.storage import Storage
from dragonfly2_tpu.rpc import (
    HashRing,
    HTTPPieceFetcher,
    PieceHTTPServer,
    RemoteScheduler,
    SchedulerHTTPServer,
    retry_call,
)
from dragonfly2_tpu.scheduler import (
    Evaluator,
    NetworkTopology,
    Resource,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
)
from dragonfly2_tpu.scheduler.resource import Host

PIECE = 32 * 1024


class WireOrigin:
    def __init__(self):
        self.fetches = 0

    def content(self, url, number):
        seed = (hash(url) ^ number) & 0xFF
        return bytes((seed + i) % 256 for i in range(PIECE))

    def fetch(self, url, number, piece_size):
        self.fetches += 1
        return self.content(url, number)


class WireNode:
    """One 'machine': piece server + remote scheduler client + conductor."""

    def __init__(self, i, scheduler_url, tmp_path, origin):
        self.storage = DaemonStorage(str(tmp_path / f"node{i}"), prefer_native=False)
        self.upload = UploadManager(self.storage)
        self.piece_server = PieceHTTPServer(self.upload)
        self.piece_server.serve()
        self.host = Host(
            id=f"node-{i}",
            hostname=f"node-{i}",
            ip="127.0.0.1",
            download_port=self.piece_server.port,
        )
        self.host.stats.network.idc = "idc-a"
        self.client = RemoteScheduler(scheduler_url)
        self.conductor = Conductor(
            self.host,
            self.storage,
            self.client,
            piece_fetcher=HTTPPieceFetcher(self.client.resolve_host),
            source_fetcher=origin,
        )

    def stop(self):
        self.piece_server.stop()


@pytest.fixture()
def wire_swarm(tmp_path):
    resource = Resource()
    service = SchedulerService(
        resource,
        Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
        Storage(str(tmp_path / "records"), buffer_size=1),
        NetworkTopology(resource.host_manager),
    )
    server = SchedulerHTTPServer(service)
    server.serve()
    origin = WireOrigin()
    nodes = [WireNode(i, server.url, tmp_path, origin) for i in range(3)]
    yield {"server": server, "service": service, "nodes": nodes, "origin": origin}
    for n in nodes:
        n.stop()
    server.stop()


class TestWireSwarm:
    def test_p2p_over_sockets(self, wire_swarm):
        nodes, origin = wire_swarm["nodes"], wire_swarm["origin"]
        url = "https://origin/wire-blob"
        r0 = nodes[0].conductor.download(url, piece_size=PIECE, content_length=4 * PIECE)
        assert r0.ok and r0.back_to_source and r0.pieces == 4
        fetches = origin.fetches

        r1 = nodes[1].conductor.download(url, piece_size=PIECE)
        assert r1.ok and not r1.back_to_source
        assert origin.fetches == fetches  # bytes came from node-0 over HTTP
        assert nodes[0].upload.upload_count == 4
        for n in range(4):
            assert nodes[1].storage.read_piece(r1.task_id, n) == origin.content(url, n)

        # Scheduler-side record written with parent attribution.
        service = wire_swarm["service"]
        service.storage.flush()
        downloads = service.storage.list_download()
        p2p = [d for d in downloads if d.parents]
        assert p2p and p2p[0].parents[0].observed_bandwidth() > 0

    def test_parent_death_reschedules_over_wire(self, wire_swarm):
        nodes = wire_swarm["nodes"]
        url = "https://origin/wire-blob-2"
        nodes[0].conductor.download(url, piece_size=PIECE, content_length=2 * PIECE)
        nodes[1].conductor.download(url, piece_size=PIECE)
        # Kill node-0's piece server: node-2 must reschedule (to node-1) or
        # fall back to source, still finishing.
        nodes[0].stop()
        r2 = nodes[2].conductor.download(url, piece_size=PIECE)
        assert r2.ok

    def test_probe_sync_over_wire(self, wire_swarm):
        nodes = wire_swarm["nodes"]
        service = wire_swarm["service"]
        # Hosts are announced during registration; probe round via the client.
        url = "https://origin/warm"
        nodes[0].conductor.download(url, piece_size=PIECE, content_length=PIECE)
        nodes[1].conductor.download(url, piece_size=PIECE)
        targets = nodes[0].client.sync_probes_start(nodes[0].host)
        assert targets, "no probe targets returned"
        nodes[0].client.sync_probes_finished(
            nodes[0].host, [(t.id, 1_000_000) for t in targets]
        )
        assert service.networktopology.edge_count() >= 1
        assert (
            service.networktopology.average_rtt(nodes[0].host.id, targets[0].id)
            == 1_000_000
        )

    def test_unknown_method_404(self, wire_swarm):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            wire_swarm["server"].url + "/rpc/nope", data=b"{}", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 404


class TestHashRing:
    def test_stable_assignment(self):
        ring = HashRing(["s1", "s2", "s3"])
        keys = [f"task-{i}" for i in range(200)]
        owners = {k: ring.pick(k) for k in keys}
        assert set(owners.values()) == {"s1", "s2", "s3"}
        # Removing one backend only moves its keys.
        ring.remove("s2")
        moved = sum(
            1 for k in keys if owners[k] != ring.pick(k) and owners[k] != "s2"
        )
        assert moved == 0
        assert all(ring.pick(k) in ("s1", "s3") for k in keys)

    def test_empty_ring(self):
        assert HashRing().pick("x") is None


class TestRetry:
    def test_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("boom")
            return "ok"

        assert retry_call(flaky, attempts=4, sleep=lambda s: None) == "ok"
        assert len(calls) == 3

    def test_exhausted_raises(self):
        def dead():
            raise TimeoutError("always")

        with pytest.raises(TimeoutError):
            retry_call(dead, attempts=2, sleep=lambda s: None)


class TestConcurrentWire:
    def test_concurrent_registrations_no_500(self, wire_swarm):
        """Two daemons registering for the same task concurrently must not
        crash the RPC with an FSM race (service._try_event)."""
        nodes = wire_swarm["nodes"]
        url = "https://origin/contended"
        nodes[0].conductor.download(url, piece_size=PIECE, content_length=2 * PIECE)
        results = {}

        def dl(i):
            results[i] = nodes[i].conductor.download(url, piece_size=PIECE)

        t1 = threading.Thread(target=dl, args=(1,))
        t2 = threading.Thread(target=dl, args=(2,))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert results[1].ok and results[2].ok


class TestServiceBinaries:
    def test_scheduler_process_and_dfdaemon_builds(self, tmp_path):
        """Real deployment shape: the scheduler CLI binary serving RPC in a
        separate OS process; two dfdaemon compositions downloading through
        it — one seeds from a file:// origin, the second gets P2P."""
        import os
        import subprocess
        import sys
        import time

        cfg = tmp_path / "sched.yaml"
        cfg.write_text(
            f"storage:\n  dir: {tmp_path}/records\nserver:\n  host: 127.0.0.1\n  port: 0\n"
        )
        # port 0 → need the bound port; patch: run a tiny launcher that prints it.
        launcher = (
            "import sys\n"
            "from dragonfly2_tpu.cli.scheduler import build\n"
            "from dragonfly2_tpu.config import SchedulerConfigFile, load_config\n"
            "from dragonfly2_tpu.rpc import SchedulerHTTPServer\n"
            "cfg = load_config(SchedulerConfigFile, sys.argv[1])\n"
            "service, storage, runner = build(cfg)\n"
            "srv = SchedulerHTTPServer(service, port=0)\n"
            "srv.serve()\n"
            "print('READY', srv.url, flush=True)\n"
            "import time; time.sleep(60)\n"
        )
        env = {**os.environ, "PYTHONPATH": os.getcwd()}
        proc = subprocess.Popen(
            [sys.executable, "-c", launcher, str(cfg)],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        try:
            line = proc.stdout.readline().strip()
            assert line.startswith("READY"), line
            url = line.split()[1]

            from dragonfly2_tpu.cli.dfdaemon import build as build_daemon
            from dragonfly2_tpu.config import DaemonConfig

            payload = os.urandom(200_000)
            blob = tmp_path / "origin.bin"
            blob.write_bytes(payload)
            src_url = f"file://{blob}"

            nodes = []
            for i in range(2):
                dc = DaemonConfig()
                dc.storage.dir = str(tmp_path / f"dd{i}")
                dc.piece_size = 65536
                # Two daemons on one host: ephemeral piece ports (the
                # piece server binds the CONFIGURED port since r4 — the
                # default 65000 would collide here).
                dc.server.port = 0
                nodes.append(build_daemon(dc, url))
            for n in nodes:
                n["announcer"].announce_once()
            r0 = nodes[0]["conductor"].download(
                src_url, piece_size=65536, content_length=len(payload)
            )
            assert r0.ok and r0.back_to_source
            r1 = nodes[1]["conductor"].download(src_url, piece_size=65536)
            assert r1.ok and not r1.back_to_source
            # Serve accounting lives with whichever server ran: the C++
            # in-engine server (native store) or the Python UploadManager.
            served = nodes[0]["upload"].upload_count + getattr(
                nodes[0]["piece_server"], "upload_count", 0
            )
            assert served == r1.pieces
            got = bytearray()
            rem = len(payload)
            for n in range(r1.pieces):
                piece = nodes[1]["storage"].read_piece(r1.task_id, n)
                got += piece[: min(len(piece), rem)]
                rem -= len(piece)
            assert bytes(got) == payload
            for n in nodes:
                n["piece_server"].stop()
        finally:
            proc.terminate()


class TestTrainerWire:
    def test_announcer_to_remote_trainer(self, tmp_path, cluster):
        """The full scheduler->trainer dataset stream over HTTP: columnar
        shards chunked up, trained server-side, models registered."""
        from dragonfly2_tpu.manager import ModelRegistry
        from dragonfly2_tpu.records.columnar import ColumnarWriter
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
        from dragonfly2_tpu.rpc import RemoteTrainer, TrainerHTTPServer
        from dragonfly2_tpu.scheduler import Announcer
        from dragonfly2_tpu.trainer.service import MLP_MODEL_NAME, TrainerService
        from dragonfly2_tpu.trainer.train import TrainConfig

        registry = ModelRegistry()
        service = TrainerService(
            registry,
            data_dir=str(tmp_path / "staged"),
            train_config=TrainConfig(epochs=3, warmup_steps=5),
        )
        server = TrainerHTTPServer(service)
        server.serve()
        try:
            # Scheduler-side records.
            rec_dir = tmp_path / "records"
            rec_dir.mkdir()
            shard = rec_dir / "download.dfc"
            with ColumnarWriter(str(shard), DOWNLOAD_COLUMNS) as w:
                w.append(cluster.generate_feature_rows(1500, seed=3))

            client = RemoteTrainer(server.url)
            session = client.open_train_stream(
                ip="10.0.0.9", hostname="sched-9", scheduler_id="sched-9"
            )
            session.send_download_shard(str(shard))
            key = session.close_and_train()
            run = client.runs[key]
            assert run.error is None, run.error
            assert run.download_rows == 1500
            assert run.models
            models = registry.list(scheduler_id="sched-9", name=MLP_MODEL_NAME)
            assert len(models) == 1
        finally:
            server.stop()

    def test_chunked_upload_reassembles(self, tmp_path, cluster):
        """A shard larger than one chunk arrives byte-identical."""
        from dragonfly2_tpu.records.columnar import ColumnarReader, ColumnarWriter
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
        from dragonfly2_tpu.rpc import RemoteTrainer, TrainerHTTPServer
        from dragonfly2_tpu.rpc import trainer_transport
        from dragonfly2_tpu.trainer.service import TrainerService

        service = TrainerService(data_dir=str(tmp_path / "staged"))
        server = TrainerHTTPServer(service)
        server.serve()
        try:
            shard = tmp_path / "big.dfc"
            with ColumnarWriter(str(shard), DOWNLOAD_COLUMNS) as w:
                w.append(cluster.generate_feature_rows(4000, seed=4))
            # Force multi-chunk with a tiny chunk size.
            orig = trainer_transport.UPLOAD_CHUNK_BYTES
            trainer_transport.UPLOAD_CHUNK_BYTES = 64 * 1024
            try:
                client = RemoteTrainer(server.url)
                session = client.open_train_stream(
                    ip="1.2.3.4", hostname="s", scheduler_id="s"
                )
                session.send_download_shard(str(shard))
            finally:
                trainer_transport.UPLOAD_CHUNK_BYTES = orig
            # Staged copy is byte-identical.
            import glob, os

            staged = glob.glob(str(tmp_path / "staged" / "*" / "download_big.dfc"))[0]
            assert os.path.getsize(staged) == os.path.getsize(shard)
            assert ColumnarReader(staged).num_rows == 4000
        finally:
            server.stop()


class TestPieceMetadataSync:
    def test_bitmap_endpoint_and_partial_parent(self, wire_swarm):
        """A partial holder's bitmap steers piece workers to the full
        holder instead of burning a failed fetch per missing piece."""
        import urllib.request

        nodes = wire_swarm["nodes"]
        url = "https://origin/partial"
        r0 = nodes[0].conductor.download(url, piece_size=PIECE, content_length=4 * PIECE)
        # node-1 becomes a PARTIAL holder: manually store only piece 0.
        task_id = r0.task_id
        nodes[1].storage.register_task(task_id, piece_size=PIECE, content_length=4 * PIECE)
        nodes[1].storage.write_piece(
            task_id, 0, nodes[0].storage.read_piece(task_id, 0)
        )
        # Bitmap endpoint reflects the holdings.
        bm_url = f"http://127.0.0.1:{nodes[1].piece_server.port}/tasks/{task_id}/pieces"
        with urllib.request.urlopen(bm_url, timeout=5) as resp:
            bm = resp.read()
        assert list(bm) == [1, 0, 0, 0]
        # Unknown host → None (mirror hasn't seen node-1 yet), known → bitmap.
        assert nodes[2].conductor.piece_fetcher.piece_bitmap("node-1", task_id) is None
        got = nodes[1].conductor.piece_fetcher.piece_bitmap("node-1", task_id)
        assert got is None or list(got) == [1, 0, 0, 0]
        nodes[1].client.announce_host(nodes[1].host)
        got = nodes[1].conductor.piece_fetcher.piece_bitmap("node-1", task_id)
        assert list(got) == [1, 0, 0, 0]
        # Register node-1 as a "succeeded" peer so the scheduler offers it;
        # node-2 must still complete cleanly (workers avoid the holes).
        reg = nodes[1].client.register_peer(host=nodes[1].host, url=url)
        for n in range(4):
            nodes[1].client.report_piece_finished(reg.peer, n, length=PIECE, cost_ns=1000)
        nodes[1].client.report_peer_finished(reg.peer)
        r2 = nodes[2].conductor.download(url, piece_size=PIECE)
        assert r2.ok
        for n in range(4):
            assert nodes[2].storage.read_piece(r2.task_id, n) == \
                wire_swarm["origin"].content(url, n)


class TestFullWireLoop:
    def test_four_process_architecture(self, tmp_path, cluster):
        """Every arrow in the architecture is a real wire: manager (REST,
        own process), scheduler (RPC, own process), trainer (HTTP, own
        process, RemoteRegistry to the manager), daemons (this process)
        download P2P -> records -> announcer streams to the trainer ->
        models land in the MANAGER process -> activation over REST ->
        the scheduler-side ML evaluator pulls the artifact."""
        import os
        import subprocess
        import sys

        env = {**os.environ, "PYTHONPATH": os.getcwd()}

        def spawn(code, *argv):
            proc = subprocess.Popen(
                [sys.executable, "-c", code, *argv],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
            )
            procs.append(proc)  # before any assert: finally always reaps it
            import select

            ready, _, _ = select.select([proc.stdout], [], [], 30)
            assert ready, "child did not print READY within 30s"
            line = proc.stdout.readline().strip()
            assert line.startswith("READY"), (line, proc.stderr.read()[:500] if proc.poll() is not None else "")
            return proc, line.split()[1]

        manager_code = (
            "import sys, time\n"
            "from dragonfly2_tpu.manager import ClusterManager, ModelRegistry\n"
            "from dragonfly2_tpu.manager.registry import BlobStore\n"
            "from dragonfly2_tpu.manager.rest import ManagerRESTServer\n"
            "reg = ModelRegistry(BlobStore(sys.argv[1]), db_path=sys.argv[1]+'/m.db')\n"
            "srv = ManagerRESTServer(reg, ClusterManager())\n"
            "srv.serve(); print('READY', srv.url, flush=True); time.sleep(120)\n"
        )
        scheduler_code = (
            "import sys, time\n"
            "from dragonfly2_tpu.records.storage import Storage\n"
            "from dragonfly2_tpu.rpc import SchedulerHTTPServer\n"
            "from dragonfly2_tpu.scheduler import Evaluator, Resource, SchedulerService, Scheduling, SchedulingConfig\n"
            "res = Resource()\n"
            "svc = SchedulerService(res, Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)), Storage(sys.argv[1], buffer_size=1))\n"
            "srv = SchedulerHTTPServer(svc)\n"
            "srv.serve(); print('READY', srv.url, flush=True); time.sleep(120)\n"
        )
        trainer_code = (
            "import sys, time\n"
            "from dragonfly2_tpu.rpc import RemoteRegistry, TrainerHTTPServer\n"
            "from dragonfly2_tpu.trainer.service import TrainerService\n"
            "from dragonfly2_tpu.trainer.train import TrainConfig\n"
            "svc = TrainerService(RemoteRegistry(sys.argv[1]), data_dir=sys.argv[2],\n"
            "    train_config=TrainConfig(epochs=6, learning_rate=3e-3, warmup_steps=10))\n"
            "srv = TrainerHTTPServer(svc)\n"
            "srv.serve(); print('READY', srv.url, flush=True); time.sleep(300)\n"
        )

        procs = []
        try:
            mproc, murl = spawn(manager_code, str(tmp_path / "manager"))
            sproc, surl = spawn(scheduler_code, str(tmp_path / "records"))
            tproc, turl = spawn(trainer_code, murl, str(tmp_path / "staged"))

            # Daemons in this process, wired entirely over TCP.
            origin = WireOrigin()
            nodes = [WireNode(i, surl, tmp_path, origin) for i in range(3)]
            url_a = "https://origin/wire-a"
            nodes[0].conductor.download(url_a, piece_size=PIECE, content_length=4 * PIECE)
            for i in (1, 2):
                for u in range(6):
                    nodes[i].conductor.download(url_a, piece_size=PIECE)

            # Announcer (scheduler side would run this; here driven directly
            # against the scheduler's record files) → remote trainer.
            from dragonfly2_tpu.records.columnar import ColumnarWriter
            from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
            from dragonfly2_tpu.rpc import RemoteRegistry, RemoteTrainer

            shard = tmp_path / "synth.dfc"
            with ColumnarWriter(str(shard), DOWNLOAD_COLUMNS) as w:
                w.append(cluster.generate_feature_rows(2000, seed=11))
            client = RemoteTrainer(turl, timeout=300)
            session = client.open_train_stream(
                ip="10.0.0.1", hostname="sched", scheduler_id="sched-wire"
            )
            session.send_download_shard(str(shard))
            key = session.close_and_train()
            run = client.runs[key]
            assert run.error is None, run.error

            # Models are in the MANAGER process; activate + pull over REST.
            registry = RemoteRegistry(murl)
            models = registry.list(scheduler_id="sched-wire", name="parent-bandwidth-mlp")
            assert len(models) == 1
            registry.activate(models[0].id)

            from dragonfly2_tpu.scheduler import MLEvaluator, ModelSubscriber

            ev = MLEvaluator()
            sub = ModelSubscriber(registry, ev, scheduler_id="sched-wire")
            assert sub.refresh() is True
            assert ev.has_model
            for n in nodes:
                n.stop()
        finally:
            for p in procs:
                p.terminate()


class TestPieceMetadataSubscription:
    """Long-poll bitmap subscription over the HTTP piece plane
    (peertask_piecetask_synchronizer.go analog, VERDICT r2 next-#8)."""

    def test_long_poll_defers_until_piece_lands(self, wire_swarm):
        import threading
        import time

        nodes = wire_swarm["nodes"]
        parent = nodes[0]
        url = "https://origin/longpoll-blob"
        tid_holder = {}
        r = parent.conductor.download(
            url, piece_size=PIECE, content_length=2 * PIECE
        )
        tid_holder["tid"] = r.task_id
        tid = r.task_id
        # Direct resolver: node-1's scheduler mirror only learns node-0
        # through a schedule response, which this test doesn't need.
        fetcher = HTTPPieceFetcher(
            lambda hid: ("127.0.0.1", parent.piece_server.port)
        )

        # have=2 (all pieces held): the poll waits the full window.
        t0 = time.monotonic()
        bm = fetcher.wait_piece_bitmap("node-0", tid, 2, 0.3)
        waited = time.monotonic() - t0
        assert waited >= 0.25, f"returned early: {waited:.2f}s"
        assert bm is not None and sum(bm) == 2

        # have=2 with a THIRD piece landing mid-window: returns promptly.
        parent.storage.register_task(
            tid + "x", piece_size=PIECE, content_length=2 * PIECE
        )

        def commit_late():
            time.sleep(0.1)
            parent.storage.write_piece(tid + "x", 0, b"z" * PIECE)

        threading.Thread(target=commit_late).start()
        t0 = time.monotonic()
        bm = fetcher.wait_piece_bitmap("node-0", tid + "x", 0, 2.0)
        waited = time.monotonic() - t0
        assert bm is not None and sum(bm) == 1
        assert waited < 1.5, f"missed the mid-window commit: {waited:.2f}s"


class TestTracePropagation:
    """VERDICT r2 next-#9: trace-id propagation through the wire — the
    §3.1 call stack is followable end-to-end by one trace id, like the
    reference's otelgrpc handlers allow."""

    def test_download_trace_links_across_http_wire(self, wire_swarm):
        from dragonfly2_tpu.utils.tracing import InMemoryExporter, default_tracer

        old = default_tracer.exporter
        exp = InMemoryExporter()
        default_tracer.exporter = exp
        try:
            nodes = wire_swarm["nodes"]
            url = "https://origin/traced-blob"
            r0 = nodes[0].conductor.download(
                url, piece_size=PIECE, content_length=2 * PIECE
            )
            assert r0.ok
            r1 = nodes[1].conductor.download(url, piece_size=PIECE)
            assert r1.ok and not r1.back_to_source
        finally:
            default_tracer.exporter = old

        downloads = exp.find("daemon/download")
        assert len(downloads) == 2
        handlers = exp.find("rpc/register_peer")
        assert len(handlers) >= 2
        for dl in downloads:
            # The server-side handler spans share the DOWNLOAD's trace id
            # and parent into the client's context — the id traveled in
            # the traceparent header, not process memory.
            linked = [h for h in handlers if h.trace_id == dl.trace_id]
            assert linked, "no server span joined the download trace"
            assert linked[0].parent_id == dl.span_id
            assert linked[0].attributes.get("transport") == "http"
        # Piece reports stayed in-trace too: they ride the report
        # batcher's flush thread now, whose daemon/report.flush span
        # carries the download context onto the batched RPC — the
        # server-side report_pieces_finished handlers join the trace.
        p2p_trace = downloads[1].trace_id
        piece_handlers = [
            h
            for h in (
                exp.find("rpc/report_pieces_finished")
                + exp.find("rpc/report_piece_finished")
            )
            if h.trace_id == p2p_trace
        ]
        assert len(piece_handlers) >= 1
        flushes = [
            s for s in exp.find("daemon/report.flush")
            if s.trace_id == p2p_trace
        ]
        assert flushes and sum(
            s.attributes.get("reports", 0) for s in flushes
        ) == 2
