"""Dynamic compile-witness cross-check (trace discipline, enforced).

``tests/conftest.py`` installs ``dragonfly2_tpu.utils.dftrace`` before any
project import, so every ``jax.jit`` constructed from project code during
this pytest session records (creations, calls, max XLA compiles per
creation) keyed by its creation site.  This module (named ``zz`` so it
collects last and sees the whole session's stats) drives representative
jitted workloads, then asserts:

- every runtime creation site maps into dflint's STATIC jit-site index
  (``tools/dflint/tracerules.py``) — an unknown site is a per-call
  construction or a resolver blind spot: fix tracerules/DF010, never
  this test;
- every per-creation compile count fits the checked-in budget
  (``tools/dflint/compile_budget.toml``) — a steady-state path that
  recompiles per call fails BY FUNCTION NAME;
- the budget's key set matches the static index exactly (staleness, the
  baseline.toml / §16 lock-graph discipline).

The acceptance mutation: un-caching ``streaming.py``'s ``self._step_fn``
into a per-call ``jax.jit(...)(...)`` must fail BOTH the static rule
(DF010) and this witness, by name.
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils import dftrace  # noqa: E402
from tools.dflint.tracerules import (  # noqa: E402
    TraceAnalysis,
    budget_staleness,
    load_budget,
    witness_compile_gaps,
)

# Sites polluted by the deliberate-mutation test below; the clean-session
# assertions subtract them so test selection order can't flake the gate.
_MUTATION_SITES: set = set()


def _witness():
    w = dftrace.witness()
    if w is None:
        pytest.skip("compile witness disabled (DF_COMPILE_WITNESS=0)")
    return w


@pytest.fixture(scope="module")
def analysis():
    from tests.test_dflint import _df_tree_program

    return TraceAnalysis(_df_tree_program(), REPO)


def _drive_streaming_steps(n_steps: int = 3):
    """A StreamingTrainer run: the canonical cached-jit workload (its
    ``__init__`` construction site must be observed, steady-state)."""
    from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
    from dragonfly2_tpu.trainer.streaming import StreamingConfig, StreamingTrainer

    cfg = StreamingConfig(batch_size=16, queue_capacity=8, checkpoint_every=10**9)
    trainer = StreamingTrainer(cfg)
    rng = np.random.default_rng(0)
    rows = rng.standard_normal(
        (cfg.batch_size * (n_steps + 1), len(DOWNLOAD_COLUMNS))
    ).astype(np.float32)
    trainer.feed(rows)
    trainer.end_of_stream()
    return trainer.run(max_steps=n_steps, idle_timeout=0.2)


class TestCompileWitness:
    def test_budget_is_current(self, analysis):
        """Budget keys must mirror the static jit-site index exactly —
        adding or removing a jit construction without regenerating the
        budget fails here (python -m tools.dflint --update-compile-budget)."""
        gaps = budget_staleness(analysis, load_budget())
        assert not gaps, "\n".join(gaps)

    def test_witness_is_installed_and_recording(self):
        w = _witness()
        steps = _drive_streaming_steps()
        assert steps >= 2
        snap = w.snapshot()
        streaming = {
            site: st for site, st in snap.items()
            if site[0] == "dragonfly2_tpu/trainer/streaming.py"
        }
        assert streaming, f"no streaming jit creation observed; saw {sorted(snap)}"
        st = next(iter(streaming.values()))
        assert st["creations"] >= 1 and st["calls"] >= 2
        assert st["max_compiles"] >= 1

    def test_every_runtime_creation_is_known_and_within_budget(self, analysis):
        w = _witness()
        _drive_streaming_steps()
        observed = {
            site: st for site, st in w.snapshot().items()
            if site not in _MUTATION_SITES
        }
        gaps = witness_compile_gaps(analysis, observed, load_budget())
        assert not gaps, (
            "compile-witness gaps (fix tools/dflint/tracerules.py or the "
            "offending construction, not this test):\n  " + "\n  ".join(gaps)
        )

    def test_steady_state_is_compile_free(self):
        """The shared hop-precompute jit must not add compiles on a
        repeat call with identical shapes (the retrace signal the budget
        exists to catch)."""
        import jax.numpy as jnp

        from dragonfly2_tpu.models.gnn import build_neighbor_table
        from dragonfly2_tpu.models.hop import precompute_hop_features_jit

        w = _witness()
        rng = np.random.default_rng(1)
        n = 32
        src = rng.integers(0, n, 64).astype(np.int32)
        dst = (src + 1 + rng.integers(0, n - 1, 64).astype(np.int32)) % n
        table = build_neighbor_table(
            n, src, dst, rng.random(64).astype(np.float32), max_neighbors=4
        )
        nf = jnp.asarray(rng.standard_normal((n, 6)).astype(np.float32))
        precompute_hop_features_jit(nf, table, hops=2)

        def hop_site_compiles():
            return sum(
                st["max_compiles"]
                for site, st in w.snapshot().items()
                if site[0] == "dragonfly2_tpu/models/hop.py"
            )

        warm = hop_site_compiles()
        precompute_hop_features_jit(nf, table, hops=2)
        precompute_hop_features_jit(nf, table, hops=2)
        assert hop_site_compiles() == warm, "steady-state repeat call recompiled"

    def test_overbudget_compile_count_fails_by_name(self, analysis):
        """Mutation sensitivity: a budgeted site reporting more compiles
        than its bound must be flagged by function name."""
        budget = load_budget()
        index = analysis.jit_site_index()
        site, key = next(
            (s, k) for s, k in sorted(index.items())
            if s[0] == "dragonfly2_tpu/trainer/streaming.py"
        )
        assert key in budget, (site, key)
        fake = {site: {"creations": 1, "calls": 50,
                       "max_compiles": budget[key] + 7}}
        gaps = witness_compile_gaps(analysis, fake, budget)
        assert len(gaps) == 1 and key in gaps[0] and "retracing" in gaps[0]

    def test_unknown_creation_site_is_a_gap(self, analysis):
        fake = {("dragonfly2_tpu/daemon/nowhere.py", 7):
                {"creations": 3, "calls": 3, "max_compiles": 3}}
        gaps = witness_compile_gaps(analysis, fake, load_budget())
        assert len(gaps) == 1 and "unknown to the static jit-site index" in gaps[0]

    def test_uncaching_streaming_step_fails_static_and_witness(self, analysis):
        """THE acceptance mutation: turn ``self._step_fn(...)`` into a
        per-call ``jax.jit(self._train_step, ...)(...)`` inside the run
        loop.  The static rule (DF010) must flag it, and actually running
        the mutant under the witness must produce a creation site unknown
        to the static index — both failures name streaming.py."""
        relpath = "dragonfly2_tpu/trainer/streaming.py"
        src_path = REPO / relpath
        source = src_path.read_text(encoding="utf-8")
        needle = "self.params, self.opt_state, loss = self._step_fn("
        assert needle in source
        mutated = source.replace(
            needle,
            "self.params, self.opt_state, loss = "
            "jax.jit(self._train_step, donate_argnums=(0, 1))(",
        )
        assert mutated != source

        # -- static half: DF010 fires on the mutated tree ------------------
        from tests.test_dflint import _df_tree_program_with

        mutant_program = _df_tree_program_with(relpath, mutated)
        mutant_findings = TraceAnalysis(mutant_program, REPO).findings()
        assert any(
            f.rule == "DF010" and f.path == relpath
            and "immediately invoked" in f.message
            for f in mutant_findings
        ), [f.render() for f in mutant_findings]

        # -- dynamic half: the witness sees an unindexed creation ----------
        w = _witness()
        before = set(w.snapshot())
        import types

        code = compile(mutated, str(src_path), "exec")
        mod_name = "dragonfly2_tpu.trainer._streaming_df010_mutant"
        mutant_mod = types.ModuleType(mod_name)
        mutant_mod.__package__ = "dragonfly2_tpu.trainer"
        mutant_mod.__file__ = str(src_path)
        # dataclasses resolves string annotations via sys.modules[__module__].
        sys.modules[mod_name] = mutant_mod
        try:
            exec(code, mutant_mod.__dict__)  # noqa: S102 — controlled mutant of our own module
            trainer = mutant_mod.StreamingTrainer(
                mutant_mod.StreamingConfig(
                    batch_size=8, queue_capacity=4, checkpoint_every=10**9
                )
            )
            from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS

            rows = np.random.default_rng(2).standard_normal(
                (24, len(DOWNLOAD_COLUMNS))
            ).astype(np.float32)
            trainer.feed(rows)
            trainer.end_of_stream()
            assert trainer.run(max_steps=2, idle_timeout=0.2) == 2
        finally:
            sys.modules.pop(mod_name, None)

        delta = {
            site: st for site, st in w.snapshot().items() if site not in before
        }
        _MUTATION_SITES.update(delta)
        gaps = witness_compile_gaps(analysis, delta, load_budget())
        assert any(
            "dragonfly2_tpu/trainer/streaming.py" in g
            and "unknown to the static jit-site index" in g
            for g in gaps
        ), (gaps, sorted(delta))
