"""Manager tests: registry versioning/activation, searcher, dynconfig, cluster."""

import pytest

from dragonfly2_tpu.manager import (
    ClusterManager,
    ClusterScopes,
    Dynconfig,
    DynconfigServer,
    ModelRegistry,
    ModelState,
    SchedulerCluster,
    SchedulerInstance,
    Searcher,
)
from dragonfly2_tpu.manager.registry import BlobStore


class TestRegistry:
    def test_versions_increment_per_scheduler(self):
        reg = ModelRegistry()
        a1 = reg.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"1")
        a2 = reg.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"2")
        b1 = reg.create_model(name="m", type="mlp", scheduler_id="s2", artifact=b"3")
        assert (a1.version, a2.version, b1.version) == (1, 2, 1)
        assert reg.load_artifact(a2) == b"2"

    def test_unknown_type_rejected(self):
        with pytest.raises(ValueError):
            ModelRegistry().create_model(
                name="m", type="transformer", scheduler_id="s", artifact=b""
            )

    def test_activation_is_exclusive_per_name(self):
        reg = ModelRegistry()
        m1 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"")
        m2 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"")
        other = reg.create_model(name="other", type="gnn", scheduler_id="s", artifact=b"")
        reg.activate(m1.id)
        reg.activate(other.id)
        reg.activate(m2.id)
        assert reg.get(m1.id).state is ModelState.INACTIVE
        assert reg.get(m2.id).state is ModelState.ACTIVE
        assert reg.get(other.id).state is ModelState.ACTIVE  # different name untouched
        assert reg.active_model("s", "m").id == m2.id

    def test_blob_store_disk_roundtrip(self, tmp_path):
        bs = BlobStore(str(tmp_path))
        bs.put("k.npz", b"\x00\x01")
        assert bs.get("k.npz") == b"\x00\x01"
        assert bs.exists("k.npz")
        assert not bs.exists("missing")


class TestSearcher:
    def _clusters(self):
        return [
            SchedulerCluster(
                id="c-idc",
                scopes=ClusterScopes(idc="idc-a|idc-b"),
                scheduler_ids=["s1"],
            ),
            SchedulerCluster(
                id="c-cidr",
                scopes=ClusterScopes(cidrs=("10.1.0.0/16",)),
                scheduler_ids=["s2"],
            ),
            SchedulerCluster(id="c-default", is_default=True, scheduler_ids=["s3"]),
            SchedulerCluster(id="c-empty", scheduler_ids=[]),  # no live schedulers
        ]

    def test_cidr_wins_for_matching_ip(self):
        s = Searcher()
        ranked = s.find_scheduler_clusters(self._clusters(), ip="10.1.2.3")
        assert ranked[0].id == "c-cidr"

    def test_idc_condition_ranks_idc_cluster(self):
        s = Searcher()
        ranked = s.find_scheduler_clusters(
            self._clusters(), ip="192.168.0.1", conditions={"idc": "idc-b"}
        )
        assert ranked[0].id == "c-idc"

    def test_empty_clusters_filtered_and_default_last_resort(self):
        s = Searcher()
        ranked = s.find_scheduler_clusters(self._clusters(), ip="192.168.0.1")
        assert "c-empty" not in [c.id for c in ranked]
        assert ranked[0].id == "c-default"

    def test_no_live_clusters_raises(self):
        with pytest.raises(LookupError):
            Searcher().find_scheduler_clusters(
                [SchedulerCluster(id="x", scheduler_ids=[])]
            )

    def test_hostname_regex(self):
        s = Searcher()
        c = SchedulerCluster(
            id="c",
            scopes=ClusterScopes(hostnames=(r"^edge-\d+$",)),
            scheduler_ids=["s"],
        )
        assert s.evaluate(c, hostname="edge-42") > s.evaluate(c, hostname="core-1")


class TestDynconfig:
    def test_observer_notified_on_change(self, tmp_path):
        server = DynconfigServer()
        server.set("scheduler-1", {"filter_parent_limit": 15})
        seen = []
        dc = Dynconfig(
            lambda: server.get("scheduler-1")[0],
            cache_path=str(tmp_path / "cache.json"),
        )
        dc.register(seen.append)
        assert dc.refresh() is True
        server.update("scheduler-1", filter_parent_limit=30)
        assert dc.refresh() is True
        assert dc.refresh() is False  # unchanged
        assert seen[-1]["filter_parent_limit"] == 30

    def test_disk_fallback_on_manager_outage(self, tmp_path):
        cache = str(tmp_path / "cache.json")
        server = DynconfigServer()
        server.set("s", {"x": 1})
        dc = Dynconfig(lambda: server.get("s")[0], cache_path=cache)
        dc.refresh()

        def down():
            raise ConnectionError("manager unreachable")

        dc2 = Dynconfig(down, cache_path=cache)
        assert dc2.get() == {"x": 1}  # served from disk cache

    def test_no_cache_no_manager_raises(self):
        def down():
            raise ConnectionError()

        with pytest.raises(RuntimeError):
            Dynconfig(down).get()


class TestClusterManager:
    def test_keepalive_expiry(self):
        cm = ClusterManager(keepalive_ttl=0.0)
        cm.register_scheduler(SchedulerInstance(id="s1", cluster_id="c"))
        import time

        time.sleep(0.01)
        assert cm.active_schedulers() == []
        cm.keepalive("s1")
        cm.ttl = 60.0
        assert [s.id for s in cm.active_schedulers()] == ["s1"]


class TestRegistryPersistence:
    def test_models_survive_restart(self, tmp_path):
        from dragonfly2_tpu.manager.registry import BlobStore

        db = str(tmp_path / "manager.db")
        blobs = str(tmp_path / "blobs")
        reg = ModelRegistry(BlobStore(blobs), db_path=db)
        m1 = reg.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"v1")
        m2 = reg.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"v2")
        reg.activate(m2.id)
        reg.create_model(name="g", type="gnn", scheduler_id="s1", artifact=b"gg")

        # "Restart": a new registry over the same db + blob dir.
        reg2 = ModelRegistry(BlobStore(blobs), db_path=db)
        models = reg2.list(scheduler_id="s1", name="m")
        assert [m.version for m in models] == [1, 2]
        assert reg2.active_model("s1", "m").version == 2
        assert reg2.load_artifact(models[1]) == b"v2"
        # Versioning continues past the restart.
        m3 = reg2.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"v3")
        assert m3.version == 3
        # Deletion persists.
        reg2.delete(m1.id)
        reg3 = ModelRegistry(BlobStore(blobs), db_path=db)
        assert [m.version for m in reg3.list(scheduler_id="s1", name="m")] == [2, 3]


class TestCrudRest:
    """Applications + scheduler-cluster CRUD over REST (VERDICT r2
    next-#4: manager/crud.py wired, not dead code) and the :config
    endpoint schedulers poll through dynconfig."""

    def _server(self, db_path=None):
        from dragonfly2_tpu.manager.crud import CrudStore
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        crud = CrudStore(db_path)
        server = ManagerRESTServer(ModelRegistry(), ClusterManager(), crud=crud)
        server.serve()
        return server, crud

    def _call(self, base, method, path, body=None):
        import json
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            base + path, data=data,
            headers={"Content-Type": "application/json"}, method=method,
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read() or b"{}")

    def test_application_crud_roundtrip(self, tmp_path):
        server, _ = self._server()
        try:
            app = self._call(server.url, "POST", "/api/v1/applications",
                             {"name": "ml-models", "url": "https://m", "priority": 2})
            assert app["name"] == "ml-models" and app["priority"] == 2
            got = self._call(server.url, "GET", "/api/v1/applications")
            assert [a["name"] for a in got] == ["ml-models"]
            upd = self._call(server.url, "POST",
                             f"/api/v1/applications/{app['id']}:update",
                             {"priority": 5})
            assert upd["priority"] == 5
            self._call(server.url, "POST",
                       f"/api/v1/applications/{app['id']}:delete", {})
            assert self._call(server.url, "GET", "/api/v1/applications") == []
        finally:
            server.stop()

    def test_cluster_config_endpoint_and_persistence(self, tmp_path):
        import urllib.error

        db = str(tmp_path / "crud.db")
        server, crud = self._server(db)
        try:
            # Default cluster seeded at construction.
            cfg = self._call(server.url, "GET", "/api/v1/clusters/default:config")
            assert cfg["scheduler_cluster_config"]["candidate_parent_limit"] == 4
            self._call(server.url, "POST", "/api/v1/clusters/default:update",
                       {"scheduler_cluster_config": {
                           "candidate_parent_limit": 2,
                           "filter_parent_limit": 9}})
            cfg = self._call(server.url, "GET", "/api/v1/clusters/default:config")
            assert cfg["scheduler_cluster_config"] == {
                "candidate_parent_limit": 2, "filter_parent_limit": 9}
            with pytest.raises(urllib.error.HTTPError):
                self._call(server.url, "GET", "/api/v1/clusters/ghost:config")
        finally:
            server.stop()
        # Write-through survives a manager restart.
        server2, _ = self._server(db)
        try:
            cfg = self._call(server2.url, "GET", "/api/v1/clusters/default:config")
            assert cfg["scheduler_cluster_config"]["candidate_parent_limit"] == 2
        finally:
            server2.stop()

    def test_dynconfig_applies_limits_live(self):
        """Observer wiring: an :update on the manager changes a live
        Scheduling's limits at the next refresh (config tier c)."""
        import json
        import urllib.request

        from dragonfly2_tpu.manager.dynconfig import Dynconfig
        from dragonfly2_tpu.scheduler import Evaluator, Scheduling, SchedulingConfig

        server, _ = self._server()
        scheduling = Scheduling(Evaluator(), SchedulingConfig(retry_interval=0))
        try:
            def fetch():
                with urllib.request.urlopen(
                    server.url + "/api/v1/clusters/default:config", timeout=5
                ) as resp:
                    return json.loads(resp.read())

            def apply(data):
                scc = data.get("scheduler_cluster_config") or {}
                for key in ("candidate_parent_limit", "filter_parent_limit"):
                    if key in scc:
                        setattr(scheduling.config, key, int(scc[key]))

            dyn = Dynconfig(fetch, refresh_interval=999.0)
            dyn.register(apply)
            dyn.refresh()
            assert scheduling.config.candidate_parent_limit == 4
            self._call(server.url, "POST", "/api/v1/clusters/default:update",
                       {"scheduler_cluster_config": {
                           "candidate_parent_limit": 1,
                           "filter_parent_limit": 3}})
            dyn.refresh()
            assert scheduling.config.candidate_parent_limit == 1
            assert scheduling.config.filter_parent_limit == 3
        finally:
            server.stop()

    def test_write_path_validation_and_default_resilience(self, tmp_path):
        import urllib.error

        from dragonfly2_tpu.manager.crud import CrudStore

        server, crud = self._server()
        try:
            # Quote-bearing ids (console XSS vector) and non-int limits
            # are rejected at the WRITE path with a 400.
            with pytest.raises(urllib.error.HTTPError) as exc:
                self._call(server.url, "POST", "/api/v1/clusters",
                           {"id": "x');alert(1)//", "name": "evil"})
            assert exc.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as exc:
                self._call(server.url, "POST", "/api/v1/clusters/default:update",
                           {"scheduler_cluster_config": {
                               "candidate_parent_limit": "oops"}})
            assert exc.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as exc:
                self._call(server.url, "POST", "/api/v1/clusters/default:update",
                           {"scheduler_cluster_config": 5})
            assert exc.value.code == 400
        finally:
            server.stop()
        # Clearing is_default must not crash-loop the next boot's
        # ensure_default_cluster (id="default" still satisfies it).
        db = str(tmp_path / "crud2.db")
        store = CrudStore(db)
        store.ensure_default_cluster()
        store.update("cluster", "default", is_default=False)
        again = CrudStore(db)
        rec = again.ensure_default_cluster()
        assert rec.id == "default"


class TestManagerRateLimit:
    def test_rest_429_past_the_bucket(self):
        import urllib.error
        import urllib.request

        from dragonfly2_tpu.manager.rest import ManagerRESTServer
        from dragonfly2_tpu.rpc.ratelimit import TokenBucket

        server = ManagerRESTServer(
            ModelRegistry(), ClusterManager(),
            rate_limit=TokenBucket(qps=0.001, burst=3),
        )
        server.serve()
        try:
            def get(path):
                try:
                    with urllib.request.urlopen(
                        server.url + path, timeout=5
                    ) as r:
                        return r.status
                except urllib.error.HTTPError as exc:
                    return exc.code

            codes = [get("/api/v1/models") for _ in range(6)]
            assert 429 in codes and 200 in codes, codes
            # Liveness is EXEMPT: probes must not 429 under load.
            assert get("/api/v1/healthy") == 200
            from dragonfly2_tpu.rpc.metrics import RATE_LIMITED_TOTAL

            assert RATE_LIMITED_TOTAL.value(transport="manager-rest") >= 1
        finally:
            server.stop()


class TestConfigCrud:
    def test_config_rows_roundtrip(self, tmp_path):
        """handlers/config.go parity: named operator key-value rows with
        sqlite persistence."""
        import json
        import urllib.request

        from dragonfly2_tpu.manager.crud import CrudStore
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        def call(base, method, path, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                base + path, data=data,
                headers={"Content-Type": "application/json"}, method=method,
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                return json.loads(resp.read() or b"{}")

        db = str(tmp_path / "crud.db")
        server = ManagerRESTServer(
            ModelRegistry(), ClusterManager(), crud=CrudStore(db)
        )
        server.serve()
        try:
            row = call(server.url, "POST", "/api/v1/configs",
                       {"name": "gc.interval", "value": "60", "bio": "ops"})
            assert row["name"] == "gc.interval"
            call(server.url, "POST", f"/api/v1/configs/{row['id']}:update",
                 {"value": "120"})
            got = call(server.url, "GET", "/api/v1/configs")
            assert [(c["name"], c["value"]) for c in got] == [("gc.interval", "120")]
        finally:
            server.stop()
        # Durable across restarts.
        server2 = ManagerRESTServer(
            ModelRegistry(), ClusterManager(), crud=CrudStore(db)
        )
        server2.serve()
        try:
            got = call(server2.url, "GET", "/api/v1/configs")
            assert got[0]["value"] == "120"
            call(server2.url, "POST", f"/api/v1/configs/{got[0]['id']}:delete", {})
            assert call(server2.url, "GET", "/api/v1/configs") == []
        finally:
            server2.stop()

    def test_config_name_unique(self):
        from dragonfly2_tpu.manager.crud import CrudStore

        store = CrudStore()
        store.create("config", name="x", value="1")
        with pytest.raises(ValueError):
            store.create("config", name="x", value="2")
        with pytest.raises(ValueError):
            store.create("config", value="no-name")
