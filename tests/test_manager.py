"""Manager tests: registry versioning/activation, searcher, dynconfig, cluster."""

import pytest

from dragonfly2_tpu.manager import (
    ClusterManager,
    ClusterScopes,
    Dynconfig,
    DynconfigServer,
    ModelRegistry,
    ModelState,
    SchedulerCluster,
    SchedulerInstance,
    Searcher,
)
from dragonfly2_tpu.manager.registry import BlobStore


class TestRegistry:
    def test_versions_increment_per_scheduler(self):
        reg = ModelRegistry()
        a1 = reg.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"1")
        a2 = reg.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"2")
        b1 = reg.create_model(name="m", type="mlp", scheduler_id="s2", artifact=b"3")
        assert (a1.version, a2.version, b1.version) == (1, 2, 1)
        assert reg.load_artifact(a2) == b"2"

    def test_unknown_type_rejected(self):
        with pytest.raises(ValueError):
            ModelRegistry().create_model(
                name="m", type="transformer", scheduler_id="s", artifact=b""
            )

    def test_activation_is_exclusive_per_name(self):
        reg = ModelRegistry()
        m1 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"")
        m2 = reg.create_model(name="m", type="mlp", scheduler_id="s", artifact=b"")
        other = reg.create_model(name="other", type="gnn", scheduler_id="s", artifact=b"")
        reg.activate(m1.id)
        reg.activate(other.id)
        reg.activate(m2.id)
        assert reg.get(m1.id).state is ModelState.INACTIVE
        assert reg.get(m2.id).state is ModelState.ACTIVE
        assert reg.get(other.id).state is ModelState.ACTIVE  # different name untouched
        assert reg.active_model("s", "m").id == m2.id

    def test_blob_store_disk_roundtrip(self, tmp_path):
        bs = BlobStore(str(tmp_path))
        bs.put("k.npz", b"\x00\x01")
        assert bs.get("k.npz") == b"\x00\x01"
        assert bs.exists("k.npz")
        assert not bs.exists("missing")


class TestSearcher:
    def _clusters(self):
        return [
            SchedulerCluster(
                id="c-idc",
                scopes=ClusterScopes(idc="idc-a|idc-b"),
                scheduler_ids=["s1"],
            ),
            SchedulerCluster(
                id="c-cidr",
                scopes=ClusterScopes(cidrs=("10.1.0.0/16",)),
                scheduler_ids=["s2"],
            ),
            SchedulerCluster(id="c-default", is_default=True, scheduler_ids=["s3"]),
            SchedulerCluster(id="c-empty", scheduler_ids=[]),  # no live schedulers
        ]

    def test_cidr_wins_for_matching_ip(self):
        s = Searcher()
        ranked = s.find_scheduler_clusters(self._clusters(), ip="10.1.2.3")
        assert ranked[0].id == "c-cidr"

    def test_idc_condition_ranks_idc_cluster(self):
        s = Searcher()
        ranked = s.find_scheduler_clusters(
            self._clusters(), ip="192.168.0.1", conditions={"idc": "idc-b"}
        )
        assert ranked[0].id == "c-idc"

    def test_empty_clusters_filtered_and_default_last_resort(self):
        s = Searcher()
        ranked = s.find_scheduler_clusters(self._clusters(), ip="192.168.0.1")
        assert "c-empty" not in [c.id for c in ranked]
        assert ranked[0].id == "c-default"

    def test_no_live_clusters_raises(self):
        with pytest.raises(LookupError):
            Searcher().find_scheduler_clusters(
                [SchedulerCluster(id="x", scheduler_ids=[])]
            )

    def test_hostname_regex(self):
        s = Searcher()
        c = SchedulerCluster(
            id="c",
            scopes=ClusterScopes(hostnames=(r"^edge-\d+$",)),
            scheduler_ids=["s"],
        )
        assert s.evaluate(c, hostname="edge-42") > s.evaluate(c, hostname="core-1")


class TestDynconfig:
    def test_observer_notified_on_change(self, tmp_path):
        server = DynconfigServer()
        server.set("scheduler-1", {"filter_parent_limit": 15})
        seen = []
        dc = Dynconfig(
            lambda: server.get("scheduler-1")[0],
            cache_path=str(tmp_path / "cache.json"),
        )
        dc.register(seen.append)
        assert dc.refresh() is True
        server.update("scheduler-1", filter_parent_limit=30)
        assert dc.refresh() is True
        assert dc.refresh() is False  # unchanged
        assert seen[-1]["filter_parent_limit"] == 30

    def test_disk_fallback_on_manager_outage(self, tmp_path):
        cache = str(tmp_path / "cache.json")
        server = DynconfigServer()
        server.set("s", {"x": 1})
        dc = Dynconfig(lambda: server.get("s")[0], cache_path=cache)
        dc.refresh()

        def down():
            raise ConnectionError("manager unreachable")

        dc2 = Dynconfig(down, cache_path=cache)
        assert dc2.get() == {"x": 1}  # served from disk cache

    def test_no_cache_no_manager_raises(self):
        def down():
            raise ConnectionError()

        with pytest.raises(RuntimeError):
            Dynconfig(down).get()


class TestClusterManager:
    def test_keepalive_expiry(self):
        cm = ClusterManager(keepalive_ttl=0.0)
        cm.register_scheduler(SchedulerInstance(id="s1", cluster_id="c"))
        import time

        time.sleep(0.01)
        assert cm.active_schedulers() == []
        cm.keepalive("s1")
        cm.ttl = 60.0
        assert [s.id for s in cm.active_schedulers()] == ["s1"]


class TestRegistryPersistence:
    def test_models_survive_restart(self, tmp_path):
        from dragonfly2_tpu.manager.registry import BlobStore

        db = str(tmp_path / "manager.db")
        blobs = str(tmp_path / "blobs")
        reg = ModelRegistry(BlobStore(blobs), db_path=db)
        m1 = reg.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"v1")
        m2 = reg.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"v2")
        reg.activate(m2.id)
        reg.create_model(name="g", type="gnn", scheduler_id="s1", artifact=b"gg")

        # "Restart": a new registry over the same db + blob dir.
        reg2 = ModelRegistry(BlobStore(blobs), db_path=db)
        models = reg2.list(scheduler_id="s1", name="m")
        assert [m.version for m in models] == [1, 2]
        assert reg2.active_model("s1", "m").version == 2
        assert reg2.load_artifact(models[1]) == b"v2"
        # Versioning continues past the restart.
        m3 = reg2.create_model(name="m", type="mlp", scheduler_id="s1", artifact=b"v3")
        assert m3.version == 3
        # Deletion persists.
        reg2.delete(m1.id)
        reg3 = ModelRegistry(BlobStore(blobs), db_path=db)
        assert [m.version for m in reg3.list(scheduler_id="s1", name="m")] == [2, 3]
